"""Decoder-only transformer with tensor- and sequence-parallel execution.

The long-context / model-parallel workload of the framework (the reference
has no sharded execution at all — SURVEY.md §2.9; this is the TPU-native
capability the rebuild adds on top of parity). Sharding design:

- params: attention QKV/out and MLP in/out kernels split over ``tp``
  (head dim / hidden dim respectively) via the PartitionSpec rules in
  ``param_sharding_rules`` — applied by train/steps.py with
  ``shard_params_by_rules``; XLA inserts the all-reduces.
- activations: [batch, seq, model] sharded (dp, sp, tp-on-hidden) — the
  ``sp`` axis is handled exactly by ring attention (parallel/ring_attention).
- bf16 compute, f32 params/softmax.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from tf_operator_tpu import parallel as parallel_compat

from tf_operator_tpu.ops import attention as device_attention
from tf_operator_tpu.parallel.ring_attention import (
    _use_flash_blocks,
    ring_attention,
    ring_flash_attention,
)


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    # Mesh wiring (static): when mesh is set and has an 'sp' axis of size >1,
    # attention runs as ring attention over that axis.
    mesh: Any = None
    seq_axis: str = "sp"
    batch_axis: str = "dp"
    tp_axis: str = "tp"
    # Bound per-device attention-score memory under ring attention: fold kv
    # in chunks of this many keys (None = whole block at once). Exact either
    # way; set for long contexts where a [Tq, Tk] f32 tile won't fit.
    # (Applies to the "stream" impl; the "flash" impl's kernels are blocked
    # in VMEM already.)
    ring_kv_chunk: int | None = None
    # Ring attention implementation: "stream" (autodiff through the ring
    # scan, supports ring_kv_chunk), "flash" (custom-VJP second-ring
    # backward with Pallas block kernels on TPU — no forward tape), or
    # "auto" (flash on TPU with tileable per-device blocks and no
    # ring_kv_chunk request, else stream).
    ring_impl: str = "auto"
    # Rematerialize each block on the backward pass (jax.checkpoint): layer
    # activations are recomputed instead of stored, trading ~1/3 more FLOPs
    # for O(n_layers) less HBM — what makes long-context training fit on a
    # chip (the flash kernel already never materializes O(S^2) scores; remat
    # removes the O(n_layers * S * d_model) residual-stream term).
    remat: bool = False
    # Autoregressive decoding mode: each Attention keeps a KV cache of
    # max_seq_len in a flax "cache" collection. A call may carry t >= 1
    # tokens (multi-token calls are block-causal prompt PREFILL; sampling
    # feeds one token per step); positions come from the cache index.
    # Tensor-parallel decode happens via GSPMD propagation from
    # tp-sharded params (param_sharding_rules); the dense decode path
    # never reads ``mesh``, and the PAGED path reads it only to pin the
    # head-sharded pool placement (_decode_attend_paged — the continuous
    # engine's SPMD step sets it, serve/engine.py). See ``generate`` for
    # the jitted sampling loop.
    decode: bool = False
    # Weight-only int8 decode: projection weights live in HBM as int8 +
    # per-channel scales and are dequantized IN VMEM by the Pallas kernel
    # (ops/int8_dense.py) — halving the per-token weight read that bounds
    # decode throughput. Params must come from ``quantize_decode_params``.
    # Only meaningful with decode=True; activations/KV cache stay bf16.
    int8_decode: bool = False
    # int8 KV cache: keys/values live in HBM as int8 with a per-(token,
    # head) f32 scale, halving the cache read that dominates long-context
    # decode (per step the attention re-reads the WHOLE cache; weights
    # amortize over batch, the cache does not). TPU-first factoring: the
    # scale is constant over the reduced head_dim axis, so it comes OUT
    # of both dots — scores = (q . k_int8) * k_scale and the value read
    # folds v_scale into the tiny [b,h,q,k] probabilities — the MXU
    # consumes the int8 cache via a fused convert, and no dequantized
    # cache tensor ever materializes. Composes with int8_decode.
    kv_int8: bool = False
    # Mixture-of-Experts: every Nth block (1-indexed from the first) swaps
    # its dense MLP for a Switch-routed expert MLP (models/moe.py) sharded
    # over ``ep_axis``. Train with make_lm_train_step(aux_loss_weight=...)
    # so the load-balancing loss is collected.
    moe_every_n: int | None = None
    moe_experts: int = 8
    moe_capacity_factor: float = 1.25
    moe_top_k: int = 1  # 1 = Switch, 2 = GShard top-2
    ep_axis: str = "ep"

    # Block-paged decode KV storage (serving): instead of a contiguous
    # [B, max_seq_len] cache row per sequence, K/V live in ONE per-layer
    # pooled tensor of fixed kv_block-token blocks and each batch lane
    # carries an int32 block table (gather indices into the pool) plus a
    # per-lane position counter. Physical blocks are allocated to actual
    # lengths (serve/kvcache.py BlockAllocator) and can be SHARED across
    # lanes (copy-on-write prefix reuse), which is what turns max-batch
    # from "what fits at max-len" into "what fits at actual lengths".
    # Table capacity is fixed at max_seq_len // kv_block (max_seq_len
    # must divide evenly — the gathered sequence axis must equal the
    # dense path's so the masked softmax reduces over the identical
    # extent, keeping paged decode bit-identical to the dense row path);
    # unused table entries point at block 0, the pinned garbage block.
    # Only the decode path reads these fields.
    kv_paged: bool = False
    kv_block: int = 64
    kv_num_blocks: int = 0
    # Paged decode attend implementation. "gather" (default) gathers
    # pool blocks back to the dense [b, max_seq_len, KV, Dh] layout and
    # reuses the dense einsum — the REFERENCE ORACLE every other path
    # pins against. "pallas" consumes the block table directly in a
    # Pallas kernel (ops/paged_attention.py): per-lane block-list
    # iteration bounded by the lane's counter, so per-step HBM traffic
    # scales with actual lane lengths instead of max_seq_len; pinned
    # bit-identical to the oracle in f32 CPU interpret mode. Requires
    # kv_paged and a geometry inside the kernel's VMEM budget
    # (paged_attend_supported — an unsupported geometry raises at trace
    # time rather than silently falling back).
    kv_attend: str = "gather"

    # Grouped-query attention: K/V get this many heads (must divide
    # n_heads); each group of n_heads/n_kv_heads query heads shares one
    # KV head. None = classic MHA (and the classic fused-qkv param tree,
    # so existing checkpoints are untouched). The decode KV cache — the
    # read that bounds long-context serving — shrinks by the group
    # factor, multiplying with kv_int8's halving; training-side the
    # saving is KV projection params/optimizer state (K/V are repeated
    # to full heads before the attention paths, so ring/flash/ulysses
    # and tp sharding are unchanged).
    n_kv_heads: int | None = None

    def __post_init__(self):
        if self.n_kv_heads is not None and (
            self.n_kv_heads <= 0 or self.n_heads % self.n_kv_heads
        ):
            # At construction, not inside a traced flax forward: a bad
            # value (0 would otherwise surface as ZeroDivisionError deep
            # in a jit trace) fails where the config was written.
            raise ValueError(
                f"n_kv_heads={self.n_kv_heads} must be a positive "
                f"divisor of n_heads={self.n_heads}"
            )
        if self.kv_paged:
            if self.kv_block < 1:
                raise ValueError(f"kv_block={self.kv_block} must be >= 1")
            if self.max_seq_len % self.kv_block:
                raise ValueError(
                    f"max_seq_len={self.max_seq_len} must be a multiple "
                    f"of kv_block={self.kv_block} (block tables address "
                    "whole blocks, and the gathered sequence axis must "
                    "equal the dense path's for bit-identical decode)"
                )
            if self.kv_num_blocks < 2:
                raise ValueError(
                    f"kv_num_blocks={self.kv_num_blocks} must be >= 2 "
                    "(block 0 is the pinned garbage block)"
                )
        if self.kv_attend not in ("gather", "pallas"):
            raise ValueError(
                f"kv_attend={self.kv_attend!r}: expected 'gather' or "
                "'pallas'"
            )
        if self.kv_attend == "pallas" and not self.kv_paged:
            raise ValueError(
                "kv_attend='pallas' requires kv_paged=True (the kernel "
                "consumes the block table; dense rows have no table)"
            )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def use_ring(self) -> bool:
        return self.mesh is not None and self.mesh.shape.get(self.seq_axis, 1) > 1


def _kv8_quant(x):
    """kv_int8's symmetric per-(token, head) quantizer: [.., t, h, dh]
    -> (int8 values, f32 absmax/127 scales over the dh axis). THE one
    copy for the dense rows and the paged pool — the paged<->dense
    bit-exactness contract (and the dense-prefill -> paged-scatter
    join) requires both storage layouts to produce identical int8 +
    scale values, so the formula lives once."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    return jnp.round(xf / s[..., None]).astype(jnp.int8), s


class Int8Dense(nn.Module):
    """Weight-only int8 projection for the decode path: kernel_q (int8) +
    per-output-channel scale, applied by ops/int8_dense.int8_apply (Pallas
    dequant-in-VMEM on TPU). Params are produced by
    ``quantize_decode_params`` from a trained tree; init creates
    zero-filled placeholders only so cache-init works."""

    features: int
    out_shape: tuple = ()
    out_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from tf_operator_tpu.ops.int8_dense import int8_apply

        k, n = x.shape[-1], self.features
        q = self.param(
            "kernel_q", lambda _, s: jnp.zeros(s, jnp.int8), (k, n)
        )
        scale = self.param(
            "scale", lambda _, s: jnp.ones(s, jnp.float32), (n,)
        )
        bias = self.param(
            "bias", lambda _, s: jnp.zeros(s, jnp.float32), (n,)
        )
        y = int8_apply(x, q, scale, out_dtype=jnp.float32) + bias
        y = y.astype(self.out_dtype)
        if self.out_shape:
            y = y.reshape(*y.shape[:-1], *self.out_shape)
        return y


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, t, _ = x.shape
        if cfg.n_kv_heads is not None:
            # GQA: separate projections — K/V carry only kv_heads
            # (validated at TransformerConfig construction).
            if cfg.decode and cfg.int8_decode:
                q = Int8Dense(
                    cfg.n_heads * cfg.head_dim,
                    out_shape=(cfg.n_heads, cfg.head_dim),
                    out_dtype=cfg.dtype, name="q",
                )(x)
                kv = Int8Dense(
                    2 * cfg.kv_heads * cfg.head_dim,
                    out_shape=(2, cfg.kv_heads, cfg.head_dim),
                    out_dtype=cfg.dtype, name="kv",
                )(x)
            else:
                q = nn.DenseGeneral(
                    (cfg.n_heads, cfg.head_dim), axis=-1,
                    dtype=cfg.dtype, name="q",
                )(x)
                kv = nn.DenseGeneral(
                    (2, cfg.kv_heads, cfg.head_dim), axis=-1,
                    dtype=cfg.dtype, name="kv",
                )(x)
            k, v = kv[:, :, 0], kv[:, :, 1]
            if not cfg.decode and cfg.kv_heads < cfg.n_heads:
                # Training/prefill paths: repeat KV heads to full heads so
                # every attention strategy (ring, flash, ulysses, the tp
                # shard_map) sees the MHA layout it was built for — the
                # GQA cache saving is a decode property; here the saving
                # is the smaller KV projection (params + optimizer
                # state). The decode path keeps the grouped layout: its
                # cache stores only kv_heads.
                g = cfg.n_heads // cfg.kv_heads
                k = jnp.repeat(k, g, axis=2)
                v = jnp.repeat(v, g, axis=2)
        else:
            if cfg.decode and cfg.int8_decode:
                qkv = Int8Dense(
                    3 * cfg.n_heads * cfg.head_dim,
                    out_shape=(3, cfg.n_heads, cfg.head_dim),
                    out_dtype=cfg.dtype, name="qkv",
                )(x)
            else:
                qkv = nn.DenseGeneral(
                    (3, cfg.n_heads, cfg.head_dim),
                    axis=-1,
                    dtype=cfg.dtype,
                    name="qkv",
                )(x)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cfg.decode:
            out = (
                self._decode_attend_paged(q, k, v)
                if cfg.kv_paged
                else self._decode_attend(q, k, v)
            )
        elif cfg.use_ring:
            batch_spec = (cfg.batch_axis,) if cfg.mesh.shape.get(cfg.batch_axis, 1) > 1 else (None,)
            # Heads are tp-sharded by the qkv kernel rule; declaring that to
            # shard_map (the ring body is head-independent) avoids an
            # all-gather of Q/K/V heads at the boundary on every layer.
            head_spec = (
                (cfg.tp_axis,)
                if cfg.mesh.shape.get(cfg.tp_axis, 1) > 1
                else (None,)
            )
            if cfg.ring_impl not in ("auto", "stream", "flash", "ulysses"):
                # A typo must not silently run the other implementation.
                raise ValueError(
                    f"ring_impl={cfg.ring_impl!r}: expected 'auto', "
                    f"'stream', 'flash', or 'ulysses'"
                )
            if (cfg.ring_impl in ("flash", "ulysses")
                    and cfg.ring_kv_chunk is not None):
                # These impls' score memory is bounded differently (flash:
                # VMEM blocks; ulysses: full-seq local attention);
                # silently dropping the requested memory bound would OOM
                # exactly the long contexts it exists for.
                raise ValueError(
                    f"ring_impl={cfg.ring_impl!r} ignores ring_kv_chunk; "
                    "use ring_impl='stream' (or 'auto') with ring_kv_chunk"
                )
            sp = cfg.mesh.shape[cfg.seq_axis]
            use_flash_ring = cfg.ring_impl == "flash" or (
                cfg.ring_impl == "auto"
                and cfg.ring_kv_chunk is None
                and _use_flash_blocks(t // sp, t // sp)
            )
            if cfg.ring_impl == "ulysses":
                # All-to-all head/sequence exchange instead of a K/V ring
                # (parallel/ulysses.py): full-sequence attention per head
                # group; requires (heads / tp) % sp == 0.
                from tf_operator_tpu.parallel.ulysses import ulysses_attention

                out = ulysses_attention(
                    q, k, v, cfg.mesh,
                    seq_axis=cfg.seq_axis,
                    batch_spec=batch_spec,
                    head_spec=head_spec,
                    causal=True,
                )
            elif use_flash_ring:
                out = ring_flash_attention(
                    q, k, v, cfg.mesh,
                    seq_axis=cfg.seq_axis,
                    batch_spec=batch_spec,
                    head_spec=head_spec,
                    causal=True,
                )
            else:
                out = ring_attention(
                    q, k, v, cfg.mesh,
                    seq_axis=cfg.seq_axis,
                    batch_spec=batch_spec,
                    head_spec=head_spec,
                    causal=True,
                    kv_chunk=cfg.ring_kv_chunk,
                )
        else:
            # ops.attention dispatches: pallas flash kernel on TPU with
            # tileable shapes, XLA reference path otherwise. The pallas
            # custom-call has no SPMD partitioning rule, so under a mesh
            # with dp/tp > 1 it must sit inside shard_map (batch over dp,
            # heads over tp — both embarrassingly parallel for attention);
            # GSPMD partitions only the surrounding ops.
            mesh = cfg.mesh
            dp = mesh.shape.get(cfg.batch_axis, 1) if mesh is not None else 1
            tp = mesh.shape.get(cfg.tp_axis, 1) if mesh is not None else 1
            # shard_map (unlike GSPMD) hard-requires divisibility; shapes
            # that don't divide keep the old GSPMD-partitionable XLA path.
            bspec = cfg.batch_axis if dp > 1 and b % dp == 0 else None
            hspec = cfg.tp_axis if tp > 1 and cfg.n_heads % tp == 0 else None
            if bspec or hspec:
                spec = jax.sharding.PartitionSpec(bspec, None, hspec, None)
                out = parallel_compat.shard_map(
                    lambda q, k, v: device_attention(q, k, v, causal=True),
                    mesh=mesh,
                    in_specs=(spec, spec, spec),
                    out_specs=spec,
                    check_vma=False,
                )(q, k, v)
            elif dp > 1 or tp > 1:
                # Indivisible under an active mesh: never hand GSPMD the
                # pallas custom-call (it has no partitioning rule).
                out = device_attention(q, k, v, causal=True, use_flash=False)
            else:
                out = device_attention(q, k, v, causal=True)
        if cfg.decode and cfg.int8_decode:
            flat = out.reshape(*out.shape[:-2], -1)
            return Int8Dense(
                cfg.d_model, out_dtype=cfg.dtype, name="out"
            )(flat)
        return nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), dtype=cfg.dtype, name="out"
        )(out)

    def _decode_attend(self, q, k, v):
        """Block attention against the layer's KV cache (t >= 1 tokens).

        The cache is a fixed [B, max_seq_len, KV, Dh] buffer of past keys
        and values (static shapes — the decode loop is jittable/scannable;
        KV = cfg.kv_heads, which is n_heads for classic MHA so existing
        cache layouts are unchanged, and n_kv_heads under GQA — the cache
        and its per-step read shrink by the group factor).
        A multi-token call (prompt PREFILL) writes all t keys/values at the
        cache index and attends causally within the block: query row i sees
        cached positions <= idx + i. Single-token calls are the sampling
        steady state. HARD precondition: at most max_seq_len total tokens
        may be decoded — past that, dynamic_update_slice clamps the write
        index and silently overwrites the last slot (``generate`` enforces
        the budget up front; callers driving apply() directly must too).
        Numerics follow reference_attention (f32 scores/softmax, d^-0.5
        scale) so decode logits match the training forward exactly
        (tests/test_training.py::test_decode_matches_full_forward).
        The attention math runs in GROUPED form throughout — query heads
        reshaped [B,t,KV,G,Dh], scores [B,KV,G,t,S] — which at G=1 is
        exactly the classic layout.
        """
        cfg = self.cfg
        b, t, h, dh = q.shape
        kv = k.shape[2]  # cfg.kv_heads
        g = h // kv
        kv8 = cfg.kv_int8
        cached_k = self.variable(
            "cache", "cached_key",
            jnp.zeros, (b, cfg.max_seq_len, kv, dh),
            jnp.int8 if kv8 else cfg.dtype,
        )
        cached_v = self.variable(
            "cache", "cached_value",
            jnp.zeros, (b, cfg.max_seq_len, kv, dh),
            jnp.int8 if kv8 else cfg.dtype,
        )
        if kv8:
            # cfg.kv_int8: K/V live as int8 with a per-(token, head) f32
            # symmetric scale — the cache read that bounds long-context
            # decode drops to ~half (1 byte/elem + 1/Dh sidecar). Each
            # scale is constant along the reduced Dh axis, so it factors
            # OUT of both attention dots below: the score matmul consumes
            # the raw int8 keys (exact in bf16 — |q_i| <= 127 needs 7
            # mantissa bits) rescaled on the [B,H,t,S] score tensor, and
            # the value scale folds into the softmax probabilities. XLA
            # fuses the int8->bf16 convert into the dot operand stream,
            # so HBM sees only int8. Numeric contract (greedy-token
            # agreement with the bf16 cache) pinned by
            # tests/test_training.py::TestKvInt8Decode.
            k_scale = self.variable(
                "cache", "key_scale",
                jnp.zeros, (b, cfg.max_seq_len, kv), jnp.float32,
            )
            v_scale = self.variable(
                "cache", "value_scale",
                jnp.zeros, (b, cfg.max_seq_len, kv), jnp.float32,
            )
        index = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        if self.is_initializing():
            # init() executes this forward once to build the variables; the
            # cache must come out untouched (index 0, zero buffers) and
            # only the OUTPUT SHAPE matters (downstream inits depend on
            # shapes, not values) — q-shaped, since GQA's v carries fewer
            # heads than the attention output.
            return jnp.zeros_like(q)
        idx = index.value
        if kv8:
            k, ks = _kv8_quant(k)
            v, vs = _kv8_quant(v)
            k_scale.value = jax.lax.dynamic_update_slice(
                k_scale.value, ks, (0, idx, 0)
            )
            v_scale.value = jax.lax.dynamic_update_slice(
                v_scale.value, vs, (0, idx, 0)
            )
        else:
            k, v = k.astype(cfg.dtype), v.astype(cfg.dtype)
        cached_k.value = jax.lax.dynamic_update_slice(
            cached_k.value, k, (0, idx, 0, 0)
        )
        cached_v.value = jax.lax.dynamic_update_slice(
            cached_v.value, v, (0, idx, 0, 0)
        )
        index.value = idx + t
        keys = (
            cached_k.value.astype(jnp.bfloat16) if kv8 else cached_k.value
        )
        # Grouped layout: [b, t, kv, g, dh] query heads against the
        # [b, S, kv, dh] cache. At g=1 (MHA) this is the classic einsum.
        qg = q.reshape(b, t, kv, g, dh)
        s = jnp.einsum(
            "bqkgd,bskd->bkgqs", qg, keys,
            preferred_element_type=jnp.float32,
        )
        if kv8:
            # scores[b,k,g,i,j] = (q . k8)[...] * ks[b,j,k].
            s = s * k_scale.value.transpose(0, 2, 1)[:, :, None, None, :]
        s = s * (dh ** -0.5)
        # Query row i (absolute position idx + i) sees keys <= idx + i.
        valid = (
            jnp.arange(cfg.max_seq_len)[None, :]
            <= (idx + jnp.arange(t))[:, None]
        )
        s = jnp.where(valid[None, None, None, :, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        if kv8:
            # Fold the value scale into the probabilities (same factoring).
            p = p * v_scale.value.transpose(0, 2, 1)[:, :, None, None, :]
        out = jnp.einsum(
            "bkgqs,bskd->bqkgd", p, cached_v.value.astype(jnp.float32)
        )
        return out.reshape(b, t, h, dh).astype(cfg.dtype)

    def _decode_attend_paged(self, q, k, v):
        """Block-paged decode attention: K/V live in ONE shared per-layer
        pool of [kv_num_blocks, kv_block, KV, Dh] and each batch lane
        addresses its own sequence through a [table_len] int32 block
        table. Versus ``_decode_attend``:

        - counters are PER-LANE vectors ([b] int32), so lanes sit at
          independent positions inside one batched call (the continuous
          engine's step is a single batched forward, not a vmap — the
          pool is shared state a vmap lane could not mutate);
        - the write scatters each lane's token K/V to flat pool row
          ``table[pos // block] * block + pos % block``. Lanes at
          index 0 are INACTIVE (a live lane always sits at >= its >= 1
          prompt tokens), and their writes are DROPPED via an
          out-of-range sentinel so a retired lane's stale table can
          never corrupt a block that was reallocated to another lane;
        - the read gathers ``pool[table]`` back into the exact
          [b, max_seq_len, KV, Dh] layout the dense path slices, then
          runs the IDENTICAL grouped einsum/mask/softmax — same sequence
          extent, same per-row contractions, which is the whole
          bit-exactness argument (pinned f32-CPU by
          tests/test_serve_engine.py against the dense slot path).

        Blocks beyond a lane's allocation point at block 0 (pinned
        garbage); their gathered values are finite and always masked, so
        they can never influence an active lane. Copy-on-write for
        shared prefix blocks is the ENGINE's job (serve/engine.py runs
        pending copies before the step that would write), so by the time
        this executes every writable block is exclusively owned.

        TENSOR PARALLELISM: when ``cfg.mesh`` carries a ``tp`` axis that
        tiles the KV heads, the pool lives head-sharded
        (P(None, None, 'tp', None) — serve/sharding.py placed it at
        allocation) and this attend pins the gathered K/V and the score
        tensor to the same head split, so the scatter-write, gather,
        einsum, mask, and softmax all run shard-local per KV-head group
        with ZERO collectives inside the attend (the only per-layer
        collective is the out-projection's all-reduce, exactly as in tp
        training) and no per-step host sync. A ``dp`` (batch_axis) mesh
        axis composes on top (the pod-scale tp×dp engine): the lane
        axis of the gathered tensors joins the dp shard when lanes
        tile, matching the slot-sharded tables and the extent-bounded
        pool slices, so the whole attend stays shard-local on BOTH
        axes. Without a mesh the constraints vanish and the math is
        byte-for-byte the single-chip path.
        """
        cfg = self.cfg
        b, t, h, dh = q.shape
        kv = k.shape[2]
        g = h // kv
        kv8 = cfg.kv_int8
        nb, blk = cfg.kv_num_blocks, cfg.kv_block
        table_len = cfg.max_seq_len // blk
        pool_k = self.variable(
            "cache", "pool_key", jnp.zeros, (nb, blk, kv, dh),
            jnp.int8 if kv8 else cfg.dtype,
        )
        pool_v = self.variable(
            "cache", "pool_value", jnp.zeros, (nb, blk, kv, dh),
            jnp.int8 if kv8 else cfg.dtype,
        )
        if kv8:
            # cfg.kv_int8 in the POOLED layout: the per-(token, head) f32
            # scales live as per-block sidecar pools [nb, blk, KV] riding
            # the same block tables — scatter, gather, copy-on-write, and
            # sharding all address them through the identical
            # table[pos // B] * B + pos % B row math as the int8 K/V
            # blocks (serve/kvcache.py POOL_KEYS). The attention math
            # below is EXACTLY the dense kv8 factoring (_decode_attend):
            # scores consume raw int8 keys rescaled on the score tensor,
            # the value scale folds into the probabilities — so paged-kv8
            # decode is bit-identical to dense-kv8 (pinned by
            # tests/test_kvcache_paged.py).
            pool_ks = self.variable(
                "cache", "pool_key_scale",
                jnp.zeros, (nb, blk, kv), jnp.float32,
            )
            pool_vs = self.variable(
                "cache", "pool_value_scale",
                jnp.zeros, (nb, blk, kv), jnp.float32,
            )
        table = self.variable(
            "cache", "block_table", jnp.zeros, (b, table_len), jnp.int32
        )
        index = self.variable(
            "cache", "cache_index", jnp.zeros, (b,), jnp.int32
        )
        if self.is_initializing():
            return jnp.zeros_like(q)
        idx = index.value  # [b]
        if kv8:
            # The shared quantizer: identical int8 values + scales land
            # in the pool as land in the dense rows.
            k, ks = _kv8_quant(k)
            v, vs = _kv8_quant(v)
        else:
            k, v = k.astype(cfg.dtype), v.astype(cfg.dtype)
        pos = idx[:, None] + jnp.arange(t)[None, :]  # [b, t] absolute
        entry = jnp.clip(pos // blk, 0, table_len - 1)
        blocks = jnp.take_along_axis(table.value, entry, axis=1)
        flat = blocks * blk + pos % blk
        # idx == 0 marks an inactive lane (mask_inactive_indices zeroed
        # it): route its write out of bounds and drop it.
        flat = jnp.where((idx > 0)[:, None], flat, nb * blk)
        shape2 = (nb * blk, kv, dh)
        pool_k.value = pool_k.value.reshape(shape2).at[flat].set(
            k, mode="drop"
        ).reshape(nb, blk, kv, dh)
        pool_v.value = pool_v.value.reshape(shape2).at[flat].set(
            v, mode="drop"
        ).reshape(nb, blk, kv, dh)
        if kv8:
            shape2s = (nb * blk, kv)
            pool_ks.value = pool_ks.value.reshape(shape2s).at[flat].set(
                ks, mode="drop"
            ).reshape(nb, blk, kv)
            pool_vs.value = pool_vs.value.reshape(shape2s).at[flat].set(
                vs, mode="drop"
            ).reshape(nb, blk, kv)
        index.value = idx + t
        if cfg.kv_attend == "pallas":
            # The Pallas kernel walks each lane's block list directly
            # (ops/paged_attention.py): no [b, max_seq_len] gather ever
            # materializes, per-step HBM traffic is bounded by actual
            # lane lengths, and the kernel is pinned bit-identical to
            # the gather path below (tests/test_paged_attention.py).
            # The scatter-write above is SHARED — only the read side
            # dispatches, so the cache leaf set (and its tp sharding,
            # serve/sharding.py) is identical across both attends.
            from tf_operator_tpu.ops.paged_attention import paged_attend

            out = paged_attend(
                q, pool_k.value, pool_v.value, table.value, idx,
                k_scale_pool=pool_ks.value if kv8 else None,
                v_scale_pool=pool_vs.value if kv8 else None,
                mesh=cfg.mesh, tp_axis=cfg.tp_axis,
                dp_axis=cfg.batch_axis,
            )
            return out.astype(cfg.dtype)
        keys = pool_k.value[table.value].reshape(
            b, cfg.max_seq_len, kv, dh
        )
        vals = pool_v.value[table.value].reshape(
            b, cfg.max_seq_len, kv, dh
        )
        if kv8:
            # Same cast the dense path applies to its int8 cache before
            # the score dot (exact in bf16: |k8| <= 127).
            keys = keys.astype(jnp.bfloat16)
            k_scales = pool_ks.value[table.value].reshape(
                b, cfg.max_seq_len, kv
            )
            v_scales = pool_vs.value[table.value].reshape(
                b, cfg.max_seq_len, kv
            )
        tp = (
            cfg.mesh.shape.get(cfg.tp_axis, 1)
            if cfg.mesh is not None else 1
        )
        dp = (
            cfg.mesh.shape.get(cfg.batch_axis, 1)
            if cfg.mesh is not None else 1
        )
        # Pod-scale tp×dp engines (serve/engine.py) shard the lane
        # (slot) axis over dp, and the extent-bounded allocator keeps
        # each lane's table inside its own shard's pool slice — so the
        # gathered [b, S, ...] tensors carry a dp component on dim 0
        # when lanes tile, keeping the gather AND the softmax
        # shard-local on both mesh axes. dp=1 (or non-tiling b) leaves
        # the tp-only specs byte-for-byte.
        bdim = cfg.batch_axis if (dp > 1 and b % dp == 0) else None
        if tp > 1 and kv % tp == 0:
            # Head-sharded placement pinned end to end: the gather stays
            # on each chip's KV/tp heads of the pool and the masked
            # softmax reduces shard-locally (its axis is the unsharded
            # sequence), so GSPMD cannot be nudged into all-gathering
            # the pool per step.
            def _pin(x, spec):
                return jax.lax.with_sharding_constraint(
                    x, jax.sharding.NamedSharding(cfg.mesh, spec)
                )

            hspec = jax.sharding.PartitionSpec(
                bdim, None, cfg.tp_axis, None
            )
            keys = _pin(keys, hspec)
            vals = _pin(vals, hspec)
            if kv8:
                # The gathered scale rows ride their head shard.
                sspec = jax.sharding.PartitionSpec(
                    bdim, None, cfg.tp_axis
                )
                k_scales = _pin(k_scales, sspec)
                v_scales = _pin(v_scales, sspec)
        qg = q.reshape(b, t, kv, g, dh)
        s = jnp.einsum(
            "bqkgd,bskd->bkgqs", qg, keys,
            preferred_element_type=jnp.float32,
        )
        if kv8:
            # scores[b,k,g,i,j] = (q . k8)[...] * ks[b,j,k] — the dense
            # kv8 factoring, scale applied in the same order so the
            # paged scores are bitwise the dense scores.
            s = s * k_scales.transpose(0, 2, 1)[:, :, None, None, :]
        if tp > 1 and kv % tp == 0:
            s = _pin(s, jax.sharding.PartitionSpec(
                bdim, cfg.tp_axis, None, None, None
            ))
        s = s * (dh ** -0.5)
        # Lane i's query row j (absolute pos[i, j]) sees keys <= pos[i, j].
        valid = (
            jnp.arange(cfg.max_seq_len)[None, None, :] <= pos[:, :, None]
        )  # [b, t, S]
        s = jnp.where(valid[:, None, None, :, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        if kv8:
            # Fold the value scale into the probabilities (same factoring).
            p = p * v_scales.transpose(0, 2, 1)[:, :, None, None, :]
        out = jnp.einsum("bkgqs,bskd->bqkgd", p, vals.astype(jnp.float32))
        return out.reshape(b, t, h, dh).astype(cfg.dtype)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        if cfg.decode and cfg.int8_decode:
            h = Int8Dense(cfg.d_ff, out_dtype=cfg.dtype, name="in_proj")(x)
            h = nn.gelu(h)
            return Int8Dense(
                cfg.d_model, out_dtype=cfg.dtype, name="out_proj"
            )(h)
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, name="in_proj")(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.d_model, dtype=cfg.dtype, name="out_proj")(h)


class Block(nn.Module):
    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = x + Attention(cfg, name="attn")(nn.RMSNorm(dtype=cfg.dtype)(x))
        if self.use_moe:
            from tf_operator_tpu.models.moe import MoeConfig, MoeMlp

            mcfg = MoeConfig(
                n_experts=cfg.moe_experts, d_model=cfg.d_model, d_ff=cfg.d_ff,
                capacity_factor=cfg.moe_capacity_factor,
                router_top_k=cfg.moe_top_k, dtype=cfg.dtype,
                ep_axis=cfg.ep_axis, data_axis=cfg.batch_axis, mesh=cfg.mesh,
            )
            x = x + MoeMlp(mcfg, name="moe")(nn.RMSNorm(dtype=cfg.dtype)(x))
        else:
            x = x + MLP(cfg, name="mlp")(nn.RMSNorm(dtype=cfg.dtype)(x))
        return x


class Transformer(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, return_hidden: bool = False):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype, name="embed")(tokens)
        if cfg.decode:
            # One position counter for the model; every layer's
            # cache_index advances in lockstep with it (each __call__
            # touches all layers exactly once) — the same per-layer-counter
            # convention as flax's canonical decode cache. Under kv_paged
            # the counter is PER-LANE ([b] int32): each lane of the
            # batched paged step sits at its own position.
            if cfg.kv_paged:
                pidx = self.variable(
                    "cache", "pos_index",
                    jnp.zeros, (tokens.shape[0],), jnp.int32,
                )
                positions = (
                    pidx.value[:, None]
                    + jnp.arange(tokens.shape[1])[None, :]
                )
            else:
                pidx = self.variable(
                    "cache", "pos_index", lambda: jnp.zeros((), jnp.int32)
                )
                positions = (
                    pidx.value + jnp.arange(tokens.shape[1])
                )[None, :]
            if not self.is_initializing():
                pidx.value = pidx.value + tokens.shape[1]
        else:
            positions = jnp.arange(tokens.shape[1])[None, :]
        pos = nn.Embed(cfg.max_seq_len, cfg.d_model, dtype=cfg.dtype, name="pos")(
            positions
        )
        x = x + pos
        block_cls = nn.remat(Block) if (cfg.remat and not cfg.decode) else Block
        for i in range(cfg.n_layers):
            use_moe = bool(cfg.moe_every_n) and (i + 1) % cfg.moe_every_n == 0
            x = block_cls(cfg, use_moe=use_moe, name=f"block_{i}")(x)
        x = nn.RMSNorm(dtype=cfg.dtype)(x)
        if cfg.decode and cfg.int8_decode:
            head: Any = Int8Dense(
                cfg.vocab_size, out_dtype=jnp.float32, name="lm_head"
            )
        else:
            head = nn.Dense(cfg.vocab_size, dtype=jnp.float32, name="lm_head")
        if return_hidden:
            # Callers computing a fused/chunked loss read lm_head params
            # directly (train/steps.py chunked_lm_xent); touching the module
            # here keeps init creating them on this path too.
            if self.is_initializing():
                head(x[:, :1].astype(jnp.float32))
            return x
        return head(x.astype(jnp.float32))


def _nucleus_filter(logits: jax.Array, top_p: float) -> jax.Array:
    """Mask logits outside the nucleus: keep the smallest set of tokens
    whose probability mass reaches ``top_p`` (always including the top
    token), set the rest to -inf so categorical renormalizes over the
    nucleus. The mask is by sorted RANK, not probability value, so exact
    ties at the cutoff cannot leak tail tokens into the nucleus. Static
    shapes (sort + cumsum + inverse permutation), jit/scan-friendly."""
    sort_idx = jnp.flip(jnp.argsort(logits, axis=-1), axis=-1)
    sorted_probs = jax.nn.softmax(
        jnp.take_along_axis(logits, sort_idx, axis=-1), axis=-1
    )
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # Keep a token iff the mass BEFORE it is still short of top_p: the
    # crossing token stays, everything after drops.
    keep_sorted = (cum - sorted_probs) < top_p
    inv = jnp.argsort(sort_idx, axis=-1)
    keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
    return jnp.where(keep, logits, -1e30)


def generate(
    cfg: TransformerConfig,
    params: Any,
    prompt: jax.Array,
    num_steps: int,
    *,
    temperature: float = 0.0,
    top_p: float | None = None,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Jitted autoregressive generation with a KV cache.

    The whole loop — one batched prompt-prefill forward, then
    ``num_steps`` of sample-and-feed via lax.scan — runs inside one jit:
    static shapes, one compilation, no host round-trips per token (the
    TPU-native decode shape; a Python token loop would be
    dispatch-bound). ``temperature=0`` is greedy; otherwise categorical
    sampling with ``rng``, optionally nucleus-filtered: ``top_p`` keeps
    the smallest set of tokens whose (tempered) probability mass reaches
    top_p and renormalizes over it. Returns [B, num_steps]
    generated tokens. The ring/remat training config is dropped for
    decoding; TENSOR-PARALLEL decode works by passing tp-sharded params
    (GSPMD propagates the shardings — see _generate_fn).

    The inference-path capability the reference delegates to user
    containers entirely (its operator never runs models); here it
    completes the LM family alongside the training step.
    """
    if prompt.shape[1] + num_steps > cfg.max_seq_len:
        raise ValueError(
            f"prompt {prompt.shape[1]} + steps {num_steps} exceeds "
            f"max_seq_len {cfg.max_seq_len}"
        )
    if temperature > 0 and rng is None:
        raise ValueError("temperature > 0 needs an rng key")
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p={top_p} must be in (0, 1]")
    if top_p is not None and temperature <= 0:
        raise ValueError("top_p requires temperature > 0 (greedy ignores it)")
    rng = jax.random.PRNGKey(0) if rng is None else rng
    fn = _generate_fn(cfg, num_steps, float(temperature),
                      None if top_p is None else float(top_p))
    return fn(params, prompt, rng)


@functools.lru_cache(maxsize=32)
def _generate_fn(cfg: TransformerConfig, num_steps: int, temperature: float,
                 top_p: float | None = None):
    """Build (and cache) the jitted decode loop for one (config, steps,
    temperature, top_p) tuple. params/prompt/rng are jit ARGUMENTS, so repeated
    generate() calls — including with updated params — reuse the same
    executable instead of re-tracing a fresh closure each time.

    Tensor-parallel decoding needs no mesh plumbing here: the decode path
    is plain GSPMD-partitionable einsums and never reads cfg.mesh, so
    calling with tp-sharded params (the training shardings from
    param_sharding_rules) is sufficient — the KV cache shards over heads
    by propagation, dp shards the batch
    (tests/test_training.py::test_tensor_parallel_decode_...)."""
    from dataclasses import replace

    dcfg = replace(cfg, decode=True, mesh=None, remat=False)
    model = Transformer(dcfg)

    def token_step(params, cache, tok):
        logits, updates = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            mutable=["cache"],
        )
        return updates["cache"], logits[:, 0]

    def run(params, prompt, rng):
        # Prompt PREFILL in ONE forward pass (block-causal attention over
        # the cache): a token-by-token prefill scan would pay the full
        # per-step weight read prompt_len times — at bench shapes that was
        # half the decode wall time for work a single batched pass does.
        cache, last_logits = _prefill(model, params, prompt)

        def sample(carry, step_rng):
            cache, logits = carry
            if temperature > 0:
                scaled = logits / temperature
                if top_p is not None:
                    scaled = _nucleus_filter(scaled, top_p)
                tok = jax.random.categorical(step_rng, scaled)
            else:
                tok = logits.argmax(-1)
            cache, logits = token_step(params, cache, tok.astype(prompt.dtype))
            return (cache, logits), tok

        (_, _), toks = jax.lax.scan(
            sample, (cache, last_logits), jax.random.split(rng, num_steps)
        )
        return toks.swapaxes(0, 1)

    return jax.jit(run)


def generate_segments(
    cfg: TransformerConfig,
    params: Any,
    prompt: jax.Array,
    num_steps: int,
    *,
    segment: int = 16,
    prefill_chunk: int | None = None,
):
    """Greedy generation in fixed-size SEGMENTS, as a generator yielding
    each segment's [B, <=segment] tokens: one prefill executable per
    prompt shape plus ONE segment executable reused for every segment of
    every request length — where ``generate`` compiles a fresh loop per
    ``num_steps``, this path serves any length from the same two
    executables (the serving win), and consumers stream tokens as each
    segment lands. ``prefill_chunk`` additionally runs the prefill
    through fixed-size chunks (prefill_chunked), removing the
    per-prompt-shape compile too — the full serving-compile trifecta:
    any (prompt_len, num_steps) pair runs on three fixed executables.

    Decode/consume OVERLAP is real: segment i+1 is dispatched (async —
    jax returns futures) BEFORE segment i is yielded, so the consumer's
    readback and I/O run while the device decodes ahead. The device
    work happens inside ``next()``; a server can therefore serialize
    device access by holding its lock around next() only, never around
    its socket writes.

    Output is bit-identical to ``generate(..., temperature=0)``: both
    run the same argmax-feed recurrence over the same decode cache; the
    segmentation only changes where the scan boundaries fall. The last
    partial segment still decodes ``segment`` tokens on device (static
    shapes) and trims host-side, so the cache must budget the overshoot:
    prompt + ceil(num_steps/segment)*segment <= cfg.max_seq_len.
    """
    if segment < 1:
        raise ValueError(f"segment={segment} must be >= 1")
    if num_steps < 1:
        raise ValueError(f"num_steps={num_steps} must be >= 1")
    n_segments = -(-num_steps // segment)
    if prompt.shape[1] + n_segments * segment > cfg.max_seq_len:
        raise ValueError(
            f"prompt {prompt.shape[1]} + {n_segments} segments of "
            f"{segment} exceeds max_seq_len {cfg.max_seq_len} (the last "
            "partial segment decodes a full segment on device)"
        )
    if prefill_chunk is not None:
        # prefill_chunked re-validates, but ITS checks would fire inside
        # the lazy gen() body — after a streaming server has committed
        # its 200/NDJSON headers. Eager here keeps the documented
        # every-validation-error-is-a-400 contract.
        _validate_prefill_chunk(cfg, prompt.shape[1], prefill_chunk)

    def trim(toks, i):
        if (i + 1) * segment > num_steps:  # overshoot of the last segment
            return toks[:, : num_steps - i * segment]
        return toks

    def gen():
        prefill_fn, segment_fn = _segment_fns(cfg, int(segment))
        if prefill_chunk is not None:
            cache, logits = prefill_chunked(
                cfg, params, prompt, chunk=prefill_chunk
            )
        else:
            cache, logits = prefill_fn(params, prompt)
        cache, logits, pending = segment_fn(params, cache, logits)
        for i in range(1, n_segments):
            # dispatch ahead of the yield: the consumer reads segment
            # i-1 while the device runs segment i
            cache, logits, nxt = segment_fn(params, cache, logits)
            yield trim(pending, i - 1)
            pending = nxt
        yield trim(pending, n_segments - 1)

    return gen()


def generate_segmented(
    cfg: TransformerConfig,
    params: Any,
    prompt: jax.Array,
    num_steps: int,
    *,
    segment: int = 16,
    prefill_chunk: int | None = None,
    on_segment=None,
) -> jax.Array:
    """Collected form of ``generate_segments``: returns the full
    [B, num_steps] tokens, invoking ``on_segment(tokens)`` per segment
    as it lands (see the generator for the streaming/locking and
    exactness contracts)."""
    chunks = []
    for toks in generate_segments(
        cfg, params, prompt, num_steps, segment=segment,
        prefill_chunk=prefill_chunk,
    ):
        chunks.append(toks)
        if on_segment is not None:
            on_segment(toks)
    return jnp.concatenate(chunks, axis=1)


def set_cache_index(cache: Any, value) -> Any:
    """Return ``cache`` with every position counter set to ``value`` (an
    int32 scalar or tracer): the per-layer ``cache_index`` AND the
    top-level ``pos_index`` that drives positional embeddings — the two
    MUST move in lockstep, or re-fed tokens keep advancing position
    embeddings while overwriting earlier cache slots (K/V written with
    the wrong position). K/V buffers are untouched: decode attention
    masks positions >= index, so rewriting the counters IS the
    rollback. Used by speculative decoding (undo rejected proposals)
    and chunked prefill (discard right-padding)."""
    from collections.abc import Mapping

    def walk(node):
        if isinstance(node, Mapping):
            # rebuilt as plain dicts — model.apply accepts them, and it
            # normalizes away FrozenDict vs dict across flax versions.
            return {
                k: (jnp.asarray(value, jnp.int32)
                    if k in ("cache_index", "pos_index")
                    else walk(v))
                for k, v in node.items()
            }
        return node

    return walk(cache)


def _head_logits(params: Any, h: jax.Array) -> jax.Array:
    """lm_head projection of one hidden row [B, d] -> f32 [B, vocab],
    dispatching on the param-tree layout (plain dense vs the int8 tree
    quantize_decode_params writes). THE single head dispatch for every
    decode entry point so quantization/layout changes cannot diverge
    them. Plain traced code."""
    head = params["lm_head"]
    if "kernel_q" in head:  # int8_decode tree (quantize_decode_params)
        from tf_operator_tpu.ops.int8_dense import int8_apply

        return int8_apply(
            h, head["kernel_q"], head["scale"], out_dtype=jnp.float32,
        ) + head["bias"]
    return h.astype(jnp.float32) @ head["kernel"] + head["bias"]


def _prefill(model: "Transformer", params: Any, prompt: jax.Array):
    """Prompt prefill in ONE block-causal forward -> (cache, logits of
    the last position). THE shared construction for every decode
    entry point (_generate_fn, _segment_fns) — including the
    int8_decode head dispatch — so their outputs cannot drift. Plain
    traced code: call from inside any jitted context."""
    cache = model.init(jax.random.PRNGKey(0), prompt[:, :1])["cache"]
    # return_hidden skips the f32 [B, P, vocab] logits over the whole
    # prompt; only the LAST position feeds sampling.
    hidden, updates = model.apply(
        {"params": params, "cache": cache}, prompt, mutable=["cache"],
        return_hidden=True,
    )
    return updates["cache"], _head_logits(params, hidden[:, -1])


def _prefill_extend(model: "Transformer", params: Any, cache: Any,
                    suffix: jax.Array):
    """Suffix prefill on a SEEDED cache: rows [0:base) already hold a
    shared prefix's K/V (gathered from the paged pool) and the counters
    sit at base — one block-causal forward of the remaining prompt
    tokens -> (cache, logits of the true last position). The
    shared-prefix admission path's sibling of ``_prefill``: same model,
    same head dispatch, and — because chunked and one-shot prefill are
    pinned bitwise identical — a prefill split at the shared boundary
    lands the same cache/logits a full prefill would, which is what
    makes skipping the prefix's compute a pure saving, never a numerics
    change. Plain traced code."""
    hidden, updates = model.apply(
        {"params": params, "cache": cache}, suffix, mutable=["cache"],
        return_hidden=True,
    )
    return updates["cache"], _head_logits(params, hidden[:, -1])


class ChunkedPrefill:
    """Resumable chunked prefill for one prompt: the ``prefill_chunked``
    loop held as state so a serving loop can interleave a token-budgeted
    number of chunks between decode iterations instead of stalling for a
    long prompt (tf_operator_tpu/serve/). ``prefill_chunked`` is this
    class run to completion — ONE copy of the right-pad, the
    last-true-position row formula, and the counter rollback.

    The last partial chunk is RIGHT-PADDED to the fixed shape: pad
    positions sit after every true position, so no true position ever
    attends one (causal); their K/V land in cache rows beyond the true
    length, which set_cache_index then masks out (decode writes
    overwrite them one by one). The cache must budget the padding:
    ceil(P/chunk)*chunk <= cfg.max_seq_len. Logits come from the true
    last position's row of the final chunk. Numerics are the same
    block-causal attention the one-shot prefill runs, so downstream
    greedy decode is unchanged (pinned vs generate in
    tests/test_prefill_chunked.py).
    """

    def __init__(self, cfg: TransformerConfig, params: Any,
                 prompt: jax.Array, chunk: int, *,
                 initial_cache: Any = None, base_index: int = 0) -> None:
        """``initial_cache``/``base_index`` seed a SUFFIX prefill: the
        cache already holds rows [0:base_index) (a shared prefix
        gathered out of the paged pool, counters at base_index) and
        ``prompt`` is only the remaining tokens — the padding budget and
        the final counter rollback both shift by base_index."""
        self.prompt_len = int(prompt.shape[1])
        self.base_index = int(base_index)
        _validate_prefill_chunk(cfg, self.prompt_len, chunk,
                                base=self.base_index)
        self.chunk = int(chunk)
        self.n_chunks = -(-self.prompt_len // self.chunk)
        self._padded = self.n_chunks * self.chunk
        if self._padded > self.prompt_len:
            prompt = jnp.concatenate(
                [prompt,
                 jnp.zeros((prompt.shape[0],
                            self._padded - self.prompt_len),
                           prompt.dtype)], axis=1,
            )
        self._prompt = prompt
        self._params = params
        init_fn, self._chunk_fn, self._head_fn = _prefill_chunk_fns(
            cfg, self.chunk
        )
        if initial_cache is None:
            self._cache = init_fn(params, prompt[:, :1])
        else:
            self._cache = initial_cache
        self._hidden = None
        self._at = 0

    @property
    def done(self) -> bool:
        return self._at >= self.n_chunks

    def feed(self, max_chunks: int = 1) -> int:
        """Run up to ``max_chunks`` chunk forwards; returns the number
        of PROMPT TOKENS processed (the unit a serving loop budgets)."""
        n = min(max_chunks, self.n_chunks - self._at)
        for _ in range(n):
            i = self._at
            self._cache, self._hidden = self._chunk_fn(
                self._params,
                self._cache,
                self._prompt[:, i * self.chunk:(i + 1) * self.chunk],
            )
            self._at += 1
        return n * self.chunk

    def result(self) -> tuple[Any, jax.Array]:
        """(cache, last-true-position logits) — call once, after done."""
        if not self.done:
            raise RuntimeError("prefill not finished")
        # True last position sits in the final chunk at row
        # p-1 - (padded-chunk).
        logits = self._head_fn(
            self._params, self._hidden,
            self.prompt_len - 1 - (self._padded - self.chunk),
        )
        cache = self._cache
        if self._padded > self.prompt_len:
            cache = set_cache_index(
                cache, self.base_index + self.prompt_len
            )
        return cache, logits


def prefill_chunked(
    cfg: TransformerConfig,
    params: Any,
    prompt: jax.Array,
    chunk: int = 64,
):
    """Prompt prefill through ONE fixed-[B, chunk] executable: (cache,
    last-position logits) for ANY prompt length — where ``_prefill``
    compiles per prompt shape, a server using this path compiles one
    prefill chunk once and serves every prompt length with
    ceil(P/chunk) calls of it. ``ChunkedPrefill`` (above) carries the
    padding/rollback contract; this is that machine run to completion.
    """
    pf = ChunkedPrefill(cfg, params, prompt, chunk)
    pf.feed(pf.n_chunks)
    return pf.result()


def _validate_prefill_chunk(cfg: TransformerConfig, p: int, chunk: int,
                            base: int = 0):
    """Shared eager validation for chunked prefill (generate_segments
    runs it before returning its generator; prefill_chunked before any
    device work): no device call may have happened when these raise.
    ``base`` is a seeded suffix prefill's starting row (ChunkedPrefill
    initial_cache/base_index) — the padding budget shifts by it."""
    if chunk < 1:
        raise ValueError(f"chunk={chunk} must be >= 1")
    if p < 1:
        raise ValueError("prompt must have at least one token")
    padded = -(-p // chunk) * chunk
    if base + padded > cfg.max_seq_len:
        at_base = f" at base {base}" if base else ""
        raise ValueError(
            f"prompt {p} right-padded to {padded}{at_base} exceeds "
            f"max_seq_len {cfg.max_seq_len} (the last partial chunk "
            "feeds a full chunk of cache rows before rollback)"
        )


@functools.lru_cache(maxsize=16)
def _prefill_chunk_fns(cfg: TransformerConfig, chunk: int):
    """(init, chunk_step, head) jitted trio for chunked prefill: init
    builds the empty cache, chunk_step feeds one fixed-[B, chunk] block
    (cache donated), head projects one hidden row to logits (row index
    a jit argument, so one executable serves every remainder)."""
    from dataclasses import replace

    dcfg = replace(cfg, decode=True, mesh=None, remat=False)
    model = Transformer(dcfg)

    def init(params, tok0):
        del params
        return model.init(jax.random.PRNGKey(0), tok0)["cache"]

    def chunk_step(params, cache, block):
        hidden, updates = model.apply(
            {"params": params, "cache": cache}, block, mutable=["cache"],
            return_hidden=True,
        )
        return updates["cache"], hidden

    def head(params, hidden, row):
        h = jax.lax.dynamic_index_in_dim(hidden, row, axis=1,
                                         keepdims=False)
        return _head_logits(params, h)

    return (
        jax.jit(init),
        jax.jit(chunk_step, donate_argnums=(1,)),
        jax.jit(head),
    )


@functools.lru_cache(maxsize=16)
def _segment_fns(cfg: TransformerConfig, segment: int):
    """(prefill, decode_segment) jitted pair for one (config, segment).

    decode_segment's shapes are independent of request length — cache is
    the static [B, max_seq_len, ...] buffer, logits [B, vocab] — so its
    executable is compiled once per (batch, config) and reused for every
    segment of every request. The cache argument is donated: segments
    update it in place instead of doubling decode memory."""
    from dataclasses import replace

    dcfg = replace(cfg, decode=True, mesh=None, remat=False)
    model = Transformer(dcfg)

    def decode_segment(params, cache, logits):
        def sample(carry, _):
            cache, logits = carry
            tok = logits.argmax(-1)
            nxt, upd = model.apply(
                {"params": params, "cache": cache},
                tok[:, None].astype(jnp.int32), mutable=["cache"],
            )
            return (upd["cache"], nxt[:, 0]), tok

        (cache, logits), toks = jax.lax.scan(
            sample, (cache, logits), None, length=segment
        )
        return cache, logits, toks.swapaxes(0, 1)

    return (
        jax.jit(functools.partial(_prefill, model)),
        jax.jit(decode_segment, donate_argnums=(1,)),
    )


def quantize_decode_params(params: Any) -> Any:
    """Trained params tree -> the int8 tree ``int8_decode=True`` expects.

    Every projection kernel (attention qkv/out, MLP in/out, lm_head)
    becomes {kernel_q int8 [k, n], scale f32 [n], bias f32 [n]} via
    symmetric per-output-channel quantization (ops/int8_dense.py);
    embeddings, position table, and norms pass through untouched (they are
    gathers / O(d) reads, not per-token full scans). MoE expert weights
    pass through too (int8 MoE decode is not implemented). The decode
    numerics contract is pinned by
    tests/test_training.py::TestInt8Decode."""
    from tf_operator_tpu.ops.int8_dense import quantize_int8

    def quant(name: str, sub: dict) -> dict:
        kern = sub["kernel"]
        if name in ("qkv", "q", "kv"):
            # [d, ...heads..., head_dim] -> [d, prod]: fused qkv, or the
            # GQA split q ([d, H, hd]) / kv ([d, 2, KV, hd]) projections.
            k2 = kern.reshape(kern.shape[0], -1)
        elif name == "out":  # [heads, head_dim, d] -> [h*hd, d]
            k2 = kern.reshape(-1, kern.shape[-1])
        else:  # already [k, n]
            k2 = kern
        q, scale = quantize_int8(k2)
        return {
            "kernel_q": q, "scale": scale,
            "bias": sub["bias"].reshape(-1).astype(jnp.float32),
        }

    targets = {"qkv", "q", "kv", "out", "in_proj", "out_proj", "lm_head"}

    def walk(tree: Any) -> Any:
        out = {}
        for name, sub in tree.items():
            if (
                name in targets
                and isinstance(sub, dict)
                and "kernel" in sub
            ):
                out[name] = quant(name, sub)
            elif isinstance(sub, dict):
                out[name] = walk(sub)
            else:
                out[name] = sub
        return out

    return walk(params)


def param_sharding_rules(tp_axis: str = "tp") -> dict[str, tuple]:
    """PartitionSpec rules (path-substring → spec) for tensor parallelism:
    QKV + MLP-in split the output feature dim, out-projections split the
    input feature dim — the Megatron pairing that needs only one all-reduce
    per block per direction."""
    return {
        "qkv/kernel": (None, None, tp_axis, None),  # [d_model,3,heads,head_dim]
        # GQA split projections: q shards its (full) head dim like qkv;
        # kv shards the KV-head dim (requires n_kv_heads % tp == 0 — with
        # fewer KV heads than tp, drop this rule and keep kv replicated).
        "attn/q/kernel": (None, tp_axis, None),  # [d_model,heads,head_dim]
        "attn/kv/kernel": (None, None, tp_axis, None),  # [d,2,kv,head_dim]
        "attn/out/kernel": (tp_axis, None, None),  # [heads,head_dim,d_model]
        "mlp/in_proj/kernel": (None, tp_axis),  # [d_model,d_ff]
        "mlp/out_proj/kernel": (tp_axis, None),  # [d_ff,d_model]
        "embed/embedding": (tp_axis, None),  # vocab split
        "lm_head/kernel": (None, tp_axis),  # vocab split
    }
