"""Decoder-only transformer with tensor- and sequence-parallel execution.

The long-context / model-parallel workload of the framework (the reference
has no sharded execution at all — SURVEY.md §2.9; this is the TPU-native
capability the rebuild adds on top of parity). Sharding design:

- params: attention QKV/out and MLP in/out kernels split over ``tp``
  (head dim / hidden dim respectively) via the PartitionSpec rules in
  ``param_sharding_rules`` — applied by train/steps.py with
  ``shard_params_by_rules``; XLA inserts the all-reduces.
- activations: [batch, seq, model] sharded (dp, sp, tp-on-hidden) — the
  ``sp`` axis is handled exactly by ring attention (parallel/ring_attention).
- bf16 compute, f32 params/softmax.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from tf_operator_tpu.ops import attention as device_attention
from tf_operator_tpu.parallel.ring_attention import ring_attention


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    # Mesh wiring (static): when mesh is set and has an 'sp' axis of size >1,
    # attention runs as ring attention over that axis.
    mesh: Any = None
    seq_axis: str = "sp"
    batch_axis: str = "dp"
    tp_axis: str = "tp"
    # Bound per-device attention-score memory under ring attention: fold kv
    # in chunks of this many keys (None = whole block at once). Exact either
    # way; set for long contexts where a [Tq, Tk] f32 tile won't fit.
    ring_kv_chunk: int | None = None
    # Rematerialize each block on the backward pass (jax.checkpoint): layer
    # activations are recomputed instead of stored, trading ~1/3 more FLOPs
    # for O(n_layers) less HBM — what makes long-context training fit on a
    # chip (the flash kernel already never materializes O(S^2) scores; remat
    # removes the O(n_layers * S * d_model) residual-stream term).
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def use_ring(self) -> bool:
        return self.mesh is not None and self.mesh.shape.get(self.seq_axis, 1) > 1


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, t, _ = x.shape
        qkv = nn.DenseGeneral(
            (3, cfg.n_heads, cfg.head_dim),
            axis=-1,
            dtype=cfg.dtype,
            name="qkv",
        )(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cfg.use_ring:
            batch_spec = (cfg.batch_axis,) if cfg.mesh.shape.get(cfg.batch_axis, 1) > 1 else (None,)
            # Heads are tp-sharded by the qkv kernel rule; declaring that to
            # shard_map (the ring body is head-independent) avoids an
            # all-gather of Q/K/V heads at the boundary on every layer.
            head_spec = (
                (cfg.tp_axis,)
                if cfg.mesh.shape.get(cfg.tp_axis, 1) > 1
                else (None,)
            )
            out = ring_attention(
                q, k, v, cfg.mesh,
                seq_axis=cfg.seq_axis,
                batch_spec=batch_spec,
                head_spec=head_spec,
                causal=True,
                kv_chunk=cfg.ring_kv_chunk,
            )
        else:
            # ops.attention dispatches: pallas flash kernel on TPU with
            # tileable shapes, XLA reference path otherwise. The pallas
            # custom-call has no SPMD partitioning rule, so under a mesh
            # with dp/tp > 1 it must sit inside shard_map (batch over dp,
            # heads over tp — both embarrassingly parallel for attention);
            # GSPMD partitions only the surrounding ops.
            mesh = cfg.mesh
            dp = mesh.shape.get(cfg.batch_axis, 1) if mesh is not None else 1
            tp = mesh.shape.get(cfg.tp_axis, 1) if mesh is not None else 1
            # shard_map (unlike GSPMD) hard-requires divisibility; shapes
            # that don't divide keep the old GSPMD-partitionable XLA path.
            bspec = cfg.batch_axis if dp > 1 and b % dp == 0 else None
            hspec = cfg.tp_axis if tp > 1 and cfg.n_heads % tp == 0 else None
            if bspec or hspec:
                spec = jax.sharding.PartitionSpec(bspec, None, hspec, None)
                out = jax.shard_map(
                    lambda q, k, v: device_attention(q, k, v, causal=True),
                    mesh=mesh,
                    in_specs=(spec, spec, spec),
                    out_specs=spec,
                    check_vma=False,
                )(q, k, v)
            elif dp > 1 or tp > 1:
                # Indivisible under an active mesh: never hand GSPMD the
                # pallas custom-call (it has no partitioning rule).
                out = device_attention(q, k, v, causal=True, use_flash=False)
            else:
                out = device_attention(q, k, v, causal=True)
        return nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), dtype=cfg.dtype, name="out"
        )(out)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, name="in_proj")(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.d_model, dtype=cfg.dtype, name="out_proj")(h)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        x = x + Attention(self.cfg, name="attn")(nn.RMSNorm(dtype=self.cfg.dtype)(x))
        x = x + MLP(self.cfg, name="mlp")(nn.RMSNorm(dtype=self.cfg.dtype)(x))
        return x


class Transformer(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, return_hidden: bool = False):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype, name="embed")(tokens)
        pos = nn.Embed(cfg.max_seq_len, cfg.d_model, dtype=cfg.dtype, name="pos")(
            jnp.arange(tokens.shape[1])[None, :]
        )
        x = x + pos
        block_cls = nn.remat(Block) if cfg.remat else Block
        for i in range(cfg.n_layers):
            x = block_cls(cfg, name=f"block_{i}")(x)
        x = nn.RMSNorm(dtype=cfg.dtype)(x)
        head = nn.Dense(cfg.vocab_size, dtype=jnp.float32, name="lm_head")
        if return_hidden:
            # Callers computing a fused/chunked loss read lm_head params
            # directly (train/steps.py chunked_lm_xent); touching the module
            # here keeps init creating them on this path too.
            if self.is_initializing():
                head(x[:, :1].astype(jnp.float32))
            return x
        return head(x.astype(jnp.float32))


def param_sharding_rules(tp_axis: str = "tp") -> dict[str, tuple]:
    """PartitionSpec rules (path-substring → spec) for tensor parallelism:
    QKV + MLP-in split the output feature dim, out-projections split the
    input feature dim — the Megatron pairing that needs only one all-reduce
    per block per direction."""
    return {
        "qkv/kernel": (None, None, tp_axis, None),  # [d_model,3,heads,head_dim]
        "attn/out/kernel": (tp_axis, None, None),  # [heads,head_dim,d_model]
        "mlp/in_proj/kernel": (None, tp_axis),  # [d_model,d_ff]
        "mlp/out_proj/kernel": (tp_axis, None),  # [d_ff,d_model]
        "embed/embedding": (tp_axis, None),  # vocab split
        "lm_head/kernel": (None, tp_axis),  # vocab split
    }
