"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

GShard/Switch-style dense dispatch, built for the MXU + GSPMD: routing is
expressed as einsums against one-hot dispatch/combine tensors (no gather /
dynamic shapes under jit), the stacked expert weights [E, ...] and the
dispatched activations [E, capacity, d] are sharded over ``ep``, and XLA's
SPMD partitioner inserts the all-to-alls that move tokens to their experts
and back — the TPU-native equivalent of a parameter-server fan-out, and a
capability the reference has no analog of (SURVEY.md §2.9: no sharded
execution of any kind).

Routing is top-k (``router_top_k``): k=1 is Switch (gate = raw top prob),
k>=2 is GShard-style (gates normalized over the selected experts, with
choice-priority capacity — every token's first choice queues before any
token's second choice, so second choices drop first). Tokens beyond an
expert's capacity pass through on the residual path (output 0 from the
MoE layer for that choice). The load-balancing auxiliary loss (Switch
Transformer form over first choices, n_experts * sum(fraction_tokens *
fraction_probs)) is sown into the ``losses`` collection; train steps read
it via apply(..., mutable=["losses"]).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class MoeConfig:
    n_experts: int = 8
    d_model: int = 256
    d_ff: int = 512
    capacity_factor: float = 1.25
    # Experts per token: 1 = Switch, 2 = GShard top-2 (see module doc).
    router_top_k: int = 1

    def __post_init__(self) -> None:
        if not 1 <= self.router_top_k <= self.n_experts:
            raise ValueError(
                f"router_top_k={self.router_top_k} must be in "
                f"[1, n_experts={self.n_experts}]"
            )
    # Tokens are routed within fixed-size groups so dispatch/combine memory
    # is linear in total tokens (group_size * capacity per group), not
    # quadratic; None = auto (<=512 tokens per group, aligned to the
    # sequence so groups never straddle dp batch shards).
    group_size: int | None = None
    dtype: Any = jnp.bfloat16
    ep_axis: str = "ep"
    data_axis: str = "dp"
    mesh: Any = None  # when set, constrain expert tensors over ep/dp axes


def top_k_dispatch(
    top_idx: jax.Array, gates: jax.Array, n_experts: int, capacity: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Choice-priority capacity dispatch (GShard top-k routing analog;
    reference has no MoE — this is TPU-stack capability beyond parity).

    top_idx/gates: [G, S, k] expert ids and renormalized gate weights per
    choice. Returns (dispatch [G,S,E,C], combine [G,S,E,C],
    first_choice_oh [G,S,E]).

    Queue positions for choice j start after all tokens' KEPT
    earlier-choice assignments to that expert, so when an expert
    overflows, later choices drop first and no slot is ever reserved for
    an assignment that was itself dropped — every expert dispatches
    exactly min(total assignments, capacity) tokens and each (expert,
    slot) holds at most one token (pinned by
    tests/test_moe_pipeline.py::test_dispatch_capacity_fully_utilized).
    """
    n_groups, group, k = top_idx.shape
    dispatch = jnp.zeros((n_groups, group, n_experts, capacity), jnp.float32)
    combine = jnp.zeros_like(dispatch)
    prior_count = jnp.zeros((n_groups, 1, n_experts), jnp.float32)
    first_choice_oh = None
    for j in range(k):
        oh = jax.nn.one_hot(
            top_idx[..., j], n_experts, dtype=jnp.float32
        )  # [G, S, E]
        if j == 0:
            first_choice_oh = oh
        position = (
            jnp.cumsum(oh, axis=1) * oh - oh + prior_count * oh
        )  # [G, S, E]
        keep = (position < capacity).astype(jnp.float32) * oh
        pos_one_hot = jax.nn.one_hot(
            jnp.sum(position * oh, axis=-1).astype(jnp.int32),
            capacity, dtype=jnp.float32,
        )  # [G, S, C]
        d_j = keep[..., None] * pos_one_hot[:, :, None, :]  # [G,S,E,C]
        dispatch = dispatch + d_j
        combine = combine + d_j * gates[..., j, None, None]
        prior_count = prior_count + keep.sum(axis=1, keepdims=True)
    return dispatch, combine, first_choice_oh


class MoeMlp(nn.Module):
    """Top-k routed expert MLP. Input/output: [batch, seq, d_model]."""

    cfg: MoeConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        b, t, d = x.shape
        group = _group_size(cfg, t)
        n_groups = b * t // group
        # Capacity scales with k: top-2 dispatches ~2x the assignments.
        capacity = max(
            1,
            int(math.ceil(
                cfg.capacity_factor * cfg.router_top_k * group
                / cfg.n_experts
            )),
        )

        w_router = self.param(
            "router", nn.initializers.lecun_normal(), (d, cfg.n_experts),
            jnp.float32,
        )
        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(),
            (cfg.n_experts, d, cfg.d_ff), jnp.float32,
        )
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(),
            (cfg.n_experts, cfg.d_ff, d), jnp.float32,
        )

        # [G, S, D]: groups are contiguous token runs within one example
        # (group <= seq len), so the G dim is batch-major and stays aligned
        # with dp batch sharding — no resharding before dispatch.
        k = cfg.router_top_k  # validated by MoeConfig.__post_init__

        tokens = x.reshape(n_groups, group, d)
        # Router in f32: tiny FLOPs, and softmax/argmax stability matters.
        logits = jnp.einsum(
            "gsd,de->gse", tokens.astype(jnp.float32), w_router
        )
        probs = jax.nn.softmax(logits, axis=-1)  # [G, S, E]
        top_vals, top_idx = jax.lax.top_k(probs, k)  # [G, S, k]
        if k == 1:
            gates = top_vals  # Switch: gate = raw top prob
        else:
            # GShard: gates renormalized over the selected experts.
            gates = top_vals / jnp.maximum(
                top_vals.sum(-1, keepdims=True), 1e-9
            )

        dispatch, combine, first_choice_oh = top_k_dispatch(
            top_idx, gates, cfg.n_experts, capacity
        )

        # Load-balancing aux loss over FIRST choices (computed before
        # capacity dropping; the Switch form, unchanged for k > 1).
        frac_tokens = jnp.mean(first_choice_oh, axis=(0, 1))
        frac_probs = jnp.mean(probs, axis=(0, 1))
        aux = cfg.n_experts * jnp.sum(frac_tokens * frac_probs)
        self.sow("losses", "moe_aux", aux)

        compute_dtype = cfg.dtype
        expert_in = jnp.einsum(
            "gsec,gsd->egcd", dispatch.astype(compute_dtype),
            tokens.astype(compute_dtype),
        )  # [E, G, C, D] — GSPMD turns this into the token->expert all-to-all
        expert_in = self._constrain(expert_in)
        h = jnp.einsum(
            "egcd,edf->egcf", expert_in, w_in.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        h = nn.gelu(h).astype(compute_dtype)
        h = self._constrain(h)
        expert_out = jnp.einsum(
            "egcf,efd->egcd", h, w_out.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        ).astype(compute_dtype)
        expert_out = self._constrain(expert_out)

        y = jnp.einsum(
            "gsec,egcd->gsd", combine.astype(compute_dtype), expert_out
        )  # expert->token all-to-all + weighted combine
        return y.reshape(b, t, d).astype(cfg.dtype)

    def _constrain(self, arr: jax.Array) -> jax.Array:
        """Pin [E, G, ...] expert tensors: experts over ep, groups over dp."""
        cfg = self.cfg
        if cfg.mesh is None:
            return arr
        ep = cfg.ep_axis if cfg.mesh.shape.get(cfg.ep_axis, 1) > 1 else None
        dp = cfg.data_axis if cfg.mesh.shape.get(cfg.data_axis, 1) > 1 else None
        if ep is None and dp is None:
            return arr
        spec = jax.sharding.PartitionSpec(ep, dp)
        return jax.lax.with_sharding_constraint(
            arr, jax.sharding.NamedSharding(cfg.mesh, spec)
        )


def _group_size(cfg: MoeConfig, seq_len: int) -> int:
    """Routing group size: explicit, or the largest divisor of the sequence
    length <= 512 (groups never straddle examples, so dispatch memory is
    group*capacity per group — linear in total tokens)."""
    if cfg.group_size is not None:
        if seq_len % cfg.group_size and cfg.group_size % seq_len:
            raise ValueError(
                f"group_size {cfg.group_size} incompatible with seq {seq_len}"
            )
        return min(cfg.group_size, seq_len)
    for g in range(min(512, seq_len), 0, -1):
        if seq_len % g == 0:
            return g
    return seq_len


def moe_param_sharding_rules(ep_axis: str = "ep") -> dict[str, tuple]:
    """PartitionSpec rules for expert-parallel placement: stacked expert
    weights split on the expert dim; router replicated."""
    return {
        "w_in": (ep_axis, None, None),
        "w_out": (ep_axis, None, None),
    }


class MoeBlock(nn.Module):
    """Pre-norm residual MoE feed-forward block (attention-free; composes
    with the Transformer's attention blocks or stands alone for tests)."""

    cfg: MoeConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        return x + MoeMlp(self.cfg, name="moe")(
            nn.RMSNorm(dtype=self.cfg.dtype)(x)
        )


def aux_loss_from(collections: dict) -> jax.Array:
    """Sum every sown moe_aux scalar from apply(..., mutable=['losses'])."""
    losses = collections.get("losses", {})
    leaves = jax.tree.leaves(losses)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum(l) for l in leaves)
