"""Speculative decoding: exact greedy acceleration with a draft model.

A small DRAFT model proposes k tokens autoregressively; the TARGET model
scores all k in ONE chunked forward against its KV cache (the same
block-causal multi-token path prompt prefill uses) and accepts the
longest prefix that matches its own greedy choices, then contributes one
more token itself (the correction at the first mismatch, or the bonus
token when everything matched). Greedy speculative decoding is EXACT:
every emitted token is the target model's argmax given the emitted
prefix, so the output is bit-identical to ``generate(target_cfg, ...)``
with ``temperature=0`` — pinned by tests/test_spec_decode.py.

Why this is the TPU-shaped decode accelerator: single-token decode is
weight-read-bound (docs/perf.md — the per-step HBM read of the full
parameter set dominates), so the target's cost per ROUND is one small
chunk forward (k+1 tokens read the weights ONCE) instead of m+1
single-token reads. With acceptance rate a and a draft that costs
fraction c of the target per token, tokens/round = a·k* + 1 (expected)
while round cost ≈ (k+1)·c + 1 target-chunk reads — the measured
component costs let the speedup curve be computed for any trained
draft/target pair (see the spec leg notes in docs/perf.md).

Mechanics that make it jittable (static shapes throughout):

- The while_loop carries (target cache, draft cache, out buffer, count,
  pending token). Each round feeds a FIXED k+1 tokens to both models.
- Cache rollback is O(1): rejected positions are undone by rewriting the
  scalar ``cache_index`` (set_cache_index) — the decode attention masks
  every position >= index, so stale K/V entries beyond it are invisible
  and get overwritten by later writes.
- Batch rows accept different prefix lengths; the round advances by the
  BATCH MIN m. Rows that accepted more still emit their own target
  argmax at position m (for them it equals their draft token), so
  per-row exactness holds with a single shared cache index.

No reference counterpart: the reference operator runs no models
(SURVEY.md §2.9); this extends the serving stack its users would bring.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from tf_operator_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    _prefill,
    set_cache_index,
)

__all__ = ["set_cache_index", "speculative_generate"]


def speculative_generate(
    target_cfg: TransformerConfig,
    target_params: Any,
    draft_cfg: TransformerConfig,
    draft_params: Any,
    prompt: jax.Array,
    num_steps: int,
    *,
    k: int = 4,
) -> tuple[jax.Array, jax.Array]:
    """Greedy speculative decode: ([B, num_steps] tokens, rounds used).

    Exact equivalent of ``generate(target_cfg, target_params, prompt,
    num_steps)`` at temperature 0, for ANY draft model (a bad draft only
    costs speed, never correctness). ``k`` = draft proposals per round;
    each round emits between 1 and k+1 tokens (batch-min acceptance + 1).
    ``rounds`` is the number of verify forwards the loop ran — the
    acceptance telemetry: tokens/round = num_steps/rounds.
    """
    if prompt.shape[1] + num_steps + k + 1 > target_cfg.max_seq_len:
        raise ValueError(
            f"prompt {prompt.shape[1]} + steps {num_steps} + speculation "
            f"margin {k + 1} exceeds target max_seq_len "
            f"{target_cfg.max_seq_len} (the cache must hold up to k "
            "rejected tokens beyond the emitted sequence)"
        )
    if prompt.shape[1] + num_steps + k + 1 > draft_cfg.max_seq_len:
        raise ValueError("draft max_seq_len too small for prompt + steps + k")
    if k < 1:
        raise ValueError(f"k={k} must be >= 1")
    for name, cfg in (("target", target_cfg), ("draft", draft_cfg)):
        if cfg.int8_decode:
            raise ValueError(
                f"{name}_cfg.int8_decode is not supported by speculative "
                "decoding (the int8 head tree has no shared greedy-head "
                "path here); quantize after choosing a decode strategy"
            )
    fn = _spec_fn(target_cfg, draft_cfg, num_steps, int(k))
    return fn(target_params, draft_params, prompt)


@functools.lru_cache(maxsize=16)
def _spec_fn(target_cfg: TransformerConfig, draft_cfg: TransformerConfig,
             num_steps: int, k: int):
    from dataclasses import replace

    tmodel = Transformer(replace(
        target_cfg, decode=True, mesh=None, remat=False))
    dmodel = Transformer(replace(
        draft_cfg, decode=True, mesh=None, remat=False))

    def run(tparams, dparams, prompt):
        b = prompt.shape[0]
        tok_dtype = prompt.dtype

        # Prompt prefill, both models (the shared _prefill construction);
        # only the target's logits matter.
        tcache, tlogits = _prefill(tmodel, tparams, prompt)
        dcache, _ = _prefill(dmodel, dparams, prompt)

        pend = tlogits.argmax(-1).astype(tok_dtype)

        # Output buffer with k+1 slack: each round unconditionally writes
        # a k+1 window at position n (n < num_steps inside the loop, so
        # the window never clamps); positions beyond the accepted count
        # hold junk until the next round's window overwrites them.
        out0 = jnp.zeros((b, num_steps + k + 1), tok_dtype)
        out0 = out0.at[:, 0].set(pend)

        def draft_step(carry, _):
            dcache, tok = carry
            logits, upd = dmodel.apply(
                {"params": dparams, "cache": dcache}, tok[:, None],
                mutable=["cache"],
            )
            nxt = logits[:, 0].argmax(-1).astype(tok_dtype)
            return (upd["cache"], nxt), nxt

        def round_body(state):
            tcache, dcache, out, n, pend, rounds = state
            t_idx = _cache_index(tcache)
            d_idx = _cache_index(dcache)

            # Draft k+1 greedy steps from the pending token. Proposals
            # are the first k outputs; the last is drafted only so the
            # draft cache contains d_k when everything gets accepted.
            (dcache, _), drafted = jax.lax.scan(
                draft_step, (dcache, pend), None, length=k + 1
            )
            drafted = drafted.swapaxes(0, 1)  # [B, k+1]
            proposals = drafted[:, :k]

            # Target verifies the whole chunk in one forward: feed
            # [pend, d_1..d_k] (k+1 tokens); logits row i predicts the
            # token AFTER chunk[i].
            chunk = jnp.concatenate([pend[:, None], proposals], axis=1)
            tlogits, tupd = tmodel.apply(
                {"params": tparams, "cache": tcache}, chunk,
                mutable=["cache"],
            )
            tcache = tupd["cache"]
            targmax = tlogits.argmax(-1).astype(tok_dtype)  # [B, k+1]

            # Per-row accepted prefix length, then the batch-min cut.
            match = proposals == targmax[:, :k]  # [B, k]
            m_row = jnp.sum(jnp.cumprod(match.astype(jnp.int32), 1), 1)
            m = jnp.min(m_row)  # scalar: tokens accepted this round

            # Emit d_1..d_m then each row's own argmax at position m
            # (correction at a mismatch; equal to the row's d_{m+1} when
            # the row accepted further — exactness per row).
            nxt_pend = jnp.take_along_axis(
                targmax, jnp.full((b, 1), m), axis=1
            )[:, 0]
            cand = jnp.where(
                jnp.arange(k + 1)[None, :] < m, drafted, nxt_pend[:, None]
            )
            out = jax.lax.dynamic_update_slice(out, cand, (0, n))

            # Rollback: true fed prefix grew by pend + accepted proposals.
            tcache = set_cache_index(tcache, t_idx + 1 + m)
            dcache = set_cache_index(dcache, d_idx + 1 + m)
            return (tcache, dcache, out, n + 1 + m, nxt_pend, rounds + 1)

        def cond(state):
            return state[3] < num_steps

        state = (tcache, dcache, out0, jnp.asarray(1, jnp.int32), pend,
                 jnp.asarray(0, jnp.int32))
        _, _, out, _, _, rounds = jax.lax.while_loop(cond, round_body, state)
        return out[:, :num_steps], rounds

    return jax.jit(run)


def _cache_index(cache: Any) -> jax.Array:
    """The shared scalar cache_index (all layers advance in lockstep)."""
    for leaf_path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        if any(
            getattr(p, "key", None) == "cache_index" for p in leaf_path
        ):
            return leaf
    raise ValueError("no cache_index in cache tree")
