"""Speculative decoding: exact acceleration with a draft model.

A small DRAFT model proposes k tokens autoregressively; the TARGET model
scores all k in ONE chunked forward against its KV cache (the same
block-causal multi-token path prompt prefill uses), accepts a prefix,
and contributes one more token itself. Both decoding modes preserve the
target's output exactly — pinned by tests/test_spec_decode.py:

- GREEDY (temperature=0): accept while the proposal matches the
  target's argmax; the output equals ``generate(target_cfg, ...)`` at
  temperature 0. Exact modulo cross-shape float reduction order: the
  k+1-token chunk forward and the single-token forward may reduce in
  different orders on accelerator backends, so a near-tie argmax can
  flip (the same tolerance tests/test_examples.py applies to the
  coalescer). Bit-exactness is pinned only where the unit tests pin it
  — f32 on CPU (tests/test_spec_decode.py).
- SAMPLED (temperature>0): accept d ~ q with probability
  min(1, p(d)/q(d)), resample rejections from the residual
  max(p-q, 0)/Z (``residual_distribution``) — the emitted-token law at
  every position is exactly the target's tempered softmax, for ANY
  draft.

Why this is the TPU-shaped decode accelerator: single-token decode is
weight-read-bound (docs/perf.md — the per-step HBM read of the full
parameter set dominates), so the target's cost per ROUND is one small
chunk forward (k+1 tokens read the weights ONCE) instead of m+1
single-token reads. With acceptance rate a and a draft that costs
fraction c of the target per token, tokens/round = a·k* + 1 (expected)
while round cost ≈ (k+1)·c + 1 target-chunk reads — the measured
component costs let the speedup curve be computed for any trained
draft/target pair (see the spec leg notes in docs/perf.md).

Mechanics that make it jittable (static shapes throughout):

- The while_loop carries (target cache, draft cache, out buffer, count,
  pending token). Each round feeds a FIXED k+1 tokens to both models.
- Cache rollback is O(1): rejected positions are undone by rewriting the
  scalar ``cache_index`` (set_cache_index) — the decode attention masks
  every position >= index, so stale K/V entries beyond it are invisible
  and get overwritten by later writes.
- Batch rows accept different prefix lengths; the round advances by the
  BATCH MIN m. Rows that accepted more still emit their own target
  argmax at position m (for them it equals their draft token), so
  per-row exactness holds with a single shared cache index.

No reference counterpart: the reference operator runs no models
(SURVEY.md §2.9); this extends the serving stack its users would bring.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from tf_operator_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    _prefill,
    set_cache_index,
)

__all__ = [
    "lane_accept_emit",
    "residual_distribution",
    "set_cache_index",
    "spec_margin",
    "speculative_generate",
]


def spec_margin(k: int) -> int:
    """Cache rows one speculative lane may touch BEYOND prompt + steps:
    up to k rejected draft tokens plus the in-flight pend write. THE
    budget formula — ``speculative_generate``'s eager check, the
    continuous engine's ``validate_request``, and serve_lm's margin
    test all read it from here so they cannot drift."""
    return k + 1


def speculative_generate(
    target_cfg: TransformerConfig,
    target_params: Any,
    draft_cfg: TransformerConfig,
    draft_params: Any,
    prompt: jax.Array,
    num_steps: int,
    *,
    k: int = 4,
    temperature: float = 0.0,
    top_p: float | None = None,
    rng: jax.Array | None = None,
    program: Any = None,
) -> tuple[jax.Array, jax.Array]:
    """Speculative decode: ([B, num_steps] tokens, rounds used).

    ``program`` (serve/constrain.CompiledProgram, optional) composes a
    token-level grammar constraint with speculation — the SOLO oracle
    the continuous engine's constrained spec lanes pin against: the
    draft walks the FSM and proposes only from mask-added logits, the
    verify re-masks the target's chunk rows with the same per-position
    state chain before the unchanged accept test (a proposal the
    grammar forbids has q = p = 0 there — a mask violation is just a
    rejection, the rewind machinery untouched), and the residual/bonus
    draws come from masked rows so every emitted token is legal. With
    ``program=None`` the constraint code never enters the trace.

    ``temperature=0`` (default) is GREEDY: equivalent to
    ``generate(target_cfg, target_params, prompt, num_steps)``, for ANY
    draft model (a bad draft only costs speed, never correctness).
    Exact modulo cross-shape float reduction order on accelerator
    backends (chunked vs single-token forwards may reduce differently;
    a near-tie argmax can flip); bit-exact as pinned by the f32 CPU
    unit tests in tests/test_spec_decode.py.

    ``temperature > 0`` is SAMPLED speculative decoding with the
    distribution-preserving accept/residual scheme: each proposal
    d ~ q is accepted with probability min(1, p(d)/q(d)); on rejection
    the token is resampled from the residual max(p - q, 0)/Z. The
    emitted-token distribution at every position is EXACTLY the
    target's tempered softmax p — the algebraic identity
    q(t)·min(1, p(t)/q(t)) + (1 - Σ_s q(s)·min(1, p(s)/q(s)))·r(t) =
    p(t) — regardless of the draft (pinned analytically and empirically
    in tests/test_spec_decode.py). Rows accept different prefix
    lengths; the round advances by the batch-min, and at the cut each
    row emits ITS OWN accept-or-residual outcome, which is a correct
    per-row sample either way. ``rng`` is required when sampling.

    ``top_p`` (sampling only) applies the nucleus filter to BOTH
    distributions — the draft proposes from its filtered q', the accept
    test and residual target the filtered p' — so the emitted law is
    exactly ``generate(..., temperature, top_p)``'s nucleus
    distribution (the identity holds for any pair of distributions,
    filtered ones included; a proposal outside the target's nucleus has
    p'(d)=0 and is surely rejected).

    ``k`` = draft proposals per round; each round emits between 1 and
    k+1 tokens. ``rounds`` is the number of verify forwards the loop
    ran — the acceptance telemetry: tokens/round = num_steps/rounds.
    """
    if prompt.shape[1] + num_steps + spec_margin(k) > target_cfg.max_seq_len:
        raise ValueError(
            f"prompt {prompt.shape[1]} + steps {num_steps} + speculation "
            f"margin {spec_margin(k)} exceeds target max_seq_len "
            f"{target_cfg.max_seq_len} (the cache must hold up to k "
            "rejected tokens beyond the emitted sequence)"
        )
    if prompt.shape[1] + num_steps + spec_margin(k) > draft_cfg.max_seq_len:
        raise ValueError("draft max_seq_len too small for prompt + steps + k")
    if k < 1:
        raise ValueError(f"k={k} must be >= 1")
    for name, cfg in (("target", target_cfg), ("draft", draft_cfg)):
        if cfg.int8_decode:
            raise ValueError(
                f"{name}_cfg.int8_decode is not supported by speculative "
                "decoding (the int8 head tree has no shared greedy-head "
                "path here); quantize after choosing a decode strategy"
            )
    if temperature < 0:
        raise ValueError(f"temperature={temperature} must be >= 0")
    if temperature > 0 and rng is None:
        raise ValueError("temperature > 0 needs an rng key")
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p={top_p} must be in (0, 1]")
    if top_p is not None and temperature <= 0:
        raise ValueError("top_p requires temperature > 0 (greedy ignores it)")
    fn = _spec_fn(target_cfg, draft_cfg, num_steps, int(k),
                  float(temperature),
                  None if top_p is None else float(top_p), program)
    if rng is None:
        rng = jax.random.PRNGKey(0)  # greedy: carried but never consumed
    return fn(target_params, draft_params, prompt, rng)


@functools.lru_cache(maxsize=16)
def _spec_fn(target_cfg: TransformerConfig, draft_cfg: TransformerConfig,
             num_steps: int, k: int, temperature: float = 0.0,
             top_p: float | None = None, program: Any = None):
    from dataclasses import replace

    from tf_operator_tpu.models.transformer import _nucleus_filter

    tmodel = Transformer(replace(
        target_cfg, decode=True, mesh=None, remat=False))
    dmodel = Transformer(replace(
        draft_cfg, decode=True, mesh=None, remat=False))
    # One round skeleton for both modes; `sampled` picks the sampling/
    # accept/emission rules at TRACE time, so the greedy executable is
    # unchanged by the branches (rng rides the carry either way but the
    # greedy trace never consumes it).
    sampled = temperature > 0
    if program is not None:
        import numpy as np

        # The program's local tables with the engine-pool convention
        # appended: a disallowed transition (reachable only after the
        # grammar COMPLETES) lands on an always-allow free state — the
        # pool's garbage row 0 — so solo and the continuous engine's
        # constrained spec lanes agree bitwise for the whole stream.
        n_states, vsz = program.allow.shape
        free = n_states
        allow_x = jnp.asarray(np.concatenate(
            [program.allow, np.ones((1, vsz), np.bool_)], axis=0
        ))
        next_x = jnp.asarray(np.concatenate(
            [np.where(program.allow, program.next.astype(np.int32),
                      free),
             np.full((1, vsz), free, np.int32)], axis=0
        ))

    def cmask(logits, st):
        """Additive grammar mask for [B, V] logits at per-row FSM
        states [B] — identity (not even traced) without a program."""
        if program is None:
            return logits
        return logits + jnp.where(allow_x[st], 0.0, -1e30)

    def advance(st, tok):
        if program is None:
            return st
        return next_x[st, tok.astype(jnp.int32)]

    def scale(logits):
        """Tempered (and optionally nucleus-filtered) logits: the ONE
        transformation both models' distributions pass through, so p
        and q are always the same kind of distribution."""
        s = logits / temperature
        if top_p is not None:
            s = _nucleus_filter(s, top_p)
        return s

    def run(tparams, dparams, prompt, rng):
        b = prompt.shape[0]
        tok_dtype = prompt.dtype

        # Prompt prefill, both models (the shared _prefill construction);
        # only the target's logits matter.
        tcache, tlogits = _prefill(tmodel, tparams, prompt)
        dcache, _ = _prefill(dmodel, dparams, prompt)

        # Per-row FSM state (all-zero init; stays zero and unused
        # without a program). pend is the first GENERATED token: its
        # distribution takes the init state's mask, and the carried
        # state is always the state AFTER pend — the engine invariant.
        st0 = jnp.zeros((b,), jnp.int32)
        tlogits = cmask(tlogits, st0)
        if sampled:
            rng, k0 = jax.random.split(rng)
            pend = jax.random.categorical(
                k0, scale(tlogits)
            ).astype(tok_dtype)
        else:
            pend = tlogits.argmax(-1).astype(tok_dtype)
        st0 = advance(st0, pend)

        # Output buffer with k+1 slack: each round unconditionally writes
        # a k+1 window at position n (n < num_steps inside the loop, so
        # the window never clamps); positions beyond the accepted count
        # hold junk until the next round's window overwrites them.
        out0 = jnp.zeros((b, num_steps + k + 1), tok_dtype)
        out0 = out0.at[:, 0].set(pend)

        def draft_step(carry, step_key):
            dcache, tok, st = carry
            logits, upd = dmodel.apply(
                {"params": dparams, "cache": dcache}, tok[:, None],
                mutable=["cache"],
            )
            logits = cmask(logits[:, 0], st)
            if sampled:
                nxt = jax.random.categorical(
                    step_key, scale(logits)
                ).astype(tok_dtype)
                return (upd["cache"], nxt, advance(st, nxt)), (nxt, logits)
            nxt = logits.argmax(-1).astype(tok_dtype)
            return (upd["cache"], nxt, advance(st, nxt)), (nxt, ())

        def round_body(state):
            tcache, dcache, out, n, pend, st, rounds, rng = state
            t_idx = _cache_index(tcache)
            d_idx = _cache_index(dcache)
            rng, k_draft, k_acc, k_res, k_bonus = jax.random.split(rng, 5)

            # Draft k+1 steps from the pending token. Proposals are the
            # first k outputs; the last is drafted only so the draft
            # cache contains d_k when everything gets accepted.
            (dcache, _, _), (drafted, qlogits) = jax.lax.scan(
                draft_step, (dcache, pend, st),
                jax.random.split(k_draft, k + 1),
            )
            drafted = drafted.swapaxes(0, 1)  # [B, k+1]
            proposals = drafted[:, :k]

            # Target verifies the whole chunk in one forward: feed
            # [pend, d_1..d_k] (k+1 tokens); logits row i predicts the
            # token AFTER chunk[i].
            chunk = jnp.concatenate([pend[:, None], proposals], axis=1)
            tlogits, tupd = tmodel.apply(
                {"params": tparams, "cache": tcache}, chunk,
                mutable=["cache"],
            )
            tcache = tupd["cache"]
            if program is not None:
                # The same FSM chain the draft walked, re-derived:
                # s_seq[:, j] is the state chunk position j's target
                # distribution must be masked by (s_0 = the carried
                # state after pend, then advancing through proposals).
                def fsm_walk(s, d):
                    return next_x[s, d], s

                s_last, s_seq = jax.lax.scan(
                    fsm_walk, st,
                    jnp.swapaxes(proposals.astype(jnp.int32), 0, 1),
                )
                s_seq = jnp.concatenate(
                    [jnp.swapaxes(s_seq, 0, 1), s_last[:, None]], axis=1
                )  # [B, k+1]
                tlogits = tlogits + jnp.where(
                    allow_x[s_seq], 0.0, -1e30
                )

            if sampled:
                # Accept tests at positions 1..k: u < p(d)/q(d), in log
                # space (ratio >= 1 always accepts; log u < 0 surely).
                qlogits = qlogits.swapaxes(0, 1)  # [B, k+1, V]
                logp = jax.nn.log_softmax(scale(tlogits[:, :k]))
                logq = jax.nn.log_softmax(scale(qlogits[:, :k]))
                sel = proposals[..., None]
                lp = jnp.take_along_axis(logp, sel, axis=-1)[..., 0]
                lq = jnp.take_along_axis(logq, sel, axis=-1)[..., 0]
                log_u = jnp.log(jax.random.uniform(
                    k_acc, (b, k), minval=1e-38, maxval=1.0
                ))
                accept = log_u < jnp.minimum(lp - lq, 0.0)  # [B, k]
            else:
                targmax = tlogits.argmax(-1).astype(tok_dtype)  # [B, k+1]
                accept = proposals == targmax[:, :k]

            # Per-row accepted prefix length, then the batch-min cut.
            m_row = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), 1), 1)
            m = jnp.min(m_row)  # scalar: tokens accepted this round

            # Emit d_1..d_m, then each row's OWN outcome at the cut.
            if sampled:
                # Accepted rows their d_{m+1}; rejected rows a residual
                # resample; every-row-accepted-k gets the bonus token.
                p_all = jnp.exp(logp)
                q_all = jnp.exp(logq)
                resample = jax.random.categorical(
                    k_res,
                    jnp.log(residual_distribution(p_all, q_all) + 1e-38),
                ).astype(tok_dtype)                 # [B, k]
                bonus = jax.random.categorical(
                    k_bonus, scale(tlogits[:, k])
                ).astype(tok_dtype)                 # [B]
                col = jnp.minimum(m, k - 1)
                at_m = jnp.take_along_axis(
                    jnp.where(accept, proposals, resample),
                    jnp.full((b, 1), col), axis=1,
                )[:, 0]
                nxt_pend = jnp.where(m == k, bonus, at_m)
            else:
                # The row's argmax at position m: correction at a
                # mismatch, equal to the row's d_{m+1} when it accepted
                # further — exactness per row.
                nxt_pend = jnp.take_along_axis(
                    targmax, jnp.full((b, 1), m), axis=1
                )[:, 0]

            cand = jnp.where(
                jnp.arange(k + 1)[None, :] < m, drafted, nxt_pend[:, None]
            )
            out = jax.lax.dynamic_update_slice(out, cand, (0, n))

            if program is not None:
                # New carried state: after the batch-min accepted
                # prefix (s_seq[:, m]) advanced through each row's own
                # next pend — always legal: resample/bonus/correction
                # all drew from mask-added rows.
                st = next_x[s_seq[:, m], nxt_pend.astype(jnp.int32)]

            # Rollback: true fed prefix grew by pend + accepted proposals.
            tcache = set_cache_index(tcache, t_idx + 1 + m)
            dcache = set_cache_index(dcache, d_idx + 1 + m)
            return (tcache, dcache, out, n + 1 + m, nxt_pend, st,
                    rounds + 1, rng)

        def cond(state):
            return state[3] < num_steps

        state = (tcache, dcache, out0, jnp.asarray(1, jnp.int32), pend,
                 st0, jnp.asarray(0, jnp.int32), rng)
        _, _, out, _, _, _, rounds, _ = jax.lax.while_loop(
            cond, round_body, state
        )
        return out[:, :num_steps], rounds

    return jax.jit(run)


def residual_distribution(p: jax.Array, q: jax.Array) -> jax.Array:
    """The rejection-resample distribution r = max(p - q, 0)/Z over the
    last axis, with a p fallback where Z == 0 (possible only when the
    accept probability was exactly 1, so the fallback never actually
    fires — it just keeps the categorical well-defined). Module-level so
    the test suite can pin the algebraic identity
    q·min(1,p/q) + (1-a)·r = p against the exact code the decoder runs."""
    r = jnp.maximum(p - q, 0.0)
    z = jnp.sum(r, axis=-1, keepdims=True)
    return jnp.where(z > 0, r / jnp.where(z > 0, z, 1.0), p)


def lane_accept_emit(k: int, tlogits: jax.Array, qlogits: jax.Array,
                     drafted: jax.Array, pend: jax.Array,
                     k_acc: jax.Array, k_res: jax.Array,
                     k_bonus: jax.Array, temperature: jax.Array,
                     top_p: jax.Array, has_top_p: jax.Array):
    """ONE lane's accept/emit round: ``round_body`` above at batch 1,
    with the trace-time sampled/greedy branches turned into traced
    selects so temperature/top_p stay DATA (the continuous engine vmaps
    this over its slot axis — serve/engine.py — and slots with
    different sampling modes ride one executable).

    Inputs are the lane's verify logits ``tlogits`` [k+1, V] (the
    target's chunk forward over [pend, d_1..d_k]), the draft's
    per-proposal logits ``qlogits`` [k+1, V], the drafted tokens
    ``drafted`` [k+1], the incoming pend token, and the round keys the
    draft pass split off the lane's rng (solo's
    ``rng, k_draft, k_acc, k_res, k_bonus = split(rng, 5)`` schedule).
    Every random draw reproduces the solo shapes exactly — uniforms
    ``(1, k)``, categoricals over ``[1, ..., V]`` — so a lane's stream
    is BITWISE the b=1 ``speculative_generate`` stream for the same
    seed (greedy lanes consume the keys into discarded selects, exactly
    as solo's greedy trace never draws them: the selected VALUES agree).

    Returns ``(toks [k+1], count, nxt_pend)``: the round's token window
    ``[pend, d_1..d_k]`` of which the first ``count = 1 + m`` are
    emitted (the incoming pend plus the accepted prefix — positions
    past the accept cut are dead until the caller's next round), and
    the pend for the next round (the correction/residual/bonus token,
    emitted at the head of the NEXT window). This is solo's out-buffer
    windowing relabeled by one position: solo writes
    ``[d_1..d_m, nxt_pend]`` after seeding out[0] with the prefill
    pend; emitting ``[pend, d_1..d_m]`` per round delivers the
    identical stream with no join-time token delivery."""
    sampled = temperature > 0

    def scale(logits):
        # Solo's scale() with the greedy guard: greedy lanes divide by 1
        # (their sampled branch is discarded by the selects below).
        s = logits / jnp.where(sampled, temperature, 1.0)
        from tf_operator_tpu.models.transformer import _nucleus_filter

        return jnp.where(has_top_p, _nucleus_filter(s, top_p), s)

    proposals = drafted[:k].astype(jnp.int32)
    targmax = tlogits.argmax(-1).astype(jnp.int32)  # [k+1]
    tl, ql = tlogits[None], qlogits[None]           # solo's b=1 shapes
    logp = jax.nn.log_softmax(scale(tl[:, :k]))
    logq = jax.nn.log_softmax(scale(ql[:, :k]))
    sel = proposals[None, :, None]
    lp = jnp.take_along_axis(logp, sel, axis=-1)[..., 0]   # [1, k]
    lq = jnp.take_along_axis(logq, sel, axis=-1)[..., 0]
    log_u = jnp.log(jax.random.uniform(
        k_acc, (1, k), minval=1e-38, maxval=1.0
    ))
    acc_s = log_u < jnp.minimum(lp - lq, 0.0)              # [1, k]
    accept = jnp.where(sampled, acc_s[0], proposals == targmax[:k])
    m = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))

    p_all, q_all = jnp.exp(logp), jnp.exp(logq)
    resample = jax.random.categorical(
        k_res, jnp.log(residual_distribution(p_all, q_all) + 1e-38)
    ).astype(jnp.int32)                                    # [1, k]
    bonus = jax.random.categorical(
        k_bonus, scale(tl[:, k])
    )[0].astype(jnp.int32)
    col = jnp.minimum(m, k - 1)
    at_m = jnp.take_along_axis(
        jnp.where(acc_s, proposals[None], resample),
        jnp.full((1, 1), col), axis=1,
    )[0, 0]
    nxt_pend = jnp.where(
        sampled, jnp.where(m == k, bonus, at_m), targmax[m]
    ).astype(jnp.int32)
    toks = jnp.concatenate([pend[None].astype(jnp.int32), proposals])
    return toks, (1 + m).astype(jnp.int32), nxt_pend


def _cache_index(cache: Any) -> jax.Array:
    """The shared scalar cache_index (all layers advance in lockstep)."""
    for leaf_path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        if any(
            getattr(p, "key", None) == "cache_index" for p in leaf_path
        ):
            return leaf
    raise ValueError("no cache_index in cache tree")
