"""ResNet-50 (v1.5) in Flax — the framework's flagship benchmark model.

This is the BASELINE.md headline workload ("MultiWorkerMirroredStrategy
ResNet-50 — v5e-16 slice"), rebuilt TPU-first: bf16 activations with f32
batch-norm statistics and f32 parameters, NHWC layout (XLA's preferred conv
layout on TPU), and shapes that tile cleanly onto the 128x128 MXU. Data
parallelism comes from jit + batch sharding (see train/steps.py), not from a
parameter-server process topology: under a sharded batch, XLA computes
batch-norm moments globally (the collectives ride ICI), which is exactly the
cross-replica sync MultiWorkerMirroredStrategy provides in the reference's
world (examples/v1alpha2/dist-mnist/dist_mnist.py:15-60 being its analog
sample).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3(stride) -> 1x1 with projection shortcut (v1.5 places the
    stride on the 3x3, matching the torchvision/MLPerf definition)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=nn.initializers.he_normal(),
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,  # compute dtype; stats/params stay f32
        )
        x = x.astype(self.dtype)
        x = conv(self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)])(x)
        x = norm()(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(
                    filters=self.width * 2**i, strides=strides, conv=conv, norm=norm
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Classifier head in f32 for a stable softmax.
        x = nn.Dense(
            self.num_classes,
            dtype=jnp.float32,
            kernel_init=nn.initializers.zeros_init(),
        )(x.astype(jnp.float32))
        return x


def resnet50(num_classes: int = 1000, dtype: Any = jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, dtype=dtype)


def resnet18(num_classes: int = 1000, dtype: Any = jnp.bfloat16) -> ResNet:
    """Smaller variant for tests/CI (still bottleneck blocks for simplicity)."""
    return ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes, dtype=dtype)
