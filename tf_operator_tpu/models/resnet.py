"""ResNet-50 (v1.5) in Flax — the framework's flagship benchmark model.

This is the BASELINE.md headline workload ("MultiWorkerMirroredStrategy
ResNet-50 — v5e-16 slice"), rebuilt TPU-first: bf16 activations with f32
batch-norm statistics and f32 parameters, NHWC layout (XLA's preferred conv
layout on TPU), and shapes that tile cleanly onto the 128x128 MXU. Data
parallelism comes from jit + batch sharding (see train/steps.py), not from a
parameter-server process topology: under a sharded batch, XLA computes
batch-norm moments globally (the collectives ride ICI), which is exactly the
cross-replica sync MultiWorkerMirroredStrategy provides in the reference's
world (examples/v1alpha2/dist-mnist/dist_mnist.py:15-60 being its analog
sample).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

ModuleDef = Any


def space_to_depth(x, block: int = 2):
    """NHWC space-to-depth: [B,H,W,C] -> [B,H/b,W/b,b*b*C].

    Channel order of the output is (dr, dc, c) flattened — the order
    ``stem_kernel_to_s2d`` assumes when embedding a 7x7 stem kernel.
    """
    b, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(f"spatial dims {(h, w)} not divisible by {block}")
    x = x.reshape(b, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, h // block, w // block, block * block * c
    )


def stem_kernel_to_s2d(k7: np.ndarray) -> np.ndarray:
    """Embed a 7x7xCxF stride-2 stem kernel into the equivalent 4x4x(4C)xF
    kernel over space-to-depth(2) input (stride 1, padding (2,1)).

    The 7x7 stride-2 receptive field of output pixel i spans input pixels
    [2i-3, 2i+3], i.e. 2x2 blocks i-2..i+1 — four blocks, stride one block.
    Input-pixel offset kr maps to block row (kr-3)//2 + 2 and within-block
    row (kr-3) % 2; taps landing in the zero-padding region read zeros on
    both paths, so the conv outputs are bit-identical in exact arithmetic.
    This is the MLPerf-era stem rewrite: the direct 7x7 conv puts C=3 input
    channels on the MXU's 128-lane reduction axis (2% utilization); the
    s2d form reduces over 4x4x12=192 taps instead of 7x7x3=147 with full
    lanes. Training uses the 4x4x12 kernel directly (a strict superset of
    the original function class); this embedding exists so tests can prove
    the rewrite is exact.
    """
    kh, kw, c, f = k7.shape
    if (kh, kw) != (7, 7):
        raise ValueError(f"expected a 7x7 stem kernel, got {k7.shape}")
    out = np.zeros((4, 4, 4 * c, f), k7.dtype)
    for kr in range(7):
        br, dr = (kr - 3) // 2 + 2, (kr - 3) % 2
        for kc in range(7):
            bc, dc = (kc - 3) // 2 + 2, (kc - 3) % 2
            out[br, bc, (dr * 2 + dc) * c : (dr * 2 + dc + 1) * c] = k7[kr, kc]
    return out


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3(stride) -> 1x1 with projection shortcut (v1.5 places the
    stride on the 3x3, matching the torchvision/MLPerf definition)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    # "conv7": the standard 7x7/s2 stem. "s2d": same function computed as a
    # 4x4/s1 conv over space-to-depth(2) input — C=3 never touches the MXU
    # reduction lanes (see stem_kernel_to_s2d for the exactness argument).
    stem: str = "conv7"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=nn.initializers.he_normal(),
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,  # compute dtype; stats/params stay f32
        )
        if self.stem not in ("conv7", "s2d"):
            raise ValueError(f"unknown stem {self.stem!r}: use 'conv7' or 's2d'")
        x = x.astype(self.dtype)
        if self.stem == "s2d":
            x = space_to_depth(x, 2)
            x = conv(
                self.width, (4, 4), strides=(1, 1),
                padding=[(2, 1), (2, 1)], name="stem_s2d",
            )(x)
        else:
            x = conv(
                self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)]
            )(x)
        x = norm()(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(
                    filters=self.width * 2**i, strides=strides, conv=conv, norm=norm
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Classifier head in f32 for a stable softmax.
        x = nn.Dense(
            self.num_classes,
            dtype=jnp.float32,
            kernel_init=nn.initializers.zeros_init(),
        )(x.astype(jnp.float32))
        return x


def resnet50(
    num_classes: int = 1000, dtype: Any = jnp.bfloat16, stem: str = "conv7"
) -> ResNet:
    return ResNet(
        stage_sizes=(3, 4, 6, 3), num_classes=num_classes, dtype=dtype, stem=stem
    )


def resnet18(num_classes: int = 1000, dtype: Any = jnp.bfloat16) -> ResNet:
    """Smaller variant for tests/CI (still bottleneck blocks for simplicity)."""
    return ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes, dtype=dtype)
