"""tf_operator_tpu — a TPU-native training-job orchestration framework.

A ground-up rebuild of the capability surface of Kubeflow's tf-operator
(reference: /root/reference, see SURVEY.md) designed TPU-first:

- A declarative ``TPUJob`` resource: per-role replica sets
  (Chief/Worker/PS/Evaluator) where a replica set may bind a whole **TPU
  pod-slice** (accelerator type + topology, e.g. ``v5e-16``) instead of a
  per-container GPU limit.
- A reconciling controller (informer cache + expectations + claiming) that
  turns the resource into gang-scheduled per-host pods and rendezvous
  services, injects the cluster-topology contract (``TF_CONFIG`` plus
  ``TPU_WORKER_HOSTNAMES`` / ``TPU_WORKER_ID`` / coordinator env), and rolls
  pod states up into condition-based job status — with restart/exit-code
  policy applied at *slice* granularity (one bad host restarts the slice).
- A JAX/Flax training stack (``models/``, ``parallel/``, ``ops/``) that
  consumes the injected topology: SPMD over ``jax.sharding.Mesh`` with
  dp/tp/sp axes, ring attention for long context, bf16 MXU-friendly kernels.

Subpackages map to the reference's layer map (SURVEY.md §1) — see each
module's docstring for the file:line parity citations.
"""

from tf_operator_tpu.version import VERSION

__version__ = VERSION
