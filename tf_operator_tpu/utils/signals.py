"""Signal → stop-event wiring for long-running processes.

Parity: pkg/util/signals/signal.go:29-43 — first SIGTERM/SIGINT trips the
stop event for graceful shutdown; a second one hard-exits.
"""

from __future__ import annotations

import os
import signal
import threading

_installed = False


def setup_signal_handler() -> threading.Event:
    """Install once; returns the stop event. Second signal exits(1) hard."""
    global _installed
    stop = threading.Event()

    def _handler(signum: int, frame: object) -> None:
        if stop.is_set():
            os._exit(1)
        stop.set()

    if not _installed and threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGTERM, _handler)
        signal.signal(signal.SIGINT, _handler)
        _installed = True
    return stop
