"""Wire-format timestamp parsing shared across the framework.

One parser for every RFC3339 timestamp that crosses a process boundary:
K8s metav1.Time fields (always "%Y-%m-%dT%H:%M:%SZ" on the wire) and
ExecCredential expirationTimestamp (may carry fractional seconds or a
numeric UTC offset). Centralized so timestamp-handling fixes land once
(TTL expiry in the controller and token expiry in kubeclient both ride
this).
"""

from __future__ import annotations

import calendar
import datetime
import time


def parse_rfc3339(ts: str) -> float | None:
    """RFC3339 timestamp → epoch seconds; None when unparseable.

    UTC-safe: parsing goes through timezone-aware datetimes (or
    calendar.timegm in the fallback), never time.mktime — mktime's DST
    guessing would shift results by an hour in DST timezones.
    """
    base = ts.strip()
    if base.endswith(("Z", "z")):
        base = base[:-1] + "+00:00"
    try:
        dt = datetime.datetime.fromisoformat(base)
    except ValueError:
        # Very old or odd producers (e.g. no offset at all): take the
        # leading seconds-resolution prefix as UTC.
        try:
            return calendar.timegm(time.strptime(ts[:19], "%Y-%m-%dT%H:%M:%S"))
        except ValueError:
            return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt.timestamp()
