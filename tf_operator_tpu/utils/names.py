"""Deterministic resource naming + DNS-safe random suffixes.

Parity: pkg/controller.v2/jobcontroller/jobcontroller_util.go:24-27
(GenGeneralName = "{job}-{type}-{index}") and pkg/util/util.go:59-75
(RandString). Stable indexed names are load-bearing: TPU_WORKER_HOSTNAMES
ordering across restarts derives from them (SURVEY.md §7 "rendezvous
correctness").
"""

from __future__ import annotations

import random
import re
import string

_DNS1035 = string.ascii_lowercase + string.digits
_LABEL_SAFE = re.compile(r"[^a-z0-9\-.]")


def rand_string(n: int) -> str:
    """DNS-label-safe random suffix (util.go:59-75 analog)."""
    return "".join(random.choice(_DNS1035) for _ in range(n))


def sanitize_dns(name: str) -> str:
    """Lowercase and strip characters not allowed in DNS labels."""
    return _LABEL_SAFE.sub("-", name.lower()).strip("-")


def gen_name(job_name: str, replica_type: str, index: int) -> str:
    """Pod/Service name for (job, type, index): "{job}-{type}-{index}"."""
    return f"{sanitize_dns(job_name)}-{replica_type.lower()}-{index}"
