"""Container exit-code → retryability policy.

Parity: pkg/util/train/train_util.go:18-53. The contract:

- 0: success.
- 1-127 ("permanent"): app-level errors — misconfigured job, import error,
  permission denied (1, 2, 126, 127, 128, and SIGSEGV's 139 enumerated in the
  reference). Retrying cannot help; the replica is failed for good.
- 130 (SIGINT), 137 (SIGKILL), 143 (SIGTERM): external interruption — node
  drain, preemption, OOM-killer at node scope. Retryable.
- 138 (128+SIGUSR1): reserved as *user-defined retryable* — training code can
  kill itself with SIGUSR1 to request a restart (e.g. on a TPU health-check
  failure) without the operator second-guessing it. The fleet-health layer
  (tf_operator_tpu/health/) additionally attributes 138 exits back to the
  cells the slice ran on and cordons them.
- >128 otherwise: died by signal; treated as retryable infrastructure noise —
  except the enumerated app-bug signals (_PERMANENT_SIGNAL_EXITS): 139
  (SIGSEGV) and 134 (SIGABRT — XLA/runtime aborts), which retrying cannot fix.

TPU addendum: on a multi-host slice a retryable exit of ONE host restarts the
WHOLE slice (ICI state is not recoverable piecemeal) — that logic lives in the
pod reconciler; this module only classifies codes.
"""

from __future__ import annotations

SUCCESS = 0
SIGUSR1_EXIT = 138  # 128 + SIGUSR1: user-requested retry

_RETRYABLE = frozenset({130, 137, 138, 143})

# Death-by-signal exits that are APP bugs, not infrastructure noise, so a
# restart cannot help: 134 (128+SIGABRT — XLA/runtime aborts, assertion
# failures, glibc heap corruption land here) and 139 (128+SIGSEGV).
_PERMANENT_SIGNAL_EXITS = frozenset({134, 139})


def is_success(exit_code: int) -> bool:
    return exit_code == SUCCESS


def is_retryable(exit_code: int) -> bool:
    """True when a restart may help (signal-based interruptions + SIGUSR1)."""
    if exit_code in _RETRYABLE:
        return True
    # Other >128 codes are deaths-by-signal we didn't enumerate; the reference
    # treats unknown signals as retryable infrastructure failures — except
    # the enumerated app-bug signals above.
    return exit_code > 128 and exit_code not in _PERMANENT_SIGNAL_EXITS


def is_permanent(exit_code: int) -> bool:
    return exit_code != SUCCESS and not is_retryable(exit_code)
