"""Container exit-code → retryability policy.

Parity: pkg/util/train/train_util.go:18-53. The contract:

- 0: success.
- 1-127 ("permanent"): app-level errors — misconfigured job, import error,
  permission denied (1, 2, 126, 127, 128, and SIGSEGV's 139 enumerated in the
  reference). Retrying cannot help; the replica is failed for good.
- 130 (SIGINT), 137 (SIGKILL), 143 (SIGTERM): external interruption — node
  drain, preemption, OOM-killer at node scope. Retryable.
- 138 (128+SIGUSR1): reserved as *user-defined retryable* — training code can
  kill itself with SIGUSR1 to request a restart (e.g. on a TPU health-check
  failure) without the operator second-guessing it.
- >128 otherwise: died by signal; treated as retryable infrastructure noise.

TPU addendum: on a multi-host slice a retryable exit of ONE host restarts the
WHOLE slice (ICI state is not recoverable piecemeal) — that logic lives in the
pod reconciler; this module only classifies codes.
"""

from __future__ import annotations

SUCCESS = 0
SIGUSR1_EXIT = 138  # 128 + SIGUSR1: user-requested retry

_RETRYABLE = frozenset({130, 137, 138, 143})


def is_success(exit_code: int) -> bool:
    return exit_code == SUCCESS


def is_retryable(exit_code: int) -> bool:
    """True when a restart may help (signal-based interruptions + SIGUSR1)."""
    if exit_code in _RETRYABLE:
        return True
    # Other >128 codes are deaths-by-signal we didn't enumerate; the reference
    # treats unknown signals as retryable infrastructure failures.
    return exit_code > 128 and exit_code not in (139,)  # 139 = SIGSEGV: app bug


def is_permanent(exit_code: int) -> bool:
    return exit_code != SUCCESS and not is_retryable(exit_code)
