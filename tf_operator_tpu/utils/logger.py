"""Structured logging keyed by job / replica / pod.

Parity: pkg/logger/logger.go:26-80 — logrus Entry factories that stamp
job/replica identity onto every line. Here: stdlib logging with a JSON or
key=value formatter and LoggerAdapter-based field binding.
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Any, MutableMapping

_ROOT = "tpuflow"


class _StructuredFormatter(logging.Formatter):
    def __init__(self, as_json: bool) -> None:
        super().__init__()
        self.as_json = as_json

    def format(self, record: logging.LogRecord) -> str:
        fields: dict[str, Any] = {
            "time": self.formatTime(record, "%Y-%m-%dT%H:%M:%S"),
            "level": record.levelname.lower(),
            "msg": record.getMessage(),
        }
        fields.update(getattr(record, "structured_fields", {}))
        if record.exc_info:
            fields["exc"] = self.formatException(record.exc_info)
        if self.as_json:
            return json.dumps(fields, default=str)
        extras = " ".join(
            f"{k}={v}" for k, v in fields.items() if k not in ("time", "level", "msg")
        )
        return f'{fields["time"]} {fields["level"]:7s} {fields["msg"]}' + (
            f"  {extras}" if extras else ""
        )


class _FieldsAdapter(logging.LoggerAdapter):
    def process(
        self, msg: str, kwargs: MutableMapping[str, Any]
    ) -> tuple[str, MutableMapping[str, Any]]:
        extra = kwargs.setdefault("extra", {})
        merged = dict(self.extra or {})
        merged.update(extra.get("structured_fields", {}))
        extra["structured_fields"] = merged
        return msg, kwargs


def configure(json_format: bool = False, level: int = logging.INFO) -> None:
    """One-time root configuration (--json-log-format flag analog)."""
    root = logging.getLogger(_ROOT)
    root.setLevel(level)
    root.handlers.clear()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_StructuredFormatter(json_format))
    root.addHandler(handler)
    root.propagate = False


def base() -> logging.Logger:
    logger = logging.getLogger(_ROOT)
    if not logger.handlers:
        configure()
    return logger


def with_fields(**fields: Any) -> logging.LoggerAdapter:
    return _FieldsAdapter(base(), fields)


def for_job(namespace: str, name: str) -> logging.LoggerAdapter:
    """LoggerForJob analog (logger.go:26-38)."""
    return with_fields(job=f"{namespace}.{name}")


def for_replica(namespace: str, name: str, rtype: str) -> logging.LoggerAdapter:
    """LoggerForReplica analog."""
    return with_fields(job=f"{namespace}.{name}", replica_type=rtype)


def for_key(key: str) -> logging.LoggerAdapter:
    """LoggerForKey analog (workqueue keys "ns/name")."""
    return with_fields(job=key)
