"""Test fixture factories.

Parity: pkg/util/testutil/ — TFJob factories (tfjob.go:26-104), pod/service
lists by phase pushed into informer caches (pod.go:57-92, service.go:47-62),
condition assertions (util.go:64-93). Used by the tier-2 controller tests and
available to downstream users for their own operator tests.
"""

from __future__ import annotations

from typing import Any

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.defaults import set_defaults
from tf_operator_tpu.api.helpers import replica_labels
from tf_operator_tpu.api.types import JobConditionType, TPUJob
from tf_operator_tpu.controller import status as status_engine
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ClusterClient
from tf_operator_tpu.utils import names

TEST_IMAGE = "test-image:latest"


def pod_template(image: str = TEST_IMAGE, **container_extra: Any) -> dict[str, Any]:
    container = {"name": constants.DEFAULT_CONTAINER_NAME, "image": image}
    container.update(container_extra)
    return {"spec": {"containers": [container]}}


def new_tpujob(
    name: str = "test-job",
    namespace: str = "default",
    worker: int | None = None,
    ps: int | None = None,
    chief: bool = False,
    evaluator: bool = False,
    tpu_accelerator: str | None = None,
    num_slices: int = 1,
    restart_policy: str | None = None,
    clean_pod_policy: str | None = None,
    ttl: int | None = None,
    max_restarts: int | None = None,
    defaulted: bool = True,
) -> TPUJob:
    replica_specs: dict[str, Any] = {}
    if worker is not None or tpu_accelerator:
        spec: dict[str, Any] = {"template": pod_template()}
        if worker is not None:
            spec["replicas"] = worker
        if tpu_accelerator:
            spec["tpu"] = {"acceleratorType": tpu_accelerator, "numSlices": num_slices}
            spec.pop("replicas", None)
        if restart_policy:
            spec["restartPolicy"] = restart_policy
        replica_specs["Worker"] = spec
    if ps is not None:
        replica_specs["PS"] = {"replicas": ps, "template": pod_template()}
    if chief:
        replica_specs["Chief"] = {"replicas": 1, "template": pod_template()}
        if restart_policy:
            replica_specs["Chief"]["restartPolicy"] = restart_policy
    if evaluator:
        replica_specs["Evaluator"] = {"replicas": 1, "template": pod_template()}

    spec_dict: dict[str, Any] = {"replicaSpecs": replica_specs}
    if clean_pod_policy:
        spec_dict["cleanPodPolicy"] = clean_pod_policy
    if ttl is not None:
        spec_dict["ttlSecondsAfterFinished"] = ttl
    if max_restarts is not None:
        spec_dict["maxRestarts"] = max_restarts

    job = TPUJob.from_dict(
        {
            "apiVersion": constants.API_VERSION,
            "kind": constants.KIND,
            "metadata": {"name": name, "namespace": namespace, "uid": f"uid-{name}"},
            "spec": spec_dict,
        }
    )
    if defaulted:
        set_defaults(job)
    return job


def new_pod_for_job(
    job: TPUJob,
    rtype: str,
    index: int,
    phase: str = objects.RUNNING,
    exit_code: int | None = None,
) -> dict[str, Any]:
    """A pod fixture as the controller would have created it."""
    pod = objects.new_pod(
        name=names.gen_name(job.metadata.name, rtype, index),
        namespace=job.metadata.namespace,
        labels=replica_labels(job.metadata.name, rtype, index),
        containers=[{"name": constants.DEFAULT_CONTAINER_NAME, "image": TEST_IMAGE}],
        owner_references=[
            {
                "apiVersion": constants.API_VERSION,
                "kind": constants.KIND,
                "name": job.metadata.name,
                "uid": job.metadata.uid,
                "controller": True,
            }
        ],
    )
    objects.set_pod_phase(pod, phase)
    if exit_code is not None:
        objects.set_container_terminated(
            pod, constants.DEFAULT_CONTAINER_NAME, exit_code
        )
    return pod


def seed_pods(
    client: ClusterClient,
    job: TPUJob,
    rtype: str,
    count: int,
    phase: str = objects.RUNNING,
    start_index: int = 0,
    exit_code: int | None = None,
) -> list[dict[str, Any]]:
    """Push `count` pods at `phase` into the cluster (the seeded-indexer
    pattern of tfcontroller_test.go)."""
    created = []
    for i in range(start_index, start_index + count):
        created.append(
            client.create(objects.PODS, new_pod_for_job(job, rtype, i, phase, exit_code))
        )
    return created


def seed_services(
    client: ClusterClient, job: TPUJob, rtype: str, count: int
) -> list[dict[str, Any]]:
    created = []
    for i in range(count):
        svc = objects.new_service(
            name=names.gen_name(job.metadata.name, rtype, i),
            namespace=job.metadata.namespace,
            labels=replica_labels(job.metadata.name, rtype, i),
            selector=replica_labels(job.metadata.name, rtype, i),
            owner_references=[
                {
                    "apiVersion": constants.API_VERSION,
                    "kind": constants.KIND,
                    "name": job.metadata.name,
                    "uid": job.metadata.uid,
                    "controller": True,
                }
            ],
        )
        created.append(client.create(objects.SERVICES, svc))
    return created


def assert_condition(job: TPUJob, ctype: str, present: bool = True) -> None:
    has = status_engine.has_condition(job.status, ctype)
    assert has == present, (
        f"expected condition {ctype} present={present}; conditions="
        f"{[(c.type, c.status) for c in job.status.conditions]}"
    )


def condition_types(job: TPUJob) -> list[str]:
    return [c.type for c in job.status.conditions if c.status == "True"]


ALL_CONDITIONS = JobConditionType
