"""Per-replica-type pod reconciliation.

Parity: pkg/controller.v2/tfcontroller/controller_pod.go — index-bucketed pod
slices, expectation-guarded creation, TF_CONFIG injection at create time,
RestartPolicy→pod-restartPolicy mapping (ExitCode→Never), and the ExitCode
retry path (delete failed-but-retryable pods so they are recreated).

TPU-native extension: **slice-granular restarts**. For a replica set bound to
a multi-host TPU slice, ICI state is not recoverable piecemeal — when one
host pod needs a restart, every pod of that slice group is deleted and
recreated together (SURVEY.md §7 "failure semantics"). Restarts are counted
on the job status and capped by spec.maxRestarts.
"""

from __future__ import annotations

from typing import Any

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.helpers import replica_labels
from tf_operator_tpu.api.types import ReplicaSpec, RestartPolicy, TPUJob
from tf_operator_tpu.ckpt import protocol as ckpt_protocol
from tf_operator_tpu.controller import cluster_spec
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.metrics import CKPT_RESUME_INJECTIONS_TOTAL
from tf_operator_tpu.topology import slices as topo_slices
from tf_operator_tpu.utils import exit_codes, names


def get_pod_slices(
    pods: list[dict[str, Any]], replicas: int
) -> tuple[list[list[dict[str, Any]]], list[dict[str, Any]]]:
    """Bucket pods by their replica-index label (controller_pod.go:109-128).

    Returns (buckets[0..replicas-1], out_of_range) — out-of-range pods are
    scale-down leftovers the caller deletes.
    """
    buckets: list[list[dict[str, Any]]] = [[] for _ in range(replicas)]
    out_of_range: list[dict[str, Any]] = []
    for pod in pods:
        idx_str = objects.labels_of(pod).get(constants.LABEL_REPLICA_INDEX)
        try:
            idx = int(idx_str)  # type: ignore[arg-type]
        except (TypeError, ValueError):
            continue
        if 0 <= idx < replicas:
            buckets[idx].append(pod)
        else:
            out_of_range.append(pod)
    return buckets, out_of_range


def map_restart_policy(replica_policy: str | None, is_multi_host_slice: bool) -> str:
    """Replica RestartPolicy → pod spec.restartPolicy.

    ExitCode maps to Never (the controller drives retries by deleting pods,
    controller_pod.go:216). Multi-host slice pods are always Never: an
    in-place container restart of one host cannot rejoin the ICI rendezvous,
    so the controller must own the restart at slice granularity.
    """
    if is_multi_host_slice:
        return "Never"
    if replica_policy == RestartPolicy.EXIT_CODE:
        return "Never"
    return replica_policy or "Never"


class PodReconciler:
    """Mixin over JobController providing reconcile_pods. Host controller
    supplies: pod_control, expectations, recorder, job_key/expectation_key."""

    def _resume_env(self, job: TPUJob) -> dict[str, str]:
        """TPU_RESUME_STEP/TPU_CKPT_DIR from the checkpoint registry, when
        the host controller carries one (duck-typed like report_pod_exit)."""
        registry = getattr(self, "ckpt", None)
        if registry is None:
            return {}
        return registry.resume_env(job)

    def build_pod(
        self, job: TPUJob, rtype: str, spec: ReplicaSpec, index: int
    ) -> dict[str, Any]:
        """Materialize the pod for (job, type, index): labels, owner ref,
        topology env, restart policy, TPU node placement."""
        template = cluster_spec.set_cluster_spec(spec.template, job, rtype, index)
        tmpl_spec = template.setdefault("spec", {})

        is_slice = bool(spec.tpu and spec.tpu.accelerator_type)
        multi_host = False
        if is_slice:
            topo = topo_slices.resolve(spec.tpu.accelerator_type, spec.tpu.topology)
            multi_host = topo.multi_host
            placement = cluster_spec.node_placement(job, rtype)
            node_selector = tmpl_spec.setdefault("nodeSelector", {})
            for k, v in placement.get("nodeSelector", {}).items():
                node_selector.setdefault(k, v)
            for c in tmpl_spec.get("containers", []):
                if c.get("name") == constants.DEFAULT_CONTAINER_NAME:
                    limits = c.setdefault("resources", {}).setdefault("limits", {})
                    limits.setdefault(
                        "google.com/tpu", placement["tpuResources"]["google.com/tpu"]
                    )

        tmpl_spec["restartPolicy"] = map_restart_policy(spec.restart_policy, multi_host)
        if job.spec.scheduling.scheduler_name:
            tmpl_spec.setdefault("schedulerName", job.spec.scheduling.scheduler_name)
        if job.spec.scheduling.priority_class:
            tmpl_spec.setdefault("priorityClassName", job.spec.scheduling.priority_class)
        # Gang admission: pods are born gated and released as one unit when
        # the whole gang is admitted (scheduler/core.py). Recreated pods
        # (slice restarts) re-gate and re-release the same way. Appended,
        # not assigned: a template's own gates (external admission control)
        # must survive — release_gang lifts only the gang gate.
        gates = self.scheduling_gates(job)
        if gates:
            existing = tmpl_spec.get("schedulingGates") or []
            present = {g.get("name") for g in existing}
            tmpl_spec["schedulingGates"] = list(existing) + [
                dict(g) for g in gates if g["name"] not in present
            ]

        # Resume injection (ckpt/registry.py): replacement pods of a job
        # with a durable checkpoint record learn the last acked step and
        # directory, so a preempted/migrated gang resumes where it acked
        # instead of step 0. Injected like the topology contract — into
        # the default container only, never overriding template-set values.
        resume = self._resume_env(job)
        if resume:
            for c in tmpl_spec.get("containers", []):
                if c.get("name") != constants.DEFAULT_CONTAINER_NAME:
                    continue
                env = c.setdefault("env", [])
                present = {e.get("name") for e in env}
                injected = False
                for k, v in resume.items():
                    if k not in present:
                        env.append({"name": k, "value": v})
                        injected = True
                if injected and ckpt_protocol.ENV_RESUME_STEP in resume:
                    CKPT_RESUME_INJECTIONS_TOTAL.inc()

        labels = replica_labels(job.metadata.name, rtype, index)
        meta = template.setdefault("metadata", {})
        meta.setdefault("labels", {}).update(labels)

        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": names.gen_name(job.metadata.name, rtype, index),
                "namespace": job.metadata.namespace,
                "labels": meta["labels"],
                "annotations": dict(meta.get("annotations", {})),
            },
            "spec": tmpl_spec,
            "status": {"phase": objects.PENDING},
        }
        return pod

    def reconcile_pods(
        self,
        job: TPUJob,
        rtype: str,
        spec: ReplicaSpec,
        pods: list[dict[str, Any]],
    ) -> dict[str, Any]:
        """Drive this replica type's pods toward spec.

        Returns a summary: {"created": n, "deleted": n, "restarts": n,
        "permanent_failure": bool} the caller folds into status.
        """
        job_key = self.job_key(job.metadata.namespace, job.metadata.name)
        exp_key = self.expectation_key(job_key, rtype, "pods")
        replicas = spec.replicas or 0
        rtype_pods = [
            p
            for p in pods
            if objects.labels_of(p).get(constants.LABEL_REPLICA_TYPE) == rtype.lower()
        ]
        buckets, out_of_range = get_pod_slices(rtype_pods, replicas)
        # "restarts" increments the restartCount counter (idempotent: only
        # landed trigger deletes); "restarting" reports that failed pods
        # were handled by a restart this sync — the status engine keys
        # Restarting-vs-Failed on it, and it must stay True even when the
        # trigger pod was already gone (stale-cache replay), else the
        # snapshot's failed count would read as permanent.
        summary = {"created": 0, "deleted": 0, "restarts": 0,
                   "restarting": False, "permanent_failure": False}

        # Scale-down leftovers.
        for pod in out_of_range:
            if self._delete_pod_expected(job, exp_key, objects.name_of(pod)):
                summary["deleted"] += 1

        # Slice grouping for restart granularity.
        group_size = 1
        if spec.tpu and spec.tpu.accelerator_type:
            topo = topo_slices.resolve(spec.tpu.accelerator_type, spec.tpu.topology)
            group_size = topo.num_hosts

        to_create: list[int] = []
        restart_indices: set[int] = set()
        permanent_indices: set[int] = set()

        for index, bucket in enumerate(buckets):
            if not bucket:
                to_create.append(index)
                continue
            # Duplicates: keep the oldest, delete the rest (defensive; the
            # expectations machinery normally prevents this).
            if len(bucket) > 1:
                bucket.sort(key=lambda p: objects.meta(p).get("creationTimestamp", ""))
                for dup in bucket[1:]:
                    if self._delete_pod_expected(job, exp_key, objects.name_of(dup)):
                        summary["deleted"] += 1
            pod = bucket[0]
            if objects.pod_phase(pod) != objects.FAILED:
                continue
            # Fleet-health cell attribution: every failed exit is reported
            # back to the cells the gang occupies (the monitor dedupes per
            # pod incarnation and scores only health-relevant codes —
            # exit-138 reports strongly, retryable churn weakly).
            report = getattr(self, "report_pod_exit", None)
            if report is not None:
                report(
                    job,
                    pod,
                    objects.terminated_exit_code(
                        pod, constants.DEFAULT_CONTAINER_NAME
                    ),
                )
            policy = spec.restart_policy
            if policy == RestartPolicy.EXIT_CODE:
                code = objects.terminated_exit_code(
                    pod, constants.DEFAULT_CONTAINER_NAME
                )
                reason = objects.terminated_reason(
                    pod, constants.DEFAULT_CONTAINER_NAME
                )
                # Container-scope OOM is permanent even though its exit code
                # (137) reads as a retryable signal: the workload's memory
                # demand will not change on retry (reference
                # training.go:207-220, OOMKilled-is-permanent).
                if reason == "OOMKilled":
                    permanent_indices.add(index)
                elif code is not None and exit_codes.is_retryable(code):
                    restart_indices.add(index)
                else:
                    permanent_indices.add(index)
            elif policy in (RestartPolicy.ON_FAILURE, RestartPolicy.ALWAYS):
                restart_indices.add(index)
            else:  # Never
                permanent_indices.add(index)

        # The pods that TRIGGERED a restart (failed + retryable), before
        # slice expansion adds healthy collateral members: a restart event
        # is counted below only when a trigger's delete actually lands.
        trigger_indices = set(restart_indices)

        # Slice-granular expansion: one bad host restarts its whole slice
        # group; a permanent failure on any host poisons the whole group.
        if group_size > 1:
            expanded: set[int] = set()
            for idx in restart_indices:
                g = idx // group_size
                if any(
                    (g * group_size + j) in permanent_indices for j in range(group_size)
                ):
                    continue  # group is permanently failed; do not thrash
                expanded.update(g * group_size + j for j in range(group_size))
            # Never restart a pod that is itself permanently failed.
            restart_indices = expanded - permanent_indices
            # Only delete group members that still have pods (missing ones
            # will be recreated by the create path).
            restart_indices = {
                i for i in restart_indices if i < replicas and buckets[i]
            }

        if permanent_indices:
            summary["permanent_failure"] = True

        # Budget check: each restart *event* (per group or per pod) counts 1.
        if restart_indices:
            groups = {i // group_size for i in restart_indices}
            budget_left = True
            if job.spec.max_restarts is not None:
                budget_left = (
                    job.status.restart_count + len(groups) <= job.spec.max_restarts
                )
            if budget_left:
                summary["restarting"] = True
                # Count one restart per group in which at least one delete
                # actually removed a live object: a stale cache can replay
                # an already-handled failed pod (informer ghost race —
                # suppressed at the source by uid tracking, but the
                # counter must stay exact against any stale-cache path);
                # a fully-ghost group's deletes all return NotFound and
                # must not re-increment restartCount.
                landed_groups: set[int] = set()
                for idx in sorted(restart_indices):
                    pod = buckets[idx][0]
                    if self._delete_pod_expected(job, exp_key, objects.name_of(pod)):
                        summary["deleted"] += 1
                        landed_groups.add(idx // group_size)
                summary["restarts"] = len(landed_groups)
            else:
                # Budget exhausted. Before declaring a terminal failure,
                # confirm a trigger pod still exists server-side WITH the
                # observed uid: a stale-cache replay of an already-handled
                # failure must not permanently fail a healthy job.
                for idx in sorted(trigger_indices):
                    if idx // group_size not in groups or not buckets[idx]:
                        continue
                    cached = buckets[idx][0]
                    if self._pod_live(job, cached):
                        summary["permanent_failure"] = True
                        break

        # Create missing pods (expectation first, then create — the order the
        # reference is careful about, controller_pod.go:131-191).
        if to_create:
            self.expectations.raise_expectations(exp_key, len(to_create), 0)
            for n, index in enumerate(to_create):
                try:
                    pod = self.build_pod(job, rtype, spec, index)
                    self.pod_control.create_pod(
                        job.metadata.namespace,
                        pod,
                        job.to_dict(),
                        self._controller_ref(job),
                    )
                    summary["created"] += 1
                except Exception:
                    # Roll back expectations for this create AND every
                    # not-yet-attempted one, else the job wedges until the
                    # expectation TTL (the aborted creates will never produce
                    # informer events to decrement them).
                    for _ in range(len(to_create) - n):
                        self.expectations.creation_observed(exp_key)
                    raise
        return summary

    def _pod_live(self, job: TPUJob, cached: dict) -> bool:
        """Whether the CACHED pod incarnation still exists server-side
        (same name AND uid). Used only on rare paths (budget exhaustion)
        where acting on a stale observation would be terminal."""
        from tf_operator_tpu.runtime.client import NotFound

        try:
            live = self.client.get(
                objects.PODS, job.metadata.namespace, objects.name_of(cached)
            )
        except NotFound:
            return False
        cached_uid = objects.uid_of(cached)
        return not cached_uid or objects.uid_of(live) == cached_uid

    def _delete_pod_expected(self, job: TPUJob, exp_key: str, name: str) -> bool:
        """Delete with a deletion expectation that is rolled back on failure.

        Returns True only when the delete REMOVED a live object. A pod
        already gone (NotFound — deleted externally, or a stale-cache
        replay of an already-handled pod) returns False: reconciliation
        treats that as done, and the restart counter depends on the
        distinction to stay exact (landed_groups above). The NotFound
        path must also release the expectation raised here, because the
        pod's DELETED event fired before we raised it.
        """
        from tf_operator_tpu.runtime.client import NotFound

        self.expectations.raise_expectations(exp_key, 0, 1)
        try:
            self.pod_control.delete_pod(job.metadata.namespace, name, job.to_dict())
            return True
        except NotFound:
            self.expectations.deletion_observed(exp_key)
            return False
        except Exception:
            self.expectations.deletion_observed(exp_key)
            raise
