"""Per-replica-type headless-service reconciliation.

Parity: pkg/controller.v2/tfcontroller/controller_service.go:37-154 — one
headless service per (replica type, index), selecting exactly that replica's
pod, exposing the named rendezvous port. Headless services give each replica
a stable DNS identity ({job}-{type}-{index}), which is what makes
TPU_WORKER_HOSTNAMES stable across pod restarts.

Unlike the reference (whose update/delete service handlers are TODO stubs,
controller_service.go:224-232), scale-down and duplicate handling are
implemented here.
"""

from __future__ import annotations

from typing import Any

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.helpers import replica_labels
from tf_operator_tpu.api.types import ReplicaSpec, TPUJob
from tf_operator_tpu.controller import cluster_spec
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.utils import names


def get_service_slices(
    services: list[dict[str, Any]], replicas: int
) -> tuple[list[list[dict[str, Any]]], list[dict[str, Any]]]:
    buckets: list[list[dict[str, Any]]] = [[] for _ in range(replicas)]
    out_of_range: list[dict[str, Any]] = []
    for svc in services:
        idx_str = objects.labels_of(svc).get(constants.LABEL_REPLICA_INDEX)
        try:
            idx = int(idx_str)  # type: ignore[arg-type]
        except (TypeError, ValueError):
            continue
        if 0 <= idx < replicas:
            buckets[idx].append(svc)
        else:
            out_of_range.append(svc)
    return buckets, out_of_range


class ServiceReconciler:
    """Mixin over JobController providing reconcile_services."""

    def build_service(
        self, job: TPUJob, rtype: str, spec: ReplicaSpec, index: int
    ) -> dict[str, Any]:
        labels = replica_labels(job.metadata.name, rtype, index)
        port = cluster_spec.get_port(job, rtype)
        return objects.new_service(
            name=names.gen_name(job.metadata.name, rtype, index),
            namespace=job.metadata.namespace,
            labels=labels,
            selector=labels,
            ports=[
                {
                    "name": constants.DEFAULT_PORT_NAME,
                    "port": port,
                    "targetPort": port,
                }
            ],
            headless=True,
        )

    def reconcile_services(
        self,
        job: TPUJob,
        rtype: str,
        spec: ReplicaSpec,
        services: list[dict[str, Any]],
    ) -> dict[str, Any]:
        job_key = self.job_key(job.metadata.namespace, job.metadata.name)
        exp_key = self.expectation_key(job_key, rtype, "services")
        replicas = spec.replicas or 0
        rtype_services = [
            s
            for s in services
            if objects.labels_of(s).get(constants.LABEL_REPLICA_TYPE) == rtype.lower()
        ]
        buckets, out_of_range = get_service_slices(rtype_services, replicas)
        summary = {"created": 0, "deleted": 0}

        for svc in out_of_range:
            if self._delete_service_expected(job, exp_key, objects.name_of(svc)):
                summary["deleted"] += 1

        to_create = []
        for index, bucket in enumerate(buckets):
            if not bucket:
                to_create.append(index)
                continue
            if len(bucket) > 1:
                bucket.sort(key=lambda s: objects.meta(s).get("creationTimestamp", ""))
                for dup in bucket[1:]:
                    if self._delete_service_expected(job, exp_key, objects.name_of(dup)):
                        summary["deleted"] += 1
            # Spec-drift repair (VERDICT #5): a service whose selector or
            # port no longer matches the desired build is a silently-broken
            # rendezvous DNS name — every TF_CONFIG/TPU_WORKER_HOSTNAMES
            # entry that resolves through it points at the wrong pod or
            # port. Recreate rather than patch: ports+selector are the
            # service's whole identity here, and delete-then-create reuses
            # the expectation machinery duplicates already exercise.
            observed = bucket[0]
            if self._service_drifted(
                observed, self.build_service(job, rtype, spec, index)
            ):
                if self._delete_service_expected(
                    job, exp_key, objects.name_of(observed)
                ):
                    summary["deleted"] += 1
                summary["repaired"] = summary.get("repaired", 0) + 1
                to_create.append(index)

        if to_create:
            self.expectations.raise_expectations(exp_key, len(to_create), 0)
            for n, index in enumerate(to_create):
                try:
                    svc = self.build_service(job, rtype, spec, index)
                    self.service_control.create_service(
                        job.metadata.namespace,
                        svc,
                        job.to_dict(),
                        self._controller_ref(job),
                    )
                    summary["created"] += 1
                except Exception:
                    # Release this and all unattempted creates (see
                    # pod_reconciler: aborted creates never produce events).
                    for _ in range(len(to_create) - n):
                        self.expectations.creation_observed(exp_key)
                    raise
        return summary

    @staticmethod
    def _service_drifted(observed: dict[str, Any], desired: dict[str, Any]) -> bool:
        """Whether the observed service's selector or ports diverge from the
        desired build. Compares only the fields this controller owns —
        cluster-assigned extras (clusterIP, ipFamilies, status) must not
        read as drift."""
        obs_spec = observed.get("spec", {}) or {}
        des_spec = desired.get("spec", {}) or {}
        if (obs_spec.get("selector") or {}) != (des_spec.get("selector") or {}):
            return True

        def _ports(spec: dict[str, Any]) -> list[tuple]:
            return sorted(
                (
                    p.get("name", ""),
                    p.get("port"),
                    p.get("targetPort", p.get("port")),
                    p.get("protocol", "TCP"),
                )
                for p in spec.get("ports", []) or []
            )

        return _ports(obs_spec) != _ports(des_spec)

    def _delete_service_expected(self, job: TPUJob, exp_key: str, name: str) -> bool:
        from tf_operator_tpu.runtime.client import NotFound

        self.expectations.raise_expectations(exp_key, 0, 1)
        try:
            self.service_control.delete_service(
                job.metadata.namespace, name, job.to_dict()
            )
            return True
        except NotFound:
            self.expectations.deletion_observed(exp_key)
            return False
        except Exception:
            self.expectations.deletion_observed(exp_key)
            raise
