"""Workload-agnostic job-controller base.

Parity: pkg/controller.v2/jobcontroller/jobcontroller.go — the deliberate
architectural split SURVEY.md §1 highlights: everything generic about "a job
that owns pods and services" lives here (listers, claiming, expectations,
workqueue, gang PDB); the TPU-specific semantics (topology env, slice-granular
restarts, condition rules) live in tpujob_controller.py. A future non-TF
workload controller reuses this base unchanged, as the reference intended its
JobController to be reused by other Kubeflow operators.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.helpers import as_owner, gen_labels
from tf_operator_tpu.control.expectations import ControllerExpectations
from tf_operator_tpu.control.pod_control import PodControlInterface
from tf_operator_tpu.control.ref_manager import RefManager
from tf_operator_tpu.control.service_control import ServiceControlInterface
from tf_operator_tpu.controller.informer import Informer
from tf_operator_tpu.controller.workqueue import RateLimitingQueue
from tf_operator_tpu.runtime import events as ev
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import AlreadyExists, ClusterClient, NotFound
from tf_operator_tpu.utils import logger


@dataclass
class JobControllerConfig:
    """Parity: jobcontroller.go:48-59 (15s reconcile, gang flag)."""

    reconcile_period: float = 15.0
    informer_resync: float = 30.0
    enable_gang_scheduling: bool = True
    namespace: str | None = None  # None = all namespaces
    threadiness: int = 1


class JobController:
    """Base: owns client, informers, expectations, queue, and generic
    pod/service machinery. Subclasses implement the sync logic."""

    def __init__(
        self,
        client: ClusterClient,
        pod_control: PodControlInterface,
        service_control: ServiceControlInterface,
        recorder: ev.EventRecorder,
        config: JobControllerConfig | None = None,
    ) -> None:
        self.client = client
        self.pod_control = pod_control
        self.service_control = service_control
        self.recorder = recorder
        self.config = config or JobControllerConfig()
        self.expectations = ControllerExpectations()
        self.queue = RateLimitingQueue()
        self.pod_informer = Informer(
            client, objects.PODS, self.config.namespace, self.config.informer_resync
        )
        self.service_informer = Informer(
            client, objects.SERVICES, self.config.namespace, self.config.informer_resync
        )
        self.log = logger.base()

    # -- labels / keys -------------------------------------------------------

    @staticmethod
    def job_key(namespace: str, name: str) -> str:
        return f"{namespace}/{name}"

    @staticmethod
    def expectation_key(job_key: str, replica_type: str, kind: str) -> str:
        return f"{job_key}/{replica_type.lower()}/{kind}"

    def gen_labels(self, job_name: str) -> dict[str, str]:
        return gen_labels(job_name)

    # -- claiming (jobcontroller.go:145-193) ---------------------------------

    def _fresh_job_exists(self, job: Any) -> bool:
        """CanAdopt recheck: re-read the job and refuse adoption if it is
        gone or being deleted."""
        try:
            fresh = self.client.get(
                objects.TPUJOBS, job.metadata.namespace, job.metadata.name
            )
        except NotFound:
            return False
        return not objects.is_deleted(fresh) and (
            objects.uid_of(fresh) == job.metadata.uid
        )

    def get_pods_for_job(self, job: Any, controller_ref: dict[str, Any]) -> list[dict[str, Any]]:
        """Claimable candidates by index (owned ∪ label-matching), then
        claim by selector+ownerRef. The reference lists the whole namespace
        here; at O(jobs) concurrent jobs that scan made every sync
        O(all pods), the dominant reconcile-wave cost. The index union is
        claim-equivalent: a pod neither owned by this job nor matching its
        labels can produce no adopt/orphan action (see
        Informer.list_for_owner)."""
        candidates = self.pod_informer.list_for_owner(
            job.metadata.uid,
            namespace=job.metadata.namespace,
            label_selector=self.gen_labels(job.metadata.name),
        )
        mgr = RefManager(
            self.client,
            job.to_dict(),
            controller_ref,
            self.gen_labels(job.metadata.name),
            can_adopt=lambda: self._fresh_job_exists(job),
        )
        return mgr.claim_pods(candidates)

    def get_services_for_job(
        self, job: Any, controller_ref: dict[str, Any]
    ) -> list[dict[str, Any]]:
        candidates = self.service_informer.list_for_owner(
            job.metadata.uid,
            namespace=job.metadata.namespace,
            label_selector=self.gen_labels(job.metadata.name),
        )
        mgr = RefManager(
            self.client,
            job.to_dict(),
            controller_ref,
            self.gen_labels(job.metadata.name),
            can_adopt=lambda: self._fresh_job_exists(job),
        )
        return mgr.claim_services(candidates)

    # -- gang scheduling (jobcontroller.go:196-249) --------------------------

    def gang_pdb_name(self, job_name: str) -> str:
        return f"{job_name}-gang"

    def sync_pdb(self, job: Any, total_replicas: int) -> None:
        """Create the minAvailable=ALL disruption budget consumed by gang
        schedulers. Skipped for single-replica jobs as in the reference
        (PDB only when >= 2 replicas)."""
        if total_replicas < 2:
            return
        ns = job.metadata.namespace
        name = self.gang_pdb_name(job.metadata.name)
        try:
            existing = self.client.get(objects.PDBS, ns, name)
            # Replica count changed (scale): keep minAvailable = ALL, or the
            # gang scheduler would admit a partial slice.
            if existing.get("spec", {}).get("minAvailable") != total_replicas:
                self.client.patch_merge(
                    objects.PDBS, ns, name, {"spec": {"minAvailable": total_replicas}}
                )
            return
        except NotFound:
            pass
        pdb = objects.new_pdb(
            name,
            ns,
            min_available=total_replicas,
            selector_labels=self.gen_labels(job.metadata.name),
            owner_references=[self._controller_ref(job)],
        )
        try:
            self.client.create(objects.PDBS, pdb)
        except AlreadyExists:
            pass

    def delete_pdb(self, job: Any) -> None:
        try:
            self.client.delete(
                objects.PDBS, job.metadata.namespace, self.gang_pdb_name(job.metadata.name)
            )
        except NotFound:
            pass

    def _controller_ref(self, job: Any) -> dict[str, Any]:
        return as_owner(job)

    # -- generic pod/service event handlers ----------------------------------

    def _resolve_job_key(self, obj: dict[str, Any]) -> str | None:
        """Map an owned object back to its job's queue key via controllerRef."""
        for ref in objects.meta(obj).get("ownerReferences", []):
            if ref.get("controller") and ref.get("kind") == constants.KIND:
                return self.job_key(objects.namespace_of(obj), ref.get("name", ""))
        return None

    def _replica_type_of(self, obj: dict[str, Any]) -> str | None:
        return objects.labels_of(obj).get(constants.LABEL_REPLICA_TYPE)

    def add_pod(self, pod: dict[str, Any]) -> None:
        key = self._resolve_job_key(pod)
        if key is None:
            return
        rtype = self._replica_type_of(pod)
        if rtype:
            self.expectations.creation_observed(
                self.expectation_key(key, rtype, "pods")
            )
        self.enqueue(key)

    def update_pod(self, old: dict[str, Any], new: dict[str, Any]) -> None:
        if objects.meta(old).get("resourceVersion") == objects.meta(new).get(
            "resourceVersion"
        ):
            return
        key = self._resolve_job_key(new) or self._resolve_job_key(old)
        if key is not None:
            self.enqueue(key)

    def delete_pod(self, pod: dict[str, Any]) -> None:
        key = self._resolve_job_key(pod)
        if key is None:
            return
        rtype = self._replica_type_of(pod)
        if rtype:
            self.expectations.deletion_observed(
                self.expectation_key(key, rtype, "pods")
            )
        self.enqueue(key)

    def add_service(self, service: dict[str, Any]) -> None:
        key = self._resolve_job_key(service)
        if key is None:
            return
        rtype = self._replica_type_of(service)
        if rtype:
            self.expectations.creation_observed(
                self.expectation_key(key, rtype, "services")
            )
        self.enqueue(key)

    def update_service(self, old: dict[str, Any], new: dict[str, Any]) -> None:
        """Out-of-band service edits (port/selector drift) must re-enqueue
        the owner so reconcile_services can repair the spec — the reference
        leaves this handler a TODO stub (controller_service.go:224-228)."""
        if objects.meta(old).get("resourceVersion") == objects.meta(new).get(
            "resourceVersion"
        ):
            return
        key = self._resolve_job_key(new) or self._resolve_job_key(old)
        if key is not None:
            self.enqueue(key)

    def delete_service(self, service: dict[str, Any]) -> None:
        key = self._resolve_job_key(service)
        if key is None:
            return
        rtype = self._replica_type_of(service)
        if rtype:
            self.expectations.deletion_observed(
                self.expectation_key(key, rtype, "services")
            )
        self.enqueue(key)

    # -- queue ---------------------------------------------------------------

    def enqueue(self, key: str) -> None:
        self.queue.add(key)

    def enqueue_after(self, key: str, delay: float) -> None:
        self.queue.add_after(key, delay)
