"""Job condition state machine + replica-status counters.

Parity: pkg/controller.v2/tfcontroller/controller_status.go:42-241. The
invariants preserved:

- Conditions are exclusive where it matters: Running and Restarting never
  both True; a terminal condition (Succeeded/Failed) flips Running to False.
- Failed is sticky: once a job has Failed=True it never un-fails.
- Success = chief succeeded when a chief exists, else all workers succeeded.
- failed>0 → Restarting when the replica's restart policy allows a retry,
  else Failed.
- StartTime set once when the job first has all replicas running;
  CompletionTime set with the terminal condition.
"""

from __future__ import annotations

from tf_operator_tpu.api.types import (
    JobCondition,
    JobConditionType,
    ReplicaStatus,
    TPUJob,
    TPUJobStatus,
)
from tf_operator_tpu.runtime import objects

# Canonical reasons (controller_status.go uses tfJobCreatedReason etc.)
REASON_CREATED = "TPUJobCreated"
REASON_RUNNING = "TPUJobRunning"
REASON_RESTARTING = "TPUJobRestarting"
REASON_SUCCEEDED = "TPUJobSucceeded"
REASON_FAILED = "TPUJobFailed"
# Fleet-health reasons (health/monitor.py drives these via the controller).
REASON_SLICE_DEGRADED = "SliceHealthSuspect"
REASON_SLICE_HEALTHY = "SliceHealthy"
REASON_MIGRATING = "SliceDraining"
REASON_MIGRATED = "MigrationComplete"

REASON_CKPT_STALE = "CheckpointQuiet"
REASON_CKPT_FRESH = "CheckpointFresh"
REASON_CKPT_SKIPPED = "CheckpointGraceExpired"
REASON_CKPT_RECOVERED = "CheckpointRecovered"

TRUE = "True"
FALSE = "False"


def new_condition(
    ctype: str, reason: str, message: str, status: str = TRUE
) -> JobCondition:
    now = objects.now_iso()
    return JobCondition(
        type=ctype,
        status=status,
        reason=reason,
        message=message,
        last_update_time=now,
        last_transition_time=now,
    )


def get_condition(status: TPUJobStatus, ctype: str) -> JobCondition | None:
    for c in status.conditions:
        if c.type == ctype and c.status == TRUE:
            return c
    return None


def has_condition(status: TPUJobStatus, ctype: str) -> bool:
    return get_condition(status, ctype) is not None


def is_succeeded(status: TPUJobStatus) -> bool:
    return has_condition(status, JobConditionType.SUCCEEDED)


def is_failed(status: TPUJobStatus) -> bool:
    return has_condition(status, JobConditionType.FAILED)


def is_running(status: TPUJobStatus) -> bool:
    return has_condition(status, JobConditionType.RUNNING)


def is_finished(status: TPUJobStatus) -> bool:
    return is_succeeded(status) or is_failed(status)


def _filter_out(status: TPUJobStatus, drop_type: str) -> None:
    status.conditions = [c for c in status.conditions if c.type != drop_type]


def set_condition(status: TPUJobStatus, cond: JobCondition) -> None:
    """Insert/update a condition, enforcing exclusivity rules."""
    # Failed is sticky: nothing dethrones it except another Failed update.
    if is_failed(status) and cond.type != JobConditionType.FAILED:
        return

    if cond.type == JobConditionType.RUNNING:
        _filter_out(status, JobConditionType.RESTARTING)
    elif cond.type == JobConditionType.RESTARTING:
        _filter_out(status, JobConditionType.RUNNING)
    elif cond.type in (JobConditionType.SUCCEEDED, JobConditionType.FAILED):
        for c in status.conditions:
            if c.type in (JobConditionType.RUNNING, JobConditionType.RESTARTING) and c.status == TRUE:
                c.status = FALSE
                c.last_transition_time = objects.now_iso()

    for c in status.conditions:
        if c.type == cond.type:
            if (
                c.status == cond.status
                and c.reason == cond.reason
                and c.message == cond.message
            ):
                # Semantically identical: keep the existing timestamps.
                # Re-stamping last_update_time here made every settled
                # reconcile's status differ by one second-granularity
                # field, defeating the controller's skip-unchanged write
                # guard at 1 Hz per job (the status write emits the very
                # watch event that re-enqueues the sync).
                return
            transitioned = c.status != cond.status
            c.status = cond.status
            c.reason = cond.reason
            c.message = cond.message
            c.last_update_time = cond.last_update_time
            if transitioned:
                c.last_transition_time = cond.last_transition_time
            return
    status.conditions.append(cond)


def update_job_conditions(
    job: TPUJob, ctype: str, reason: str, message: str, status: str = TRUE
) -> None:
    set_condition(job.status, new_condition(ctype, reason, message, status))


def initialize_replica_statuses(job: TPUJob, replica_type: str) -> None:
    job.status.replica_statuses.setdefault(replica_type, ReplicaStatus())


def update_replica_statuses(job: TPUJob, replica_type: str, pod: dict) -> None:
    """Count one pod into the per-type Active/Succeeded/Failed counters
    (controller_status.go:144-153)."""
    initialize_replica_statuses(job, replica_type)
    rs = job.status.replica_statuses[replica_type]
    phase = objects.pod_phase(pod)
    if phase == objects.RUNNING:
        rs.active += 1
    elif phase == objects.SUCCEEDED:
        rs.succeeded += 1
    elif phase == objects.FAILED:
        rs.failed += 1
