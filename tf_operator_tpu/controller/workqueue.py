"""Rate-limited work queue with per-key serialization.

Parity: the client-go workqueue the reference builds its hot loop on
(pkg/controller/controller.go:77-95,122-126): items are deduplicated, a key
being processed is never handed to a second worker (re-queued on `done` if it
went dirty meanwhile), failed items come back with per-item exponential
backoff (5ms → 1000s) under an overall token bucket (10 qps, burst 100).
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Hashable


class ItemExponentialBackoff:
    """Per-item exponential failure backoff (5ms base, 1000s cap)."""

    def __init__(self, base: float = 0.005, cap: float = 1000.0) -> None:
        self.base = base
        self.cap = cap
        self._failures: dict[Hashable, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Hashable) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return min(self.base * (2**n), self.cap)

    def forget(self, item: Hashable) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Hashable) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class TokenBucket:
    """Overall qps limiter (10 qps / burst 100 by default)."""

    def __init__(self, qps: float = 10.0, burst: int = 100) -> None:
        self.qps = qps
        self.burst = burst
        self._tokens = float(burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def delay(self) -> float:
        """Seconds until a token is available; consumes one (possibly future) token."""
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
            self._last = now
            self._tokens -= 1.0
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self.qps


class RateLimitingQueue:
    """Deduplicating, delayable, rate-limited queue of hashable keys."""

    def __init__(
        self,
        backoff: ItemExponentialBackoff | None = None,
        bucket: TokenBucket | None = None,
    ) -> None:
        self._backoff = backoff or ItemExponentialBackoff()
        self._bucket = bucket or TokenBucket()
        self._cond = threading.Condition()
        self._queue: list[Hashable] = []  # ready FIFO
        self._dirty: set[Hashable] = set()
        self._processing: set[Hashable] = set()
        self._delayed: list[tuple[float, int, Hashable]] = []  # heap by ready-time
        # item -> earliest pending ready-time: the coalescing ledger for the
        # delayed heap. Heap entries whose time no longer matches it are
        # superseded duplicates and are dropped at pop (lazy deletion).
        self._delayed_pending: dict[Hashable, float] = {}
        # Count of delayed enqueues coalesced into an already-pending entry
        # (observability; the scale bench reports it).
        self.coalesced = 0
        self._seq = 0
        self._shutdown = False

    # -- core add/get/done ---------------------------------------------------

    def add(self, item: Hashable) -> None:
        with self._cond:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item not in self._processing:
                self._queue.append(item)
                self._cond.notify()

    def add_after(self, item: Hashable, delay: float) -> None:
        """Schedule item for the ready queue after ``delay``; duplicate
        delayed enqueues coalesce to the EARLIEST deadline. Every consumer
        of a delayed pass is a level-triggered reconcile that reschedules
        its own next pass, so one (earliest) pending entry per key is
        equivalent to N of them — while N per key is what the periodic
        requeue + resync traffic produced at scale (heap growth O(waves ×
        jobs) instead of O(jobs))."""
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutdown:
                return
            ready = time.monotonic() + delay
            pending = self._delayed_pending.get(item)
            if pending is not None and pending <= ready:
                self.coalesced += 1
                return
            self._delayed_pending[item] = ready
            self._seq += 1
            heapq.heappush(self._delayed, (ready, self._seq, item))
            self._cond.notify()

    def add_rate_limited(self, item: Hashable) -> None:
        self.add_after(item, self._backoff.when(item) + self._bucket.delay())

    def _drain_delayed(self) -> float | None:
        """Move due delayed items to ready; return seconds to next due item."""
        now = time.monotonic()
        while self._delayed and self._delayed[0][0] <= now:
            ready, _, item = heapq.heappop(self._delayed)
            if self._delayed_pending.get(item) != ready:
                continue  # superseded by an earlier re-add; already served
            del self._delayed_pending[item]
            if item not in self._dirty:
                self._dirty.add(item)
                if item not in self._processing:
                    self._queue.append(item)
        if self._delayed:
            return max(0.0, self._delayed[0][0] - now)
        return None

    def get(self, timeout: float | None = None) -> Hashable | None:
        """Blocking pop; None on timeout or shutdown."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                next_due = self._drain_delayed()
                if self._queue:
                    item = self._queue.pop(0)
                    self._dirty.discard(item)
                    self._processing.add(item)
                    return item
                if self._shutdown:
                    return None
                wait = next_due
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(wait)

    def done(self, item: Hashable) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    # -- rate-limiter passthrough -------------------------------------------

    def forget(self, item: Hashable) -> None:
        self._backoff.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return self._backoff.num_requeues(item)

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)
