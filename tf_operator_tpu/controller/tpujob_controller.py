"""The TPUJob operator: reconciles TPUJob resources into gang-scheduled pods
and rendezvous services, and rolls pod state up into condition-based status.

Parity map (pkg/controller.v2/tfcontroller/):
- tfcontroller.go:104-350  → __init__/run/_worker/sync_job
- tfcontroller.go:363-430  → reconcile_job (claim, terminal path, per-type
  reconcile, single status update)
- controller_tfjob.go      → add_job (decode-validate + Created condition),
  delete_pods_and_services (CleanPodPolicy), cleanup_job (TTL)
- controller_status.go     → update_job_status roll-up (chief-else-workers)
- informer.go              → decode-time validation with warning events

Status updates go through the status "subresource" with conflict retry —
the hardening SURVEY.md §7 calls for over the reference's bare Update.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.defaults import set_defaults
from tf_operator_tpu.api.types import (
    JobConditionType,
    ReplicaType,
    RestartPolicy,
    TPUJob,
)
from tf_operator_tpu.api.validation import validate_spec
from tf_operator_tpu.api.types import CleanPodPolicy
from tf_operator_tpu.ckpt import protocol as ckpt_protocol
from tf_operator_tpu.ckpt.registry import CheckpointRegistry
from tf_operator_tpu.control.pod_control import PodControlInterface, RealPodControl
from tf_operator_tpu.control.service_control import (
    RealServiceControl,
    ServiceControlInterface,
)
from tf_operator_tpu.controller import status as status_engine
from tf_operator_tpu.controller.informer import EventHandlers, Informer
from tf_operator_tpu.controller.jobcontroller import JobController, JobControllerConfig
from tf_operator_tpu.controller.pod_reconciler import PodReconciler
from tf_operator_tpu.controller.service_reconciler import ServiceReconciler
from tf_operator_tpu.runtime import events as ev
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ClusterClient, Conflict, NotFound
from tf_operator_tpu.runtime.metrics import REGISTRY
from tf_operator_tpu.runtime.tracing import TRACER
from tf_operator_tpu.scheduler import GangScheduler
from tf_operator_tpu.scheduler.gang import is_gated
from tf_operator_tpu.utils import logger
from tf_operator_tpu.utils.times import parse_rfc3339

# Observability (absent from the reference — SURVEY.md §5): reconcile
# latency/outcome plus queue pressure, scraped via /metrics.
SYNC_SECONDS = REGISTRY.histogram(
    "tpu_operator_sync_duration_seconds",
    "Wall time of one reconcile pass", ("result",),
)
SYNCS_TOTAL = REGISTRY.counter(
    "tpu_operator_syncs_total", "Reconcile passes by outcome", ("result",),
)
QUEUE_DEPTH = REGISTRY.gauge(
    "tpu_operator_workqueue_depth", "Keys waiting in the workqueue",
)
RESTARTS_TOTAL = REGISTRY.counter(
    "tpu_operator_slice_restarts_total",
    "Slice/pod restart events (any restart policy; one per restarted "
    "group per sync)",
)


def _semantic_status(status: dict) -> dict:
    """Status minus the volatile reconcile stamp — the comparison basis for
    every skip-unchanged guard. Only lastReconcileTime is excluded; it then
    records the last MEANINGFUL reconcile, which is exactly what its one
    consumer (cleanup_job's TTL fallback) wants."""
    out = dict(status)
    out.pop("lastReconcileTime", None)
    return out


class TPUJobController(JobController, PodReconciler, ServiceReconciler):
    def __init__(
        self,
        client: ClusterClient,
        config: JobControllerConfig | None = None,
        pod_control: PodControlInterface | None = None,
        service_control: ServiceControlInterface | None = None,
        recorder: ev.EventRecorder | None = None,
        scheduler: GangScheduler | None = None,
    ) -> None:
        recorder = recorder or ev.EventRecorder(client)
        super().__init__(
            client,
            pod_control or RealPodControl(client, recorder),
            service_control or RealServiceControl(client, recorder),
            recorder,
            config,
        )
        # Gang admission authority (scheduler/core.py). The operator main
        # may pass a capacity/quota-configured instance; the default is an
        # unbounded fleet, which still runs the full gate → admit → release
        # pipeline so no partial slice can ever run.
        self.scheduler = scheduler or GangScheduler()
        # The scheduler shares this controller's pod informer: gang release
        # relists and eviction work-lists become cache index lookups
        # instead of per-call API LISTs (core.py _list_gang_pods).
        self.scheduler.attach(
            client, recorder, wakeup=self.enqueue, pod_lister=self.pod_informer
        )
        # Checkpoint registry (ckpt/registry.py): per-job checkpoint
        # roll-up, the eviction barrier's ack source, and resume-env
        # injection. The operator main may wire a flag-configured one onto
        # the scheduler first; otherwise a default registry is created —
        # it is pure observation until workers actually report, and the
        # eviction barrier additionally needs checkpoint_grace > 0.
        self.ckpt: CheckpointRegistry = (
            getattr(self.scheduler, "ckpt", None)
            or CheckpointRegistry(self.scheduler)
        )
        self.ckpt.attach(client, recorder)
        # Fleet-health monitor (health/monitor.py), when one was wired onto
        # the scheduler (operator main builds it; tests construct their
        # own). Attaching recovers persisted cordons before the first sync
        # so a restarted controller never re-places a gang on withdrawn
        # cells. Without a monitor the health surfaces stay dormant.
        self.health = getattr(self.scheduler, "health", None)
        self.node_informer: Informer | None = None
        if self.health is not None:
            # Node informer for the heartbeat sweep: the monitor's poll
            # reads this watch-maintained cache (zero API round-trips in
            # steady state) once run() has started and synced it; before
            # that the monitor falls back to a direct LIST.
            self.node_informer = Informer(
                client, objects.NODES, None, self.config.informer_resync
            )
            self.health.attach(client, recorder, node_lister=self.node_informer)
        self.job_informer = Informer(
            client, objects.TPUJOBS, self.config.namespace, self.config.informer_resync
        )
        self.job_informer.add_event_handlers(
            EventHandlers(
                on_add=self.add_job, on_update=self.update_job, on_delete=self.delete_job
            )
        )
        self.pod_informer.add_event_handlers(
            EventHandlers(
                on_add=self.add_pod, on_update=self.update_pod, on_delete=self.delete_pod
            )
        )
        self.service_informer.add_event_handlers(
            EventHandlers(
                on_add=self.add_service,
                on_update=self.update_service,
                on_delete=self.delete_service,
            )
        )
        # Test seams (tfcontroller.go:84-90 exposes syncHandler etc. for the
        # tier-2 harness).
        self.sync_handler = self.sync_job
        self.update_status_handler = self._write_status
        self.delete_job_handler = self._delete_job_resource
        self._workers: list[threading.Thread] = []
        # job key -> terminal condition type already recorded (evented) by
        # THIS controller — the in-memory half of the terminal-once guard
        # (see _terminal_already_recorded); cleared when the job is deleted.
        self._terminal_recorded: dict[str, str] = {}
        # job key -> highest restart_count this process has written; guards
        # the counter against informer-staleness regression (see sync path).
        self._restart_floor: dict[str, int] = {}

    # ------------------------------------------------------------------ decode

    def decode_job(self, obj: dict[str, Any]) -> TPUJob | None:
        """Convert + default + validate an unstructured TPUJob; reject bad
        specs with a warning event (informer.go:87-110 behavior)."""
        try:
            job = TPUJob.from_dict(obj)
            set_defaults(job)
            validate_spec(job.spec)
            return job
        except Exception as e:
            # Decode barrier: ANY failure (validation or malformed structure)
            # must reject the CR with an event rather than wedge the
            # controller (issue #561 behavior, informer.go:87-110).
            self.recorder.warning(obj, ev.FAILED_VALIDATION, str(e))
            logger.for_key(objects.key_of(obj)).warning("rejected TPUJob: %s", e)
            return None

    # -------------------------------------------------------------- handlers

    def add_job(self, obj: dict[str, Any]) -> None:
        job = self.decode_job(obj)
        if job is None:
            return
        if not job.status.conditions:
            status_engine.update_job_conditions(
                job,
                JobConditionType.CREATED,
                status_engine.REASON_CREATED,
                f"TPUJob {job.metadata.name} is created.",
            )
            try:
                self._write_status(job)
            except (Conflict, NotFound):
                pass
        self.enqueue(job.key)

    def update_job(self, old: dict[str, Any], new: dict[str, Any]) -> None:
        self.enqueue(f"{objects.namespace_of(new)}/{objects.name_of(new)}")

    def delete_job(self, obj: dict[str, Any]) -> None:
        key = f"{objects.namespace_of(obj)}/{objects.name_of(obj)}"
        self._terminal_recorded.pop(key, None)
        self._restart_floor.pop(key, None)
        self.scheduler.release_job(key)
        self.ckpt.forget(key)
        for rtype in ReplicaType.ALL:
            self.expectations.delete_expectations(
                self.expectation_key(key, rtype, "pods")
            )
            self.expectations.delete_expectations(
                self.expectation_key(key, rtype, "services")
            )
        # Owned pods/services are garbage-collected via ownerReferences by the
        # cluster backend (memcluster executor / K8s GC); nothing to enqueue.

    # ------------------------------------------------------------------ run

    def run(self, stop: threading.Event) -> None:
        """Start informers + worker threads; blocks until stop is set."""
        informers = [self.job_informer, self.pod_informer, self.service_informer]
        if self.node_informer is not None:
            informers.append(self.node_informer)
        for informer in informers:
            informer.start(stop)
        # Block on each informer's synced event rather than polling
        # has_synced in a 10ms sleep loop — the waits overlap (syncs run
        # in parallel informer threads), bounded by one shared deadline.
        deadline = time.monotonic() + 30
        for informer in informers:
            informer.synced_event.wait(max(0.0, deadline - time.monotonic()))
        for i in range(self.config.threadiness):
            t = threading.Thread(target=self._worker, name=f"worker-{i}", daemon=True)
            t.start()
            self._workers.append(t)
        stop.wait()
        self.queue.shut_down()
        for t in self._workers:
            t.join(timeout=2)

    def _worker(self) -> None:
        while True:
            key = self.queue.get()
            if key is None:
                return
            QUEUE_DEPTH.set(len(self.queue))
            t0 = time.monotonic()
            result = "ok"
            try:
                with TRACER.span("sync_job", key=str(key)):
                    requeue = self.sync_handler(key)
                self.queue.forget(key)
                if requeue:
                    self.enqueue_after(key, self.config.reconcile_period)
            except Exception:
                result = "error"
                logger.for_key(str(key)).exception("sync failed; requeueing")
                self.queue.add_rate_limited(key)
            finally:
                dt = time.monotonic() - t0
                SYNC_SECONDS.observe(dt, result=result)
                SYNCS_TOTAL.inc(result=result)
                self.queue.done(key)

    # ------------------------------------------------------------------ sync

    def get_job(self, namespace: str, name: str) -> TPUJob | None:
        obj = self.job_informer.get(namespace, name)
        if obj is None:
            try:
                obj = self.client.get(objects.TPUJOBS, namespace, name)
            except NotFound:
                return None
        return self.decode_job(obj)

    def satisfied_expectations(self, job: TPUJob) -> bool:
        key = self.job_key(job.metadata.namespace, job.metadata.name)
        for rtype in job.spec.replica_specs:
            if not self.expectations.satisfied(
                self.expectation_key(key, rtype, "pods")
            ):
                return False
            if not self.expectations.satisfied(
                self.expectation_key(key, rtype, "services")
            ):
                return False
        return True

    def sync_job(self, key: str) -> bool:
        """One reconcile pass for a job key. Returns True to request a
        periodic requeue (running jobs re-sync every reconcile_period)."""
        t0 = time.monotonic()
        namespace, _, name = key.partition("/")
        job = self.get_job(namespace, name)
        if job is None:
            self.delete_job({"metadata": {"namespace": namespace, "name": name}})
            return False
        if not self.satisfied_expectations(job):
            return True
        requeue = self.reconcile_job(job)
        logger.for_key(key).debug(
            "sync done in %.3fs", time.monotonic() - t0
        )
        return requeue

    def scheduling_gates(self, job: TPUJob) -> list[dict[str, str]]:
        """Admission gates stamped on every pod at creation (build_pod)."""
        if not self.config.enable_gang_scheduling:
            return []
        return self.scheduler.gates_for(job)

    def reconcile_job(self, job: TPUJob) -> bool:
        ref = self._controller_ref(job)
        pods = self.get_pods_for_job(job, ref)
        services = self.get_services_for_job(job, ref)
        # Snapshot for the skip-unchanged status guard below.
        status_before = job.status.to_dict()

        # Checkpoint roll-up BEFORE anything acts on the job: per-pod
        # durable-save reports become the job's annotation record (persist-
        # first) + status.lastCheckpointStep, and the registry's ack cache
        # is what the scheduler's eviction barrier consults this sync.
        self.ckpt.observe(job, pods)

        if status_engine.is_finished(job.status):
            self.scheduler.release_job(job.key)
            self.delete_pods_and_services(job, pods, services)
            self.delete_pdb(job)
            return self.cleanup_job(job)

        # Gang admission: every live job is arbitrated as one all-or-nothing
        # unit BEFORE any pod exists. A queued gang creates nothing — its
        # pods, services and PDB all wait for admission, so an unadmitted
        # job leaves zero footprint to deadlock or leak (VERDICT #3/#5).
        admitted = True
        total_replicas = sum(
            r.replicas or 0 for r in job.spec.replica_specs.values()
        )
        if self.config.enable_gang_scheduling:
            decision = self.scheduler.reconcile_gang(job, has_pods=bool(pods))
            admitted = decision.admitted
            if decision.evicting and decision.requeue_after is not None:
                # A graceful-eviction barrier is holding this gang's pods:
                # re-sync at the grace deadline so expiry never waits for
                # the periodic resync (acks arrive sooner via the pod
                # MODIFIED events their annotation patches emit).
                self.enqueue_after(job.key, decision.requeue_after)

        if (
            self.config.enable_gang_scheduling
            and job.spec.scheduling.gang
            and admitted
        ):
            self.sync_pdb(job, total_replicas)

        # Fleet-health conditions (SliceDegraded/JobMigrating): surfaced on
        # every sync so operators see degradation and in-flight migrations
        # on the job object itself, not only in /debug/health.
        if self.health is not None and self.config.enable_gang_scheduling:
            self._sync_health_conditions(job, admitted)

        # Checkpoint conditions (CheckpointStale/CheckpointSkipped): like
        # the health conditions, auxiliary roll-ups surfaced every sync.
        self._sync_ckpt_conditions(job)

        if not admitted:
            if pods:
                # Recovered graceful-eviction barrier: a predecessor
                # controller persisted state=queued + signal-gen + grace
                # deadline and died before the held deletion loop ran. The
                # pods keep their flush window — deletion waits until every
                # pod acks the persisted generation or the deadline passes,
                # exactly as the original barrier would have.
                barrier = self.ckpt.barrier_status(job, pods)
                if barrier is not None and barrier.waiting:
                    self.update_job_status(job, pods, False, False)
                    self._maybe_write_status(job, status_before)
                    self.enqueue_after(
                        job.key, max(0.05, barrier.remaining)
                    )
                    return True
                if barrier is not None and barrier.expired:
                    self.ckpt.note_skipped(
                        job.metadata.namespace, job.metadata.name,
                        barrier.gen, typed=job,
                    )
                # A queued gang with pods is an interrupted preemption (the
                # scheduler persisted state=queued, then the controller died
                # before the deletion loop finished): finish the eviction —
                # a queued gang must leave zero footprint, and half a slice
                # left running would occupy chips the ledger no longer
                # charges for.
                for pod in pods:
                    try:
                        self.pod_control.delete_pod(
                            job.metadata.namespace,
                            objects.name_of(pod),
                            job.to_dict(),
                        )
                    except NotFound:
                        pass
                if barrier is not None:
                    # The recovered barrier just completed: retire its
                    # record like the scheduler's own completion does.
                    self.ckpt.clear_barrier(job)
                return True
            # Waiting in the admission queue: record observation time only;
            # the scheduler wakes this key the moment capacity frees up,
            # and the periodic resync re-pumps the queue meanwhile (aging).
            self.update_job_status(job, pods, False, False)
            self._maybe_write_status(job, status_before)
            return True

        # Monotonic rebase BEFORE reconciling: this controller is the sole
        # writer of restart_count, but the informer cache can be one status
        # write stale — a sync computed from that stale base would silently
        # LOSE the previous sync's increment when the conflict retry
        # re-stamps the fresh RV (counter regression observed under chaos:
        # injected 6, counted 5), and the maxRestarts budget check inside
        # reconcile_pods would over-allow by the same margin. The floor
        # carries the freshest value this process has ever written.
        floor = self._restart_floor.get(job.key, 0)
        if job.status.restart_count < floor:
            job.status.restart_count = floor

        restarts = 0
        restarting = False
        permanent_failure = False
        for rtype, spec in sorted(job.spec.replica_specs.items()):
            summary = self.reconcile_pods(job, rtype, spec, pods)
            restarts += summary["restarts"]
            restarting = restarting or summary["restarting"]
            permanent_failure = permanent_failure or summary["permanent_failure"]
            self.reconcile_services(job, rtype, spec, services)

        job.status.restart_count += restarts
        if restarts:
            self._restart_floor[job.key] = job.status.restart_count
            RESTARTS_TOTAL.inc(restarts)
        if admitted and self.config.enable_gang_scheduling:
            # Every expected pod now exists (or this pass just created the
            # stragglers): lift the gates as one unit. Runs on any sync
            # whose cached view still shows gated or missing pods, so a
            # crash between create and release is finished by the next pass
            # (or the next controller incarnation) rather than wedging —
            # while a fully released steady-state gang skips the relist
            # release_gang would otherwise pay every sync.
            if len(pods) < total_replicas or any(is_gated(p) for p in pods):
                self.scheduler.release_gang(job)
        self.update_job_status(job, pods, restarting, permanent_failure)
        return self._maybe_write_status(job, status_before)

    def _maybe_write_status(self, job: TPUJob, status_before: dict) -> bool:
        # Skip-unchanged guard (the standard controller idiom): a status
        # write ALWAYS emits a job MODIFIED watch event, which re-enqueues
        # this very sync — without the guard every no-op pass re-stamps
        # last_reconcile_time and the loop feeds itself (profiled round 5:
        # ~144 syncs and ~150 status writes per job over a 3 s fleet
        # bench). Comparison excludes only the volatile stamp
        # (_semantic_status).
        if _semantic_status(job.status.to_dict()) == _semantic_status(
            status_before
        ):
            return True
        try:
            self.update_status_handler(job)
        except Conflict:
            # Stale read: drop this pass; the enqueue from the watch event (or
            # the periodic resync) will retry against the fresh object.
            self.enqueue(job.key)
        except NotFound:
            return False
        return True

    # ------------------------------------------------------------- terminal

    def delete_pods_and_services(
        self, job: TPUJob, pods: list[dict], services: list[dict]
    ) -> None:
        """CleanPodPolicy enforcement (controller_tfjob.go:75-100): None →
        keep everything; Running → delete only still-active pods; All →
        delete all pods. Services are removed whenever the policy is not
        None (they hold DNS names, and on TPU leaked pods hold whole slices).
        """
        policy = job.spec.clean_pod_policy or CleanPodPolicy.RUNNING
        if policy == CleanPodPolicy.NONE:
            return
        for pod in pods:
            phase = objects.pod_phase(pod)
            if policy == CleanPodPolicy.RUNNING and phase not in (
                objects.RUNNING,
                objects.PENDING,
            ):
                continue
            try:
                self.pod_control.delete_pod(
                    job.metadata.namespace, objects.name_of(pod), job.to_dict()
                )
            except NotFound:
                pass
        for svc in services:
            try:
                self.service_control.delete_service(
                    job.metadata.namespace, objects.name_of(svc), job.to_dict()
                )
            except NotFound:
                pass

    def cleanup_job(self, job: TPUJob) -> bool:
        """TTLSecondsAfterFinished (controller_tfjob.go:102-125): requeue
        until expiry, then delete the TPUJob itself. Returns requeue flag."""
        ttl = job.spec.ttl_seconds_after_finished
        if ttl is None:
            return False
        finished_at = job.status.completion_time or job.status.last_reconcile_time
        if not finished_at:
            return False
        finished_epoch = parse_rfc3339(finished_at)
        if finished_epoch is None:
            # Unparseable completion time: no basis for a TTL clock; leave
            # the job alone rather than failing the sync forever.
            return False
        expiry = finished_epoch + ttl
        now = time.time()
        if now < expiry:
            self.enqueue_after(job.key, expiry - now)
            return False
        self.delete_job_handler(job)
        return False

    def _delete_job_resource(self, job: TPUJob) -> None:
        try:
            self.client.delete(objects.TPUJOBS, job.metadata.namespace, job.metadata.name)
        except NotFound:
            pass

    # --------------------------------------------------------------- status

    def update_job_status(
        self,
        job: TPUJob,
        pods: list[dict[str, Any]],
        restarting: bool,
        permanent_failure: bool,
    ) -> None:
        """Recompute replica counters + conditions from observed pods
        (controller_status.go:42-119 semantics, slice-aware)."""
        job.status.replica_statuses = {}
        for rtype in job.spec.replica_specs:
            status_engine.initialize_replica_statuses(job, rtype)
        for pod in pods:
            rtype_label = objects.labels_of(pod).get(constants.LABEL_REPLICA_TYPE)
            for rtype in job.spec.replica_specs:
                if rtype.lower() == rtype_label:
                    status_engine.update_replica_statuses(job, rtype, pod)
        job.status.last_reconcile_time = objects.now_iso()

        name = job.metadata.name
        rs = job.status.replica_statuses

        # All expected replicas running → Running condition + StartTime.
        def _replicas(rtype: str) -> int:
            return job.spec.replica_specs[rtype].replicas or 0

        all_running = all(
            rs[rtype].active >= _replicas(rtype) for rtype in job.spec.replica_specs
        ) and any(_replicas(rtype) > 0 for rtype in job.spec.replica_specs)
        if all_running:
            if job.status.start_time is None:
                job.status.start_time = objects.now_iso()
            status_engine.update_job_conditions(
                job,
                JobConditionType.RUNNING,
                status_engine.REASON_RUNNING,
                f"TPUJob {name} is running.",
            )

        # Success: chief succeeded when a chief exists, else all workers done
        # (controller_status.go:54-74).
        succeeded = False
        if ReplicaType.CHIEF in job.spec.replica_specs:
            succeeded = rs[ReplicaType.CHIEF].succeeded >= 1
        elif ReplicaType.WORKER in job.spec.replica_specs:
            w = _replicas(ReplicaType.WORKER)
            succeeded = w > 0 and rs[ReplicaType.WORKER].succeeded >= w
        if succeeded:
            newly_terminal = not self._terminal_already_recorded(
                job, JobConditionType.SUCCEEDED
            )
            if job.status.completion_time is None:
                job.status.completion_time = objects.now_iso()
            status_engine.update_job_conditions(
                job,
                JobConditionType.SUCCEEDED,
                status_engine.REASON_SUCCEEDED,
                f"TPUJob {name} successfully completed.",
            )
            if newly_terminal:
                self.recorder.normal(
                    job.to_dict(), status_engine.REASON_SUCCEEDED, "Job completed"
                )
            return

        total_failed = sum(s.failed for s in rs.values())
        if restarting and not permanent_failure:
            # Failed pods observed this sync were deleted for a (slice)
            # restart (or their deletion had already landed and the cache
            # is one step stale) — the snapshot's failed counts are about
            # to clear.
            status_engine.update_job_conditions(
                job,
                JobConditionType.RESTARTING,
                status_engine.REASON_RESTARTING,
                f"TPUJob {name} is restarting "
                f"({job.status.restart_count} restart(s) total).",
            )
            return
        if permanent_failure or (total_failed > 0 and not self._any_restartable(job)):
            newly_terminal = not self._terminal_already_recorded(
                job, JobConditionType.FAILED
            )
            if job.status.completion_time is None:
                job.status.completion_time = objects.now_iso()
            status_engine.update_job_conditions(
                job,
                JobConditionType.FAILED,
                status_engine.REASON_FAILED,
                f"TPUJob {name} has failed ({total_failed} failed replica pod(s)).",
            )
            if newly_terminal:
                self.recorder.warning(
                    job.to_dict(), status_engine.REASON_FAILED, "Job failed"
                )
        elif total_failed > 0:
            status_engine.update_job_conditions(
                job,
                JobConditionType.RESTARTING,
                status_engine.REASON_RESTARTING,
                f"TPUJob {name} is restarting ({job.status.restart_count} restart(s) total).",
            )

    def _sync_health_conditions(self, job: TPUJob, admitted: bool) -> None:
        """Roll fleet-health state up into job conditions + events.

        - JobMigrating=True while the gang carries the migrated-at marker
          and is not (yet) re-admitted; flipped False (MigrationComplete)
          once the gang holds a fresh admission.
        - SliceDegraded=True while an admitted gang's placement includes
          cells with open suspicion or a cordon (named in the message);
          flipped False when the cells heal or the gang moved elsewhere.
        Both transitions emit one event each (set_condition dedupes
        semantically-identical updates, so steady state writes nothing).
        """
        from tf_operator_tpu.scheduler.gang import (
            ANNOTATION_MIGRATED_AT,
            ANNOTATION_PREEMPTED_AT,
        )

        ann = job.metadata.annotations or {}
        migrated_at = ann.get(ANNOTATION_MIGRATED_AT, "")
        # migrated-at outlives the migration on the job (annotations are
        # never garbage-collected); a LATER ordinary preemption must not
        # resurrect JobMigrating off the stale stamp. Migration writes
        # both stamps with one timestamp, so "this eviction was a
        # migration" ⇔ migrated-at >= preempted-at (ISO strings compare
        # lexicographically).
        migrating_now = (
            bool(migrated_at)
            and migrated_at >= ann.get(ANNOTATION_PREEMPTED_AT, "")
            and not admitted
        )
        was_migrating = status_engine.has_condition(
            job.status, JobConditionType.JOB_MIGRATING
        )
        if migrating_now and not was_migrating:
            msg = (
                "gang evicted off draining/cordoned cells at "
                f"{ann.get(ANNOTATION_MIGRATED_AT)}; awaiting re-placement "
                "on healthy cells"
            )
            status_engine.update_job_conditions(
                job, JobConditionType.JOB_MIGRATING,
                status_engine.REASON_MIGRATING, msg,
            )
            self.recorder.warning(
                job.to_dict(), status_engine.REASON_MIGRATING, msg
            )
        elif admitted and was_migrating:
            msg = "migration complete; gang re-placed on healthy cells"
            status_engine.update_job_conditions(
                job, JobConditionType.JOB_MIGRATING,
                status_engine.REASON_MIGRATED, msg, status=status_engine.FALSE,
            )
            self.recorder.normal(
                job.to_dict(), status_engine.REASON_MIGRATED, msg
            )

        degraded = (
            self.health.degraded_cells_for(job.key) if admitted else []
        )
        was_degraded = status_engine.has_condition(
            job.status, JobConditionType.SLICE_DEGRADED
        )
        if degraded:
            msg = (
                "slice placement includes unhealthy cells: "
                + ", ".join(degraded[:8])
                + ("…" if len(degraded) > 8 else "")
            )
            status_engine.update_job_conditions(
                job, JobConditionType.SLICE_DEGRADED,
                status_engine.REASON_SLICE_DEGRADED, msg,
            )
            if not was_degraded:
                self.recorder.warning(
                    job.to_dict(), status_engine.REASON_SLICE_DEGRADED, msg
                )
        elif was_degraded:
            status_engine.update_job_conditions(
                job, JobConditionType.SLICE_DEGRADED,
                status_engine.REASON_SLICE_HEALTHY,
                "slice cells healthy", status=status_engine.FALSE,
            )

    def _sync_ckpt_conditions(self, job: TPUJob) -> None:
        """Roll checkpoint-registry state up into job conditions.

        - CheckpointStale=True while a Running job's checkpoint roll-up
          has gone quiet past the registry's staleness threshold; flipped
          False on the next advance.
        - CheckpointSkipped=True while the most recent eviction proceeded
          past the grace deadline without an ack (skipped-at >= acked-at
          on the annotations — both stamps are ISO, so the comparison is
          lexicographic like the migrated-at/preempted-at pair above);
          flipped False once a newer ack lands.
        Jobs that never report a checkpoint (and were never skipped) get
        neither condition — the roll-up must be a strict no-op for
        non-checkpointing workloads.
        """
        ann = job.metadata.annotations or {}
        acked_at = ann.get(ckpt_protocol.JOB_ACKED_AT, "")
        skipped_at = ann.get(ckpt_protocol.JOB_SKIPPED_AT, "")
        if not acked_at and not skipped_at:
            return

        rec = self.ckpt.record_of(job.key)
        stale_now = rec is not None and rec.stale
        was_stale = status_engine.has_condition(
            job.status, JobConditionType.CHECKPOINT_STALE
        )
        if stale_now and not was_stale:
            msg = (
                f"no checkpoint advance since {acked_at or 'job start'} "
                f"(threshold {self.ckpt.config.stale_after:.0f}s)"
            )
            status_engine.update_job_conditions(
                job, JobConditionType.CHECKPOINT_STALE,
                status_engine.REASON_CKPT_STALE, msg,
            )
            self.recorder.warning(
                job.to_dict(), status_engine.REASON_CKPT_STALE, msg
            )
        elif not stale_now and was_stale:
            status_engine.update_job_conditions(
                job, JobConditionType.CHECKPOINT_STALE,
                status_engine.REASON_CKPT_FRESH,
                "checkpoint roll-up advancing again",
                status=status_engine.FALSE,
            )

        skipped_now = bool(skipped_at) and skipped_at >= acked_at
        was_skipped = status_engine.has_condition(
            job.status, JobConditionType.CHECKPOINT_SKIPPED
        )
        if skipped_now and not was_skipped:
            msg = (
                f"evicted at {skipped_at} without a checkpoint ack; "
                "resume will use the last recorded step"
            )
            status_engine.update_job_conditions(
                job, JobConditionType.CHECKPOINT_SKIPPED,
                status_engine.REASON_CKPT_SKIPPED, msg,
            )
            self.recorder.warning(
                job.to_dict(), status_engine.REASON_CKPT_SKIPPED, msg
            )
        elif not skipped_now and was_skipped:
            status_engine.update_job_conditions(
                job, JobConditionType.CHECKPOINT_SKIPPED,
                status_engine.REASON_CKPT_RECOVERED,
                "a newer checkpoint ack superseded the skipped eviction",
                status=status_engine.FALSE,
            )

    def report_pod_exit(
        self, job: TPUJob, pod: dict[str, Any], exit_code: int | None
    ) -> None:
        """Pod-reconciler hook (cell attribution): forward a failed pod's
        exit to the health monitor, which scores it against the cells the
        gang occupies."""
        if self.health is None or exit_code is None:
            return
        self.health.record_pod_exit(job.key, objects.uid_of(pod), exit_code)

    def _terminal_already_recorded(self, job: TPUJob, ctype: str) -> bool:
        """Terminal-once guard without a per-sync API round-trip.

        The reference derives this from cache (controller_status.go:42-119);
        a fresh GET per sync would be avoidable apiserver load at O(100)
        jobs × 15 s resync. Two cache layers cover the two staleness cases:
        - the job's own conditions (decoded from the informer cache) cover
          writes this controller OR a predecessor made, once observed;
        - _terminal_recorded covers the informer-lag window right after THIS
          controller wrote the condition (the event must not double-fire
          while the watch delta is still in flight).
        Marks the condition as recorded when it reports False, so each
        (job, condition) transitions exactly once per controller incarnation.
        """
        if self._terminal_recorded.get(job.key) == ctype:
            return True
        seen = any(
            c.type == ctype and c.status == "True" for c in job.status.conditions
        )
        self._terminal_recorded[job.key] = ctype
        return seen

    def _any_restartable(self, job: TPUJob) -> bool:
        """Whether the failed pods belong to a replica set whose policy can
        restart them. For ExitCode, a failed-and-still-Failed pod means the
        code was permanent (retryable ones were deleted this sync)."""
        for rtype, spec in job.spec.replica_specs.items():
            st = job.status.replica_statuses.get(rtype)
            if st is None or st.failed == 0:
                continue
            if spec.restart_policy in (RestartPolicy.ALWAYS, RestartPolicy.ON_FAILURE):
                return True
            if spec.restart_policy == RestartPolicy.EXIT_CODE:
                # Failed pods under ExitCode still present are permanent.
                continue
        return False

    def _write_status(self, job: TPUJob) -> None:
        """Status-subresource update with conflict retry (the hardening over
        controller_status.go:122-125's bare Update).

        On conflict the fresh object is consulted, not just its RV: if the
        store already reached a terminal state this (stale) computation must
        not overwrite it — blindly bumping the RV would turn optimistic
        concurrency into last-writer-wins and lose the terminal condition.

        Uniform no-op skip: when the informer cache already shows exactly
        this status, the write is dropped before it reaches the wire. The
        sync path's own diff-against-snapshot guard (_maybe_write_status)
        catches most no-ops; this second layer covers every OTHER caller —
        add_job re-observing an already-stamped Created condition on a
        handler replay, and post-conflict recomputes that converged on the
        stored value. A write wrongly needed is never skipped: a stale
        cache differs from the computed status and falls through.
        """
        cached = self.job_informer.get(job.metadata.namespace, job.metadata.name)
        if cached is not None and _semantic_status(
            cached.get("status") or {}
        ) == _semantic_status(job.status.to_dict()):
            return
        for attempt in range(3):
            try:
                self.client.update_status(objects.TPUJOBS, job.to_dict())
                return
            except Conflict:
                if attempt == 2:
                    raise
                fresh = self.client.get(
                    objects.TPUJOBS, job.metadata.namespace, job.metadata.name
                )
                fresh_status = fresh.get("status", {})
                fresh_terminal = any(
                    c.get("type") in (JobConditionType.SUCCEEDED, JobConditionType.FAILED)
                    and c.get("status") == "True"
                    for c in fresh_status.get("conditions", [])
                )
                mine_terminal = status_engine.is_finished(job.status)
                if fresh_terminal and not mine_terminal:
                    return  # keep the store's terminal status
                job.metadata.resource_version = str(
                    objects.meta(fresh).get("resourceVersion", "")
                )


