"""Cluster-topology contract generation — the TF_CONFIG analog, TPU-first.

Parity: pkg/controller.v2/tfcontroller/controller_tensorflow.go:33-124
(genTFConfigJSONStr/genClusterSpec) + GetPortFromTFJob (controller_util.go:
28-41). Two contracts are injected into the default container of every
replica pod:

1. ``TF_CONFIG`` — the classic map for tf.distribute strategies:
   ``{"cluster": {role: ["host:port", ...]}, "task": {"type","index"},
   "environment": "cloud"}``. Evaluators are excluded from the cluster map
   exactly as in the reference (controller_tensorflow.go:103-107).
   On TPU replica sets this is what points MultiWorkerMirroredStrategy at the
   ICI mesh (one worker per slice host).

2. The TPU mesh env — what JAX's ``jax.distributed.initialize`` and libtpu
   consume directly: ``TPU_WORKER_HOSTNAMES`` (stable, index-ordered),
   ``TPU_WORKER_ID``, ``TPU_COORDINATOR_ADDRESS`` (worker 0 of the slice),
   accelerator type + topology, and per-slice MEGASCALE vars when a replica
   set spans multiple slices (DCN multislice).

Host ordering is derived from indexed pod/service names
({job}-{type}-{index}), so it is stable across pod restarts — the rendezvous
correctness property SURVEY.md §7 calls out.
"""

from __future__ import annotations

import copy
import json
from typing import Any

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import ReplicaType, TPUJob
from tf_operator_tpu.topology import slices
from tf_operator_tpu.utils import names


def get_port(job: TPUJob, replica_type: str) -> int:
    """Rendezvous port for a replica type: the named port on the default
    container, else the global default."""
    spec = job.spec.replica_specs.get(replica_type)
    if spec is not None:
        for c in spec.template.get("spec", {}).get("containers", []):
            if c.get("name") != constants.DEFAULT_CONTAINER_NAME:
                continue
            for p in c.get("ports", []):
                if p.get("name") == constants.DEFAULT_PORT_NAME:
                    return int(p.get("containerPort", constants.DEFAULT_PORT))
    return constants.DEFAULT_PORT


def replica_hostname(job: TPUJob, replica_type: str, index: int) -> str:
    """DNS name of a replica's headless service (== pod name)."""
    return names.gen_name(job.metadata.name, replica_type, index)


def gen_cluster_spec(job: TPUJob) -> dict[str, list[str]]:
    """role → ["host:port", ...] for every replica type except Evaluator."""
    cluster: dict[str, list[str]] = {}
    for rtype, spec in sorted(job.spec.replica_specs.items()):
        if rtype == ReplicaType.EVALUATOR:
            continue
        port = get_port(job, rtype)
        cluster[rtype.lower()] = [
            f"{replica_hostname(job, rtype, i)}:{port}"
            for i in range(spec.replicas or 0)
        ]
    return cluster


def gen_tf_config(job: TPUJob, replica_type: str, index: int) -> str:
    """The TF_CONFIG JSON for one replica."""
    config = {
        "cluster": gen_cluster_spec(job),
        "task": {"type": replica_type.lower(), "index": index},
        "environment": "cloud",
    }
    return json.dumps(config, sort_keys=True)


def gen_tpu_env(job: TPUJob, replica_type: str, index: int) -> dict[str, str]:
    """TPU mesh env for one replica of a slice-bound replica set.

    For ``num_slices`` > 1 the replica set's pods are partitioned into
    contiguous index ranges, one range per slice; each slice has its own
    in-slice worker ids and coordinator (worker 0 of that slice), and the
    MEGASCALE vars wire slice 0's coordinator as the DCN rendezvous point.
    """
    spec = job.spec.replica_specs.get(replica_type)
    if spec is None or spec.tpu is None or not spec.tpu.accelerator_type:
        return {}
    topo = slices.resolve(spec.tpu.accelerator_type, spec.tpu.topology)
    num_slices = max(1, spec.tpu.num_slices)
    port = get_port(job, replica_type)

    slice_id, worker_id = divmod(index, topo.num_hosts)
    base = slice_id * topo.num_hosts
    hosts = [
        replica_hostname(job, replica_type, base + i) for i in range(topo.num_hosts)
    ]
    env = {
        constants.ENV_TPU_WORKER_HOSTNAMES: ",".join(hosts),
        constants.ENV_TPU_WORKER_ID: str(worker_id),
        constants.ENV_TPU_ACCELERATOR_TYPE: topo.accelerator_type,
        constants.ENV_TPU_TOPOLOGY: topo.topology,
        constants.ENV_COORDINATOR_ADDRESS: f"{hosts[0]}:{port}",
        constants.ENV_NUM_PROCESSES: str(topo.num_hosts),
    }
    if num_slices > 1:
        slice0_coord = replica_hostname(job, replica_type, 0)
        # The DCN rendezvous gets its own port: on slice 0's worker 0 the
        # in-slice coordinator (jax.distributed) and the cross-slice
        # coordinator both live in one pod, and they cannot share a bind —
        # the same separation real multislice makes (MEGASCALE coordinator
        # :8080 vs jax coordinator :8471).
        env.update(
            {
                "MEGASCALE_NUM_SLICES": str(num_slices),
                "MEGASCALE_SLICE_ID": str(slice_id),
                "MEGASCALE_COORDINATOR_ADDRESS": (
                    f"{slice0_coord}:{port + constants.DCN_PORT_OFFSET}"
                ),
            }
        )
    return env


def set_cluster_spec(
    pod_template: dict[str, Any], job: TPUJob, replica_type: str, index: int
) -> dict[str, Any]:
    """Return a copy of the pod template with the topology contract injected
    into the default container only (parity: replicas.go:202-234 injects
    TF_CONFIG into the "tensorflow" container only)."""
    tmpl = copy.deepcopy(pod_template)
    injected = {constants.ENV_TF_CONFIG: gen_tf_config(job, replica_type, index)}
    injected.update(gen_tpu_env(job, replica_type, index))

    for c in tmpl.get("spec", {}).get("containers", []):
        if c.get("name") != constants.DEFAULT_CONTAINER_NAME:
            continue
        env = c.setdefault("env", [])
        present = {e.get("name") for e in env}
        for k, v in injected.items():
            if k not in present:
                env.append({"name": k, "value": v})
    return tmpl


def node_placement(job: TPUJob, replica_type: str) -> dict[str, Any]:
    """GKE node-selector terms pinning slice pods to the right TPU node pool.

    The TPU-native replacement for the reference's accelerator volume/env
    config injection (helper/helpers.go:50-104): placement is derived from
    the slice spec, not from an operator-side config file.
    """
    spec = job.spec.replica_specs.get(replica_type)
    if spec is None or spec.tpu is None or not spec.tpu.accelerator_type:
        return {}
    topo = slices.resolve(spec.tpu.accelerator_type, spec.tpu.topology)
    return {
        "nodeSelector": {
            "cloud.google.com/gke-tpu-accelerator": topo.gke_accelerator,
            "cloud.google.com/gke-tpu-topology": topo.topology,
        },
        "tpuResources": {"google.com/tpu": topo.chips_per_host},
    }
