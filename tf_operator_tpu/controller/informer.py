"""Informer: list+watch cache with event handlers and periodic resync.

Parity: the SharedInformerFactory / unstructured-informer machinery the
reference builds on (pkg/util/unstructured/informer.go:24-62,
tfcontroller/informer.go:34-55). The controller reads the world from this
cache (never directly from the API) and reacts to deltas via handlers; a
periodic resync re-delivers everything so missed events self-heal.

Tests drive it synchronously via ``sync_now()`` — the analog of seeding
informer indexers directly in the reference's tier-2 tests
(tfcontroller_test.go "seeds informer indexers").
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable

from tf_operator_tpu.api.helpers import selector_matches
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ADDED, DELETED, MODIFIED, ClusterClient
from tf_operator_tpu.utils import logger

Handler = Callable[[dict[str, Any]], None]
UpdateHandler = Callable[[dict[str, Any], dict[str, Any]], None]


@dataclass
class EventHandlers:
    on_add: Handler | None = None
    on_update: UpdateHandler | None = None
    on_delete: Handler | None = None


class Informer:
    def __init__(
        self,
        client: ClusterClient,
        kind: str,
        namespace: str | None = None,
        resync_period: float = 30.0,
    ) -> None:
        self._client = client
        self.kind = kind
        self.namespace = namespace
        self.resync_period = resync_period
        self._cache: dict[str, dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._handlers: list[EventHandlers] = []
        self._synced = threading.Event()
        self._thread: threading.Thread | None = None
        self._log = logger.with_fields(informer=kind)
        # UIDs of objects whose deletion was observed (watch or relist
        # diff): late watch events for these uids are stale replays —
        # with an async-delivery backend (kubeclient's HTTP reader
        # thread) a pre-list event can arrive AFTER the relist and
        # resurrect a deleted object into the cache ("ghost"). UIDs are
        # never reused, so suppression is exact while a uid stays in the
        # FIFO; the bound makes it BEST-EFFORT in namespaces churning
        # more deletions than the cap between a stale buffered event and
        # its late replay, so the cap scales with the live-cache size
        # (see _mark_dead) with 1024 as the floor.
        self._dead_uids: dict[str, None] = {}
        self._dead_uids_cap = 1024

    # -- registration / cache reads -----------------------------------------

    def add_event_handlers(self, handlers: EventHandlers) -> None:
        self._handlers.append(handlers)

    def has_synced(self) -> bool:
        return self._synced.is_set()

    def get(self, namespace: str, name: str) -> dict[str, Any] | None:
        with self._lock:
            return self._cache.get(f"{namespace}/{name}")

    def list(
        self,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict[str, Any]]:
        with self._lock:
            out = []
            for key, obj in self._cache.items():
                if namespace is not None and not key.startswith(namespace + "/"):
                    continue
                if label_selector and not selector_matches(
                    label_selector, objects.labels_of(obj)
                ):
                    continue
                out.append(obj)
            out.sort(key=objects.key_of)
            return out

    # -- delta processing ----------------------------------------------------

    def _mark_dead(self, obj: dict[str, Any]) -> None:
        uid = objects.uid_of(obj)
        if not uid:
            return
        self._dead_uids[uid] = None
        # Scale the suppression window with the namespace's live size: a
        # cache of N objects can churn ~N deletions in one relist cycle,
        # so a fixed cap would silently lose exactness at scale.
        cap = max(self._dead_uids_cap, 4 * len(self._cache))
        while len(self._dead_uids) > cap:
            self._dead_uids.pop(next(iter(self._dead_uids)))

    def _apply(self, etype: str, obj: dict[str, Any]) -> None:
        key = objects.key_of(obj)
        uid = objects.uid_of(obj)
        with self._lock:
            old = self._cache.get(key)
            if etype == DELETED:
                replayed = bool(uid) and uid in self._dead_uids
                # A DELETED naming a DIFFERENT live incarnation (same key,
                # new uid — the relist already replaced it) must not pop
                # the live object; its on_delete still fires (below) if
                # this is the first observation of that deletion.
                stale_incarnation = (
                    old is not None
                    and uid
                    and objects.uid_of(old)
                    and objects.uid_of(old) != uid
                )
                self._mark_dead(obj)
                if not stale_incarnation:
                    self._cache.pop(key, None)
                if replayed:
                    # Handlers (expectation decrements) already ran for
                    # this deletion — e.g. the relist diff synthesized it
                    # and the buffered watch DELETED arrives later.
                    return
            else:
                if uid and uid in self._dead_uids:
                    # Stale replay of an object whose deletion was already
                    # observed — applying it would resurrect a ghost.
                    return
                self._cache[key] = obj
        for h in self._handlers:
            try:
                if etype == ADDED and old is None:
                    if h.on_add:
                        h.on_add(obj)
                elif etype == DELETED:
                    if h.on_delete:
                        h.on_delete(obj)
                else:
                    if h.on_update:
                        h.on_update(old if old is not None else obj, obj)
            except Exception:
                self._log.exception("informer handler failed")

    def sync_now(self) -> None:
        """Synchronous full list → cache + handler deltas. Used by tests and
        as the initial sync of the background loop."""
        fresh = {
            objects.key_of(o): o
            for o in self._client.list(self.kind, self.namespace)
        }
        with self._lock:
            stale = [k for k in self._cache if k not in fresh]
        for key in stale:
            with self._lock:
                obj = self._cache.get(key)
            if obj is not None:
                self._apply(DELETED, obj)
        for obj in fresh.values():
            with self._lock:
                known = objects.key_of(obj) in self._cache
            self._apply(MODIFIED if known else ADDED, obj)
        self._synced.set()

    # -- background loop -----------------------------------------------------

    def start(self, stop: threading.Event) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, args=(stop,), name=f"informer-{self.kind}", daemon=True
        )
        self._thread.start()

    def _drain(self, watch: Any) -> None:
        """Apply every already-buffered watch event.

        MUST run before a relist: `sync_now` rebuilds the cache from a
        fresh LIST, and applying a pre-list buffered event afterwards
        would replay stale state over it — observed as a "ghost" failed
        pod resurrected into the cache after its DELETED had been
        synthesized by the list diff, which a concurrent worker sync then
        double-counted as a second restart (chaos soak, restartCount 20
        vs 19 injected). client-go avoids the same race by restarting the
        watch from the list's resourceVersion; draining first gives the
        same pre-list/post-list ordering without RV coupling (events that
        arrive DURING the list are post-snapshot for our backends, which
        list under a store lock / at a single RV).
        """
        while True:
            event = watch.next(timeout=0)
            if event is None:
                return
            self._apply(event.type, event.object)

    def _run(self, stop: threading.Event) -> None:
        watch = self._client.watch(self.kind, self.namespace)
        self._drain(watch)  # events buffered between watch-start and list
        self.sync_now()
        import time as _time

        last_resync = _time.monotonic()
        while not stop.is_set():
            event = watch.next(timeout=0.2)
            if event is not None:
                self._apply(event.type, event.object)
            if _time.monotonic() - last_resync >= self.resync_period:
                try:
                    self._drain(watch)
                    self.sync_now()
                except Exception:
                    self._log.exception("resync failed")
                last_resync = _time.monotonic()
        watch.stop()
