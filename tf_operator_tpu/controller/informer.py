"""Informer: list+watch cache with secondary indexes, event handlers and
periodic resync.

Parity: the SharedInformerFactory / unstructured-informer machinery the
reference builds on (pkg/util/unstructured/informer.go:24-62,
tfcontroller/informer.go:34-55). The controller reads the world from this
cache (never directly from the API) and reacts to deltas via handlers; a
periodic resync re-delivers everything so missed events self-heal.

Reads are index lookups, not scans. Three incremental secondary indexes are
maintained on every ADDED/MODIFIED/DELETED delta, so the cost of a cache
read is O(result), not O(world):

- **namespace** — key set per namespace (the old ``list(namespace=...)``
  prefix scan);
- **owner uid** — key set per controller ownerReference uid, serving
  ``get_pods_for_job``-style "everything this job owns" lookups;
- **label term** — key set per (label, value) pair. A label-selector query
  hashes each of its terms and intersects the matching key sets
  (smallest-set first). Indexing per *term* rather than per whole selector
  keeps delta maintenance O(#labels on the object): a whole-selector index
  would have to re-evaluate every registered selector (one per live job —
  O(jobs)) on every pod event, which is exactly the O(jobs x pods) blow-up
  this index exists to remove.

Tests drive it synchronously via ``sync_now()`` — the analog of seeding
informer indexers directly in the reference's tier-2 tests
(tfcontroller_test.go "seeds informer indexers").
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable

from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ADDED, DELETED, MODIFIED, ClusterClient
from tf_operator_tpu.runtime.metrics import (
    INFORMER_CACHE_SIZE,
    INFORMER_INDEX_HITS,
)
from tf_operator_tpu.utils import logger

Handler = Callable[[dict[str, Any]], None]
UpdateHandler = Callable[[dict[str, Any], dict[str, Any]], None]


@dataclass
class EventHandlers:
    on_add: Handler | None = None
    on_update: UpdateHandler | None = None
    on_delete: Handler | None = None


def _controller_uid(obj: dict[str, Any]) -> str:
    """Uid of the controller ownerReference, '' when unowned."""
    for ref in objects.meta(obj).get("ownerReferences", []) or []:
        if ref.get("controller"):
            return str(ref.get("uid", ""))
    return ""


class Informer:
    def __init__(
        self,
        client: ClusterClient,
        kind: str,
        namespace: str | None = None,
        resync_period: float = 30.0,
    ) -> None:
        self._client = client
        self.kind = kind
        self.namespace = namespace
        self.resync_period = resync_period
        self._cache: dict[str, dict[str, Any]] = {}
        # Secondary indexes: key sets, maintained by _cache_put/_cache_pop
        # (the ONLY two mutators of _cache) so cache and indexes can never
        # drift apart, whatever path — watch delta, relist diff, ghost
        # suppression — mutated the cache.
        self._by_namespace: dict[str, set[str]] = {}
        self._by_owner: dict[str, set[str]] = {}
        self._by_label: dict[tuple[str, str], set[str]] = {}
        self._lock = threading.RLock()
        self._handlers: list[EventHandlers] = []
        self._synced = threading.Event()
        self._thread: threading.Thread | None = None
        self._log = logger.with_fields(informer=kind)
        # UIDs of objects whose deletion was observed (watch or relist
        # diff): late watch events for these uids are stale replays —
        # with an async-delivery backend (kubeclient's HTTP reader
        # thread) a pre-list event can arrive AFTER the relist and
        # resurrect a deleted object into the cache ("ghost"). UIDs are
        # never reused, so suppression is exact while a uid stays in the
        # FIFO; the bound makes it BEST-EFFORT in namespaces churning
        # more deletions than the cap between a stale buffered event and
        # its late replay, so the cap scales with the live-cache size
        # (see _mark_dead) with 1024 as the floor.
        self._dead_uids: dict[str, None] = {}
        self._dead_uids_cap = 1024

    # -- registration / cache reads -----------------------------------------

    def add_event_handlers(self, handlers: EventHandlers) -> None:
        self._handlers.append(handlers)

    def has_synced(self) -> bool:
        return self._synced.is_set()

    @property
    def synced_event(self) -> threading.Event:
        """Waitable sync barrier: set after the first full list lands in
        the cache. Callers block on it (``.wait(timeout)``) instead of
        polling ``has_synced`` in a sleep loop."""
        return self._synced

    def wait_synced(self, timeout: float | None = None) -> bool:
        return self._synced.wait(timeout)

    def get(self, namespace: str, name: str) -> dict[str, Any] | None:
        with self._lock:
            return self._cache.get(f"{namespace}/{name}")

    def list(
        self,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict[str, Any]]:
        with self._lock:
            if label_selector:
                keys = self._select_keys(label_selector, namespace)
                INFORMER_INDEX_HITS.inc(kind=self.kind, index="label")
            elif namespace is not None:
                keys = self._by_namespace.get(namespace, set())
                INFORMER_INDEX_HITS.inc(kind=self.kind, index="namespace")
            else:
                keys = self._cache.keys()
            out = [self._cache[k] for k in keys]
            out.sort(key=objects.key_of)
            return out

    def list_for_owner(
        self,
        owner_uid: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict[str, Any]]:
        """Union of the owner-uid and label-selector indexes — the claim
        candidate set for one controlling object: everything it owns (so a
        relabeled orphan can be released) plus everything matching its
        labels (so an unowned match can be adopted). Equivalent to the
        full-namespace scan RefManager used to filter, because candidates
        in neither set can produce a claim action."""
        with self._lock:
            keys: set[str] = set()
            if owner_uid:
                keys |= self._by_owner.get(owner_uid, set())
                INFORMER_INDEX_HITS.inc(kind=self.kind, index="owner")
            if label_selector:
                keys |= self._select_keys(label_selector, namespace)
                INFORMER_INDEX_HITS.inc(kind=self.kind, index="label")
            if namespace is not None:
                ns_keys = self._by_namespace.get(namespace, set())
                keys &= ns_keys
            out = [self._cache[k] for k in keys]
            out.sort(key=objects.key_of)
            return out

    def _select_keys(
        self, selector: dict[str, str], namespace: str | None
    ) -> set[str]:
        """Keys matching every selector term: intersect the per-term key
        sets, smallest first (lock held)."""
        term_sets: list[set[str]] = []
        for term in selector.items():
            s = self._by_label.get(term)
            if not s:
                return set()
            term_sets.append(s)
        term_sets.sort(key=len)
        keys = set(term_sets[0])
        for s in term_sets[1:]:
            keys &= s
        if namespace is not None:
            keys &= self._by_namespace.get(namespace, set())
        return keys

    # -- cache + index mutation (lock held) ----------------------------------

    def _index_add(self, key: str, obj: dict[str, Any]) -> None:
        self._by_namespace.setdefault(objects.namespace_of(obj), set()).add(key)
        uid = _controller_uid(obj)
        if uid:
            self._by_owner.setdefault(uid, set()).add(key)
        for term in objects.labels_of(obj).items():
            self._by_label.setdefault(term, set()).add(key)

    def _index_remove(self, key: str, obj: dict[str, Any]) -> None:
        def _discard(table: dict, idx_key: Any) -> None:
            s = table.get(idx_key)
            if s is not None:
                s.discard(key)
                if not s:
                    del table[idx_key]

        _discard(self._by_namespace, objects.namespace_of(obj))
        uid = _controller_uid(obj)
        if uid:
            _discard(self._by_owner, uid)
        for term in objects.labels_of(obj).items():
            _discard(self._by_label, term)

    def _cache_put(self, key: str, obj: dict[str, Any]) -> None:
        old = self._cache.get(key)
        if old is not None:
            # Labels or ownerReferences may have changed: deindex the old
            # incarnation first or a relabel would leave a stale entry.
            self._index_remove(key, old)
        self._cache[key] = obj
        self._index_add(key, obj)
        INFORMER_CACHE_SIZE.set(len(self._cache), kind=self.kind)

    def _cache_pop(self, key: str) -> dict[str, Any] | None:
        obj = self._cache.pop(key, None)
        if obj is not None:
            self._index_remove(key, obj)
            INFORMER_CACHE_SIZE.set(len(self._cache), kind=self.kind)
        return obj

    def check_indexes(self) -> None:
        """Invariant check (tests): every index entry resolves to a cached
        object that actually has the indexed property, and every cached
        object is fully indexed. Raises AssertionError on drift."""
        with self._lock:
            for ns, keys in self._by_namespace.items():
                for k in keys:
                    assert k in self._cache, f"namespace index ghost {k}"
                    assert objects.namespace_of(self._cache[k]) == ns
            for uid, keys in self._by_owner.items():
                for k in keys:
                    assert k in self._cache, f"owner index ghost {k}"
                    assert _controller_uid(self._cache[k]) == uid
            for term, keys in self._by_label.items():
                for k in keys:
                    assert k in self._cache, f"label index ghost {k}"
                    labels = objects.labels_of(self._cache[k])
                    assert labels.get(term[0]) == term[1]
            for k, obj in self._cache.items():
                assert k in self._by_namespace.get(objects.namespace_of(obj), set())
                uid = _controller_uid(obj)
                if uid:
                    assert k in self._by_owner.get(uid, set())
                for term in objects.labels_of(obj).items():
                    assert k in self._by_label.get(term, set())

    # -- delta processing ----------------------------------------------------

    def _mark_dead(self, obj: dict[str, Any]) -> None:
        uid = objects.uid_of(obj)
        if not uid:
            return
        self._dead_uids[uid] = None
        # Scale the suppression window with the namespace's live size: a
        # cache of N objects can churn ~N deletions in one relist cycle,
        # so a fixed cap would silently lose exactness at scale.
        cap = max(self._dead_uids_cap, 4 * len(self._cache))
        while len(self._dead_uids) > cap:
            self._dead_uids.pop(next(iter(self._dead_uids)))

    def _apply(self, etype: str, obj: dict[str, Any]) -> None:
        key = objects.key_of(obj)
        uid = objects.uid_of(obj)
        with self._lock:
            old = self._cache.get(key)
            if etype == DELETED:
                replayed = bool(uid) and uid in self._dead_uids
                # A DELETED naming a DIFFERENT live incarnation (same key,
                # new uid — the relist already replaced it) must not pop
                # the live object; its on_delete still fires (below) if
                # this is the first observation of that deletion.
                stale_incarnation = (
                    old is not None
                    and uid
                    and objects.uid_of(old)
                    and objects.uid_of(old) != uid
                )
                self._mark_dead(obj)
                if not stale_incarnation:
                    self._cache_pop(key)
                if replayed:
                    # Handlers (expectation decrements) already ran for
                    # this deletion — e.g. the relist diff synthesized it
                    # and the buffered watch DELETED arrives later.
                    return
            else:
                if uid and uid in self._dead_uids:
                    # Stale replay of an object whose deletion was already
                    # observed — applying it would resurrect a ghost.
                    return
                self._cache_put(key, obj)
        for h in self._handlers:
            try:
                if etype == ADDED and old is None:
                    if h.on_add:
                        h.on_add(obj)
                elif etype == DELETED:
                    if h.on_delete:
                        h.on_delete(obj)
                else:
                    if h.on_update:
                        h.on_update(old if old is not None else obj, obj)
            except Exception:
                self._log.exception("informer handler failed")

    def sync_now(self) -> None:
        """Synchronous full list → cache + handler deltas. Used by tests and
        as the initial sync of the background loop."""
        fresh = {
            objects.key_of(o): o
            for o in self._client.list(self.kind, self.namespace)
        }
        with self._lock:
            stale = [k for k in self._cache if k not in fresh]
        for key in stale:
            with self._lock:
                obj = self._cache.get(key)
            if obj is not None:
                self._apply(DELETED, obj)
        for obj in fresh.values():
            with self._lock:
                known = objects.key_of(obj) in self._cache
            self._apply(MODIFIED if known else ADDED, obj)
        self._synced.set()

    # -- background loop -----------------------------------------------------

    def start(self, stop: threading.Event) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, args=(stop,), name=f"informer-{self.kind}", daemon=True
        )
        self._thread.start()

    def _drain(self, watch: Any) -> None:
        """Apply every already-buffered watch event.

        MUST run before a relist: `sync_now` rebuilds the cache from a
        fresh LIST, and applying a pre-list buffered event afterwards
        would replay stale state over it — observed as a "ghost" failed
        pod resurrected into the cache after its DELETED had been
        synthesized by the list diff, which a concurrent worker sync then
        double-counted as a second restart (chaos soak, restartCount 20
        vs 19 injected). client-go avoids the same race by restarting the
        watch from the list's resourceVersion; draining first gives the
        same pre-list/post-list ordering without RV coupling (events that
        arrive DURING the list are post-snapshot for our backends, which
        list under a store lock / at a single RV).
        """
        while True:
            event = watch.next(timeout=0)
            if event is None:
                return
            self._apply(event.type, event.object)

    def _run(self, stop: threading.Event) -> None:
        watch = self._client.watch(self.kind, self.namespace)
        # Initial sync, retried: a transient apiserver outage at startup
        # must not kill the informer thread permanently (observed as an
        # unhandled ConnectionRefused from the chaos suite's stub
        # teardown) — a dead thread would leave has_synced() false forever
        # while the controller runs against an empty cache.
        while not stop.is_set():
            try:
                self._drain(watch)  # events buffered between watch-start and list
                self.sync_now()
                break
            except Exception:
                self._log.exception("initial sync failed; retrying")
                stop.wait(1.0)
        import time as _time

        last_resync = _time.monotonic()
        while not stop.is_set():
            event = watch.next(timeout=0.2)
            if event is not None:
                self._apply(event.type, event.object)
            if _time.monotonic() - last_resync >= self.resync_period:
                try:
                    self._drain(watch)
                    self.sync_now()
                except Exception:
                    self._log.exception("resync failed")
                last_resync = _time.monotonic()
        watch.stop()
