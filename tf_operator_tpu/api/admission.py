"""Admission validation for unstructured TPUJob objects.

The server-side half of the validation story — the analog of the reference's
CRD OpenAPI validation (examples/crd/crd-v1alpha2.yaml:24-47), which rejects
bad specs at the API boundary *before* they are stored. The controller's
decode barrier (tpujob_controller.decode_job, the informer.go:87-110
behavior) stays as defense-in-depth for objects that reach the store by
other means.

Three enforcement points share this function:
- runtime/apiserver.py rejects invalid create/update/patch with 422,
- runtime/kubestub.py emulates CRD admission the same way,
- dashboard/backend.py validates deploys so the UI surfaces the message.
On a real cluster, deploy/crd.yaml's structural schema covers the same
rules apiserver-side.
"""

from __future__ import annotations

import copy
import re
from typing import Any

from tf_operator_tpu.api.defaults import set_defaults
from tf_operator_tpu.api.types import TPUJob
from tf_operator_tpu.api.validation import ValidationError, validate_spec

# RFC 1123 DNS label — pod/service names are derived from the job name, so
# the job name must itself be a valid label (reference: genName truncates to
# 40 chars for the same reason, replicas.go:574-585).
_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
MAX_NAME_LEN = 63


def validate_tpujob_object(obj: dict[str, Any]) -> None:
    """Validate an unstructured TPUJob for admission; raises ValidationError.

    Structural checks first (the CRD-schema layer), then full spec
    validation on a defaulted copy — defaulting before validating mirrors
    the order the controller's decode barrier uses, so both layers accept
    exactly the same set of objects. The stored object is what the client
    sent; defaults are applied at decode time, not persisted.
    """
    if not isinstance(obj, dict):
        raise ValidationError("body must be a JSON object")
    meta = obj.get("metadata")
    if not isinstance(meta, dict) or not meta.get("name"):
        raise ValidationError("metadata.name is required")
    name = str(meta["name"])
    if len(name) > MAX_NAME_LEN or not _DNS1123.match(name):
        raise ValidationError(
            f"metadata.name {name!r} must be a DNS-1123 label (max {MAX_NAME_LEN} chars)"
        )
    spec = obj.get("spec")
    if not isinstance(spec, dict):
        raise ValidationError("spec is required and must be an object")
    if not isinstance(spec.get("replicaSpecs"), dict) or not spec["replicaSpecs"]:
        raise ValidationError("spec.replicaSpecs must be a non-empty object")

    try:
        job = TPUJob.from_dict(copy.deepcopy(obj))
        set_defaults(job)
    except ValidationError:
        raise
    except Exception as e:  # malformed nested structure (wrong types, etc.)
        raise ValidationError(f"malformed TPUJob: {e}") from e
    validate_spec(job.spec)
