"""API-group constants for the TPUJob resource.

Analog of the reference's pkg/apis/tensorflow/v1alpha2/constants.go:17-30 and
the group/kind registration in v1alpha2/types.go:28-66, re-keyed for a
TPU-native operator.
"""

from __future__ import annotations

# API group / version / kind (the CRD coordinates).
GROUP_NAME = "tpuflow.org"
VERSION = "v1"
KIND = "TPUJob"
PLURAL = "tpujobs"
SINGULAR = "tpujob"
CRD_NAME = f"{PLURAL}.{GROUP_NAME}"
API_VERSION = f"{GROUP_NAME}/{VERSION}"

# The container in each replica pod template that receives the cluster
# topology contract.  Kept as "tensorflow" for drop-in parity with the
# reference (v1alpha2/constants.go: DefaultContainerName), so existing TFJob
# pod templates keep working.
DEFAULT_CONTAINER_NAME = "tensorflow"

# Named port on the default container used for the gRPC rendezvous mesh
# (v1alpha2/constants.go: DefaultPortName/DefaultPort).
DEFAULT_PORT_NAME = "tfjob-port"
DEFAULT_PORT = 2222

# Cross-slice (DCN) rendezvous port for multislice jobs: the MEGASCALE
# coordinator must NOT share the in-slice coordinator's port — on slice 0's
# worker 0 BOTH services live in one pod, and real multislice separates them
# the same way (jax coordinator :8471 vs MEGASCALE coordinator :8080). By
# convention the DCN port is the job port + this offset; the local executor
# maps it per pod like the main port.
DCN_PORT_OFFSET = 1

# Labels stamped on every pod/service the controller creates.  Parity with
# jobcontroller.GenLabels (jobcontroller.go:132-140) + the pod-level
# tf-replica-type / tf-replica-index labels (controller_pod.go:109-128).
LABEL_GROUP_NAME = "group-name"
LABEL_JOB_NAME = "tpu-job-name"
LABEL_REPLICA_TYPE = "tpu-replica-type"
LABEL_REPLICA_INDEX = "tpu-replica-index"
LABEL_JOB_ROLE = "job-role"

# Env var names of the injected topology contract (the TF_CONFIG analog;
# reference: controller_tensorflow.go:66-96 emits only TF_CONFIG).
ENV_TF_CONFIG = "TF_CONFIG"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_ACCELERATOR_TYPE = "TPU_ACCELERATOR_TYPE"
ENV_TPU_TOPOLOGY = "TPU_TOPOLOGY"
ENV_COORDINATOR_ADDRESS = "TPU_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "TPU_NUM_PROCESSES"

# Namespace the operator itself runs in (KUBEFLOW_NAMESPACE analog,
# v1alpha2/constants.go:18-19).
ENV_OPERATOR_NAMESPACE = "TPUFLOW_NAMESPACE"
DEFAULT_OPERATOR_NAMESPACE = "default"
