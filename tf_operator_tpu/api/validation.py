"""Validation for TPUJob specs.

Parity: pkg/apis/tensorflow/validation/validation.go:29-55
(ValidateAlphaTwoTFJobSpec): every replica set has containers, images are
non-empty, at least one container is named after the default container; plus
the TPU-native rules (valid accelerator/topology, replicas consistent with
slice host count, at most one Chief).  Validation runs at decode time, as the
reference does in its unstructured informer (informer.go:87-110), so a
malformed CR is rejected with an event instead of wedging the controller.
"""

from __future__ import annotations

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    CleanPodPolicy,
    ReplicaType,
    RestartPolicy,
    TPUJobSpec,
)
from tf_operator_tpu.topology import slices


class ValidationError(ValueError):
    """A TPUJob spec that must be rejected at admission/decode time."""


def validate_spec(spec: TPUJobSpec) -> None:
    if not spec.replica_specs:
        raise ValidationError("replicaSpecs must not be empty")

    if spec.clean_pod_policy is not None and spec.clean_pod_policy not in CleanPodPolicy.CHOICES:
        raise ValidationError(
            f"cleanPodPolicy {spec.clean_pod_policy!r} not in {CleanPodPolicy.CHOICES}"
        )
    if spec.ttl_seconds_after_finished is not None and spec.ttl_seconds_after_finished < 0:
        raise ValidationError("ttlSecondsAfterFinished must be >= 0")

    for rtype, replica in spec.replica_specs.items():
        where = f"replicaSpecs[{rtype}]"
        if rtype not in ReplicaType.ALL:
            raise ValidationError(
                f"{where}: unknown replica type; expected one of {ReplicaType.ALL}"
            )
        if replica.restart_policy is not None and replica.restart_policy not in RestartPolicy.ALL:
            raise ValidationError(
                f"{where}: restartPolicy {replica.restart_policy!r} not in {RestartPolicy.ALL}"
            )
        if replica.replicas is not None and replica.replicas < 0:
            raise ValidationError(f"{where}: replicas must be >= 0")

        containers = replica.template.get("spec", {}).get("containers", [])
        if not containers:
            raise ValidationError(f"{where}: template.spec.containers is empty")
        default_found = False
        for i, c in enumerate(containers):
            if not c.get("image"):
                raise ValidationError(f"{where}: containers[{i}].image is empty")
            if c.get("name") == constants.DEFAULT_CONTAINER_NAME:
                default_found = True
        if not default_found:
            raise ValidationError(
                f"{where}: no container named "
                f"{constants.DEFAULT_CONTAINER_NAME!r} (the topology contract "
                f"is injected into that container only)"
            )

        if replica.tpu and replica.tpu.accelerator_type:
            if replica.tpu.num_slices < 1:
                raise ValidationError(f"{where}: tpu.numSlices must be >= 1")
            try:
                topo = slices.resolve(replica.tpu.accelerator_type, replica.tpu.topology)
            except slices.TopologyError as e:
                raise ValidationError(f"{where}: {e}") from e
            want = topo.num_hosts * replica.tpu.num_slices
            if replica.replicas is not None and replica.replicas != want:
                raise ValidationError(
                    f"{where}: replicas={replica.replicas} inconsistent with "
                    f"{replica.tpu.accelerator_type} × {replica.tpu.num_slices} "
                    f"slice(s) = {want} host pod(s)"
                )

    chief = spec.replica_specs.get(ReplicaType.CHIEF)
    if chief is not None and (chief.replicas or 0) > 1:
        raise ValidationError("replicaSpecs[Chief]: at most 1 chief replica is allowed")
