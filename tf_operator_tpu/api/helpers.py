"""Small API-object helpers.

Parity: pkg/apis/tensorflow/helper/helpers.go:36-47 (AsOwner) and the
label-selector builders. The accelerator-config-injection half of that file
(helpers.go:50-104, nvidia.com/gpu volumes) is superseded by the first-class
TPU slice spec — see topology/slices.py and controller/cluster_spec.py.
"""

from __future__ import annotations

from typing import Any

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import TPUJob


def as_owner(job: TPUJob) -> dict[str, Any]:
    """Controller OwnerReference for resources created on behalf of a job."""
    return {
        "apiVersion": job.api_version,
        "kind": job.kind,
        "name": job.metadata.name,
        "uid": job.metadata.uid,
        "controller": True,
        "blockOwnerDeletion": True,
    }


def gen_labels(job_name: str) -> dict[str, str]:
    """Base labels for everything owned by a job (jobcontroller.go:132-140)."""
    return {
        constants.LABEL_GROUP_NAME: constants.GROUP_NAME,
        constants.LABEL_JOB_NAME: job_name,
    }


def replica_labels(job_name: str, replica_type: str, index: int) -> dict[str, str]:
    labels = gen_labels(job_name)
    labels[constants.LABEL_REPLICA_TYPE] = replica_type.lower()
    labels[constants.LABEL_REPLICA_INDEX] = str(index)
    return labels


def labels_to_selector(labels: dict[str, str]) -> str:
    """K8s label-selector string, sorted for determinism."""
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def selector_matches(selector: dict[str, str], labels: dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def is_controlled_by(obj_meta: dict[str, Any], job: TPUJob) -> bool:
    """True when obj's controller ownerReference points at this job (by UID)."""
    for ref in obj_meta.get("ownerReferences", []):
        if ref.get("controller") and ref.get("uid") == job.metadata.uid:
            return True
    return False


def get_controller_of(obj_meta: dict[str, Any]) -> dict[str, Any] | None:
    for ref in obj_meta.get("ownerReferences", []):
        if ref.get("controller"):
            return ref
    return None
