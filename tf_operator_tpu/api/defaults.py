"""Defaulting for TPUJob specs.

Parity: pkg/apis/tensorflow/v1alpha2/defaults.go:35-106 —
CleanPodPolicy→Running, replicas→1, RestartPolicy→Never, inject the named
rendezvous port on the default container, normalize replica-type key case —
plus the TPU-specific rules: a replica set bound to a slice gets
replicas = num_hosts × num_slices (one pod per TPU host), and gang
scheduling resolves to "on" whenever any multi-host slice is present.
"""

from __future__ import annotations

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    CleanPodPolicy,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    TPUJob,
    TPUJobSpec,
)
from tf_operator_tpu.topology import slices

_CANONICAL_TYPES = {t.lower(): t for t in ReplicaType.ALL}


def canonical_replica_type(name: str) -> str:
    """'ps' → 'PS', 'worker' → 'Worker' (defaults.go setTypeNamesToCamelCase)."""
    return _CANONICAL_TYPES.get(name.lower(), name)


def _default_port(replica: ReplicaSpec) -> None:
    """Ensure the default container exposes the named rendezvous port
    (defaults.go setDefaultPort)."""
    containers = replica.template.get("spec", {}).get("containers", [])
    for c in containers:
        if c.get("name") != constants.DEFAULT_CONTAINER_NAME:
            continue
        ports = c.setdefault("ports", [])
        if not any(p.get("name") == constants.DEFAULT_PORT_NAME for p in ports):
            ports.append(
                {
                    "name": constants.DEFAULT_PORT_NAME,
                    "containerPort": constants.DEFAULT_PORT,
                }
            )


def _default_replicas(replica: ReplicaSpec) -> None:
    if replica.tpu and replica.tpu.accelerator_type:
        topo = slices.resolve(replica.tpu.accelerator_type, replica.tpu.topology)
        want = topo.num_hosts * max(1, replica.tpu.num_slices)
        # A slice binding fully determines the pod count; an explicit replicas
        # that disagrees is corrected here and flagged by validation.
        if replica.replicas is None:
            replica.replicas = want
        # Record the inferred topology so downstream layers don't re-derive.
        if replica.tpu.topology is None:
            replica.tpu.topology = topo.topology
    elif replica.replicas is None:
        replica.replicas = 1


def set_defaults_spec(spec: TPUJobSpec) -> TPUJobSpec:
    # Normalize replica-type key case first so later logic sees canonical keys.
    spec.replica_specs = {
        canonical_replica_type(t): r for t, r in spec.replica_specs.items()
    }
    if spec.clean_pod_policy is None:
        spec.clean_pod_policy = CleanPodPolicy.RUNNING

    any_multi_host = False
    for replica in spec.replica_specs.values():
        if replica.restart_policy is None:
            replica.restart_policy = RestartPolicy.NEVER
        _default_replicas(replica)
        _default_port(replica)
        if replica.tpu and replica.tpu.accelerator_type:
            topo = slices.resolve(replica.tpu.accelerator_type, replica.tpu.topology)
            any_multi_host = any_multi_host or topo.multi_host

    if spec.scheduling.gang is None:
        spec.scheduling.gang = any_multi_host
    return spec


def set_defaults(job: TPUJob) -> TPUJob:
    """Apply defaults in place (scheme.Default analog) and return the job."""
    set_defaults_spec(job.spec)
    return job
