"""TPUServe resource schema: long-running serving fleets.

Where a TPUJob runs to completion, a TPUServe keeps ``replicas`` serving
processes alive indefinitely: each replica is a gang-admitted child
TPUJob (a serve_lm-equivalent entrypoint behind the continuous engine's
supervisor), the fleet controller (tf_operator_tpu/fleet/controller.py)
owns membership and replacement, a router spreads traffic by live
occupancy/queue depth, and an autoscaler grows/shrinks the fleet between
``minReplicas`` and ``maxReplicas``.

The object round-trips to/from plain dicts like TPUJob (api/types.py) so
both cluster backends store it unchanged; the typed layer carries
defaults/validation/controller logic. TF-Replicator's replica
abstraction (arxiv 1902.00465) is the model: placement, membership and
traffic wiring belong to the framework, not the user.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    JobCondition,
    ObjectMeta,
    SchedulingPolicy,
    TPUSliceSpec,
)

# CRD coordinates (same group/version as TPUJob).
KIND_SERVE = "TPUServe"
PLURAL_SERVE = "tpuserves"
SERVE_API_VERSION = constants.API_VERSION

# Env vars injected into each replica's default container: a
# serve_lm-style entrypoint reads them as defaults for --port /
# --replica-id, so one pod template serves every replica index.
ENV_SERVE_PORT = "TPU_SERVE_PORT"
ENV_SERVE_REPLICA_ID = "TPU_SERVE_REPLICA_ID"
ENV_SERVE_MODEL_VERSION = "TPU_SERVE_MODEL_VERSION"
ENV_SERVE_ROLE = "TPU_SERVE_ROLE"

# Child-job wiring (fleet/controller.py): each replica is one child
# TPUJob named "{serve}-r{index}" (decode pool) or "{serve}-p{index}"
# (prefill pool). The label pair is the child selector; the version
# rides an ANNOTATION because model versions are arbitrary strings
# (checkpoint paths), not label-safe values; the role label splits a
# disaggregated fleet's children into its two pools.
LABEL_SERVE_NAME = "fleet.tpuflow.org/serve"
LABEL_SERVE_INDEX = "fleet.tpuflow.org/index"
LABEL_SERVE_ROLE = "fleet.tpuflow.org/role"
ANNOTATION_MODEL_VERSION = "fleet.tpuflow.org/model-version"

# Replica roles. "" on the SPEC means a unified fleet (every replica
# both prefills and decodes — the pre-disaggregation shape); "decode"/
# "prefill" pin a whole TPUServe to one pool (operators running the
# pools as two objects). On a CHILD job the role label is always
# explicit.
ROLE_UNIFIED = ""
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
SERVE_ROLES = (ROLE_UNIFIED, ROLE_PREFILL, ROLE_DECODE)

# Prefill-pool endpoints live at portBase + PREFILL_PORT_OFFSET + index
# so the two pools' port spans can never collide; validate_serve_spec
# bounds both spans.
PREFILL_PORT_OFFSET = 1000


@dataclass
class AutoscalePolicy:
    """Queue-depth / TTFT driven horizontal scaling. Disabled by default:
    a TPUServe then holds exactly ``spec.replicas`` replicas."""

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 8
    # Scale up when aggregate queue depth per READY replica exceeds this.
    queue_high: float = 8.0
    # Scale down when it drops under this (and the TTFT trigger is quiet).
    queue_low: float = 1.0
    # Scale up when fleet TTFT p99 exceeds this (0 disables the trigger).
    ttft_p99_high_s: float = 0.0
    # Decode-pool signals (disaggregated serving; 0 disables each):
    # scale up when the fleet's worst inter-token-latency p99 exceeds
    # this — shipped joins barely queue, so a saturated decode pool
    # shows in its step time first…
    itl_p99_high_s: float = 0.0
    # …or when mean active-slot occupancy across ready replicas does.
    occupancy_high: float = 0.0
    scale_up_cooldown_s: float = 5.0
    scale_down_cooldown_s: float = 30.0

    def to_dict(self) -> dict[str, Any]:
        return {
            "enabled": self.enabled,
            "minReplicas": self.min_replicas,
            "maxReplicas": self.max_replicas,
            "queueHigh": self.queue_high,
            "queueLow": self.queue_low,
            "ttftP99HighSeconds": self.ttft_p99_high_s,
            "itlP99HighSeconds": self.itl_p99_high_s,
            "occupancyHigh": self.occupancy_high,
            "scaleUpCooldownSeconds": self.scale_up_cooldown_s,
            "scaleDownCooldownSeconds": self.scale_down_cooldown_s,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "AutoscalePolicy":
        return cls(
            enabled=bool(d.get("enabled", False)),
            min_replicas=int(d.get("minReplicas", 1)),
            max_replicas=int(d.get("maxReplicas", 8)),
            queue_high=float(d.get("queueHigh", 8.0)),
            queue_low=float(d.get("queueLow", 1.0)),
            ttft_p99_high_s=float(d.get("ttftP99HighSeconds", 0.0)),
            itl_p99_high_s=float(d.get("itlP99HighSeconds", 0.0)),
            occupancy_high=float(d.get("occupancyHigh", 0.0)),
            scale_up_cooldown_s=float(d.get("scaleUpCooldownSeconds", 5.0)),
            scale_down_cooldown_s=float(
                d.get("scaleDownCooldownSeconds", 30.0)
            ),
        )


@dataclass
class PrefixRoutingPolicy:
    """Fleet-global prefix reuse (fleet/prefixes.py). Disabled by
    default: the router keeps the plain least-loaded pick. Enabled, the
    router scores ``load - weight * hit_fraction`` over the replicas'
    advertised prefix digest chains, routes sessions home while home
    stays routable, and (``pull``) fetches a missing exact-prefix entry
    from the replica that advertises it before falling back to a full
    local prefill."""

    enabled: bool = False
    # Load units a FULL prefix hit outbids; 0.0 degrades to
    # least-loaded even when enabled (advertisements still flow).
    weight: float = 1.0
    # MUST match the replica engines' paged KV block size — the digest
    # chain is block-aligned and hashes per block.
    kv_block: int = 64
    session_affinity: bool = True
    pull: bool = True
    pull_timeout_s: float = 5.0
    # Hot entries each replica advertises on /healthz (MRU first).
    advertise_max: int = 32

    def to_dict(self) -> dict[str, Any]:
        return {
            "enabled": self.enabled,
            "weight": self.weight,
            "kvBlock": self.kv_block,
            "sessionAffinity": self.session_affinity,
            "pull": self.pull,
            "pullTimeoutSeconds": self.pull_timeout_s,
            "advertiseMax": self.advertise_max,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PrefixRoutingPolicy":
        return cls(
            enabled=bool(d.get("enabled", False)),
            weight=float(d.get("weight", 1.0)),
            kv_block=int(d.get("kvBlock", 64)),
            session_affinity=bool(d.get("sessionAffinity", True)),
            pull=bool(d.get("pull", True)),
            pull_timeout_s=float(d.get("pullTimeoutSeconds", 5.0)),
            advertise_max=int(d.get("advertiseMax", 32)),
        )


@dataclass
class TPUServeSpec:
    """One serving fleet: N replicas of one pod template."""

    replicas: int = 1
    # core/v1 PodTemplateSpec (unstructured) for ONE replica's serve
    # process; the controller injects TPU_SERVE_PORT/TPU_SERVE_REPLICA_ID.
    template: dict[str, Any] = field(default_factory=dict)
    # Per-replica TPU slice binding (each replica is its own gang).
    tpu: TPUSliceSpec | None = None
    # Replica endpoints are host:(port_base + per-fleet offset); the
    # local executor serves everything on one host.
    host: str = "127.0.0.1"
    port_base: int = 9100
    # Rolling-update key: changing it surges a new-version replica per
    # index, waits for readiness, then drains the old one.
    model_version: str = ""
    # Disaggregated prefill/decode (serve/disagg.py). ``role`` pins the
    # WHOLE fleet to one pool ("" = unified); ``prefill_replicas`` > 0
    # (unified/decode fleets only) makes the controller reconcile a
    # SECOND child pool — "{serve}-p{index}" prefill replicas at
    # portBase + PREFILL_PORT_OFFSET + index — scaled by
    # ``prefill_autoscale`` on prefill queue depth, while the decode
    # pool keeps ``autoscale`` (occupancy/ITL signals).
    role: str = ROLE_UNIFIED
    prefill_replicas: int = 0
    prefill_autoscale: AutoscalePolicy = field(
        default_factory=AutoscalePolicy
    )
    # Seconds a scale-down/rolling-update replica stays DRAINING (router
    # deregistered, scheduler preemption-exempt) before its child job is
    # deleted and the SIGTERM bounded drain runs.
    scale_down_grace_s: float = 5.0
    autoscale: AutoscalePolicy = field(default_factory=AutoscalePolicy)
    # Fleet-global prefix reuse: prefix-aware routing + session
    # affinity + cross-replica KV pulls for this fleet's router.
    prefix_routing: PrefixRoutingPolicy = field(
        default_factory=PrefixRoutingPolicy
    )
    scheduling: SchedulingPolicy = field(default_factory=SchedulingPolicy)

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"replicas": self.replicas}
        if self.template:
            d["template"] = copy.deepcopy(self.template)
        if self.tpu is not None:
            d["tpu"] = self.tpu.to_dict()
        if self.host != "127.0.0.1":
            d["host"] = self.host
        if self.port_base != 9100:
            d["portBase"] = self.port_base
        if self.model_version:
            d["modelVersion"] = self.model_version
        if self.scale_down_grace_s != 5.0:
            d["scaleDownGraceSeconds"] = self.scale_down_grace_s
        if self.role:
            d["role"] = self.role
        if self.prefill_replicas:
            d["prefillReplicas"] = self.prefill_replicas
        if self.prefill_autoscale != AutoscalePolicy():
            d["prefillAutoscale"] = self.prefill_autoscale.to_dict()
        auto = self.autoscale.to_dict()
        if self.autoscale != AutoscalePolicy():
            d["autoscale"] = auto
        if self.prefix_routing != PrefixRoutingPolicy():
            d["prefixRouting"] = self.prefix_routing.to_dict()
        sched = self.scheduling.to_dict()
        if sched:
            d["scheduling"] = sched
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TPUServeSpec":
        return cls(
            replicas=int(d.get("replicas", 1)),
            template=copy.deepcopy(d.get("template", {})),
            tpu=TPUSliceSpec.from_dict(d["tpu"]) if d.get("tpu") else None,
            host=d.get("host", "127.0.0.1"),
            port_base=int(d.get("portBase", 9100)),
            model_version=str(d.get("modelVersion", "")),
            scale_down_grace_s=float(d.get("scaleDownGraceSeconds", 5.0)),
            role=str(d.get("role", ROLE_UNIFIED)),
            prefill_replicas=int(d.get("prefillReplicas", 0)),
            prefill_autoscale=AutoscalePolicy.from_dict(
                d.get("prefillAutoscale", {})
            ),
            autoscale=AutoscalePolicy.from_dict(d.get("autoscale", {})),
            prefix_routing=PrefixRoutingPolicy.from_dict(
                d.get("prefixRouting", {})
            ),
            scheduling=SchedulingPolicy.from_dict(d.get("scheduling", {})),
        )


@dataclass
class TPUServeStatus:
    """Fleet roll-up: child-job + membership counts by readiness."""

    replicas: int = 0       # child jobs that exist
    ready: int = 0          # membership READY (router-routable)
    draining: int = 0
    # CUMULATIVE replicas declared dead over the fleet's lifetime: a
    # dead replica is deleted and replaced within the same sync, so a
    # point-in-time count would always read 0.
    dead: int = 0
    target: int = 0         # current desired count (autoscaler-adjusted)
    model_version: str = ""  # version every READY replica serves
    # Prefill pool roll-up (disaggregated fleets; all 0 otherwise).
    prefill_replicas: int = 0
    prefill_ready: int = 0
    prefill_target: int = 0
    conditions: list[JobCondition] = field(default_factory=list)
    last_reconcile_time: str | None = None

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "replicas": self.replicas,
            "ready": self.ready,
            "draining": self.draining,
            "dead": self.dead,
            "target": self.target,
        }
        if self.prefill_replicas or self.prefill_target \
                or self.prefill_ready:
            d["prefillReplicas"] = self.prefill_replicas
            d["prefillReady"] = self.prefill_ready
            d["prefillTarget"] = self.prefill_target
        if self.model_version:
            d["modelVersion"] = self.model_version
        if self.conditions:
            d["conditions"] = [c.to_dict() for c in self.conditions]
        if self.last_reconcile_time:
            d["lastReconcileTime"] = self.last_reconcile_time
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TPUServeStatus":
        return cls(
            replicas=int(d.get("replicas", 0)),
            ready=int(d.get("ready", 0)),
            draining=int(d.get("draining", 0)),
            dead=int(d.get("dead", 0)),
            target=int(d.get("target", 0)),
            model_version=str(d.get("modelVersion", "")),
            prefill_replicas=int(d.get("prefillReplicas", 0)),
            prefill_ready=int(d.get("prefillReady", 0)),
            prefill_target=int(d.get("prefillTarget", 0)),
            conditions=[
                JobCondition.from_dict(c) for c in d.get("conditions", [])
            ],
            last_reconcile_time=d.get("lastReconcileTime"),
        )


@dataclass
class TPUServe:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUServeSpec = field(default_factory=TPUServeSpec)
    status: TPUServeStatus = field(default_factory=TPUServeStatus)

    api_version: str = SERVE_API_VERSION
    kind: str = KIND_SERVE

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def to_dict(self) -> dict[str, Any]:
        return {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TPUServe":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata", {})),
            spec=TPUServeSpec.from_dict(d.get("spec", {})),
            status=TPUServeStatus.from_dict(d.get("status", {})),
            api_version=d.get("apiVersion", SERVE_API_VERSION),
            kind=d.get("kind", KIND_SERVE),
        )


class ServeValidationError(ValueError):
    """A TPUServe spec that must be rejected at decode time."""


def validate_serve_spec(spec: TPUServeSpec) -> None:
    if spec.replicas < 0:
        raise ServeValidationError("replicas must be >= 0")
    if spec.port_base < 1 or spec.port_base > 65000:
        raise ServeValidationError("portBase must be in [1, 65000]")
    if spec.scale_down_grace_s < 0:
        raise ServeValidationError("scaleDownGraceSeconds must be >= 0")
    containers = spec.template.get("spec", {}).get("containers", [])
    if not containers:
        raise ServeValidationError("template.spec.containers is empty")
    if not any(
        c.get("name") == constants.DEFAULT_CONTAINER_NAME for c in containers
    ):
        raise ServeValidationError(
            f"no container named {constants.DEFAULT_CONTAINER_NAME!r} "
            "(serve env is injected into that container only)"
        )
    if spec.role not in SERVE_ROLES:
        raise ServeValidationError(
            f"role must be one of {SERVE_ROLES!r}, got {spec.role!r}"
        )
    if spec.prefill_replicas < 0:
        raise ServeValidationError("prefillReplicas must be >= 0")
    if spec.role == ROLE_PREFILL and (
            spec.prefill_replicas or spec.prefill_autoscale.enabled):
        raise ServeValidationError(
            "a role=prefill fleet IS a prefill pool; prefillReplicas/"
            "prefillAutoscale only apply to unified/decode fleets "
            "growing a second pool"
        )
    auto = spec.autoscale
    if auto.min_replicas < 0 or auto.max_replicas < max(1, auto.min_replicas):
        raise ServeValidationError(
            "autoscale bounds must satisfy 0 <= minReplicas <= maxReplicas "
            "(maxReplicas >= 1)"
        )
    if auto.enabled and auto.queue_low > auto.queue_high:
        raise ServeValidationError(
            "autoscale.queueLow must be <= autoscale.queueHigh "
            "(the hysteresis band must not invert)"
        )
    pauto = spec.prefill_autoscale
    if pauto.enabled and pauto.queue_low > pauto.queue_high:
        raise ServeValidationError(
            "prefillAutoscale.queueLow must be <= queueHigh "
            "(the hysteresis band must not invert)"
        )
    pr = spec.prefix_routing
    if pr.enabled:
        if pr.kv_block < 1:
            raise ServeValidationError(
                "prefixRouting.kvBlock must be >= 1 (and must match "
                "the replica engines' paged KV block size)"
            )
        if pr.weight < 0:
            raise ServeValidationError(
                "prefixRouting.weight must be >= 0 (0 routes "
                "least-loaded; negative would PENALIZE prefix hits)"
            )
        if pr.advertise_max < 1:
            raise ServeValidationError(
                "prefixRouting.advertiseMax must be >= 1 (nothing "
                "advertised means nothing to score or pull)"
            )
        if pr.pull and pr.pull_timeout_s <= 0:
            raise ServeValidationError(
                "prefixRouting.pullTimeoutSeconds must be > 0 when "
                "pulls are enabled"
            )
    # Replica ports are portBase + index; index allocation is bounded
    # by the fleet's peak width plus indices quarantined after removal,
    # so the span above portBase must hold twice the widest the fleet
    # can get (surge replica included) — otherwise a valid spec could
    # hand a replica a port past 65535 that it can never bind.
    ceiling = max(spec.replicas, auto.max_replicas if auto.enabled else 0)
    if 2 * (ceiling + 1) > 65535 - spec.port_base:
        raise ServeValidationError(
            f"portBase {spec.port_base} leaves only "
            f"{65535 - spec.port_base} ports above it; a fleet that can "
            f"reach {ceiling} replicas needs 2*(replicas+1) for surge "
            "and quarantined-index headroom"
        )
    if spec.prefill_replicas or pauto.enabled:
        # Decode indices live in [0, PREFILL_PORT_OFFSET); prefill
        # indices at portBase + PREFILL_PORT_OFFSET + i. Both spans must
        # fit, and the decode span must stay clear of the offset.
        if 2 * (ceiling + 1) > PREFILL_PORT_OFFSET:
            raise ServeValidationError(
                f"a disaggregated fleet's decode pool is bounded at "
                f"{PREFILL_PORT_OFFSET // 2 - 1} replicas (the prefill "
                f"pool's ports start at portBase + {PREFILL_PORT_OFFSET})"
            )
        p_ceiling = max(
            spec.prefill_replicas,
            pauto.max_replicas if pauto.enabled else 0,
        )
        if (spec.port_base + PREFILL_PORT_OFFSET
                + 2 * (p_ceiling + 1) > 65535):
            raise ServeValidationError(
                f"portBase {spec.port_base} + prefill offset "
                f"{PREFILL_PORT_OFFSET} leaves no headroom for a "
                f"prefill pool of {p_ceiling} replicas"
            )
