"""TPUJob resource schema.

The user-facing API: one modern schema modeled on the reference's v1alpha2
generation (pkg/apis/tensorflow/v1alpha2/types.go:28-230) — replica *map*
rather than list, condition-based status rather than phases — extended with a
first-class TPU pod-slice spec per replica set.

Objects round-trip to/from plain dicts (the "unstructured" form) because the
runtime store, the REST dashboard, and the YAML examples all speak dicts; the
typed layer exists for defaults/validation/controller logic, exactly the role
the generated Go structs play in the reference.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any

from tf_operator_tpu.api import constants


# ---------------------------------------------------------------------------
# Enums (string-valued, as in the reference API group)
# ---------------------------------------------------------------------------

class ReplicaType:
    """Parity: v1alpha2/types.go:117-132 (PS/Worker/Chief/Evaluator)."""

    CHIEF = "Chief"
    WORKER = "Worker"
    PS = "PS"
    EVALUATOR = "Evaluator"

    ALL = (CHIEF, WORKER, PS, EVALUATOR)


class RestartPolicy:
    """Parity: v1alpha2/types.go:99-112, incl. the ExitCode policy."""

    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    NEVER = "Never"
    EXIT_CODE = "ExitCode"

    ALL = (ALWAYS, ON_FAILURE, NEVER, EXIT_CODE)


class CleanPodPolicy:
    """Parity: v1alpha2/types.go:86-93."""

    NONE = "None"
    RUNNING = "Running"
    ALL = "All"

    CHOICES = (NONE, RUNNING, ALL)


class JobConditionType:
    """Parity: v1alpha2/types.go:190-216, extended with the fleet-health
    conditions (SliceDegraded: the gang's cells carry open suspicion or a
    cordon; JobMigrating: the gang was evicted off draining/cordoned cells
    and awaits re-placement). Both are auxiliary — they ride alongside the
    lifecycle conditions and never gate the terminal state machine."""

    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    SLICE_DEGRADED = "SliceDegraded"
    JOB_MIGRATING = "JobMigrating"
    # Checkpoint coordination (ckpt/registry.py; auxiliary like the two
    # above): CheckpointStale — a Running job's checkpoint roll-up has gone
    # quiet past the staleness threshold; CheckpointSkipped — the last
    # eviction proceeded past the grace deadline without a checkpoint ack.
    CHECKPOINT_STALE = "CheckpointStale"
    CHECKPOINT_SKIPPED = "CheckpointSkipped"

    ALL = (CREATED, RUNNING, RESTARTING, SUCCEEDED, FAILED,
           SLICE_DEGRADED, JOB_MIGRATING, CHECKPOINT_STALE,
           CHECKPOINT_SKIPPED)


# ---------------------------------------------------------------------------
# Spec
# ---------------------------------------------------------------------------

@dataclass
class TPUSliceSpec:
    """First-class TPU pod-slice binding for a replica set.

    This replaces the reference's nvidia.com/gpu resource-limit path
    (helper/helpers.go:50-104): instead of "this container wants 2 GPUs",
    a replica set declares "this replica set *is* a v5e-16 slice" and the
    controller derives host count, gang semantics, node placement, and the
    runtime mesh env from it.
    """

    accelerator_type: str = ""  # e.g. "v5e-16"
    topology: str | None = None  # e.g. "4x4"; inferred when omitted
    # Run this many independent slices (each gets its own gang); analog of
    # multislice training over DCN.
    num_slices: int = 1

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"acceleratorType": self.accelerator_type}
        if self.topology:
            d["topology"] = self.topology
        if self.num_slices != 1:
            d["numSlices"] = self.num_slices
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TPUSliceSpec":
        return cls(
            accelerator_type=d.get("acceleratorType", ""),
            topology=d.get("topology"),
            num_slices=int(d.get("numSlices", 1)),
        )


@dataclass
class ReplicaSpec:
    """One role's replica set. Parity: v1alpha2/types.go:68-84.

    ``template`` is a core/v1 PodTemplateSpec kept unstructured (dict), as
    the reference keeps the full v1.PodTemplateSpec.
    """

    replicas: int | None = None
    template: dict[str, Any] = field(default_factory=dict)
    restart_policy: str | None = None
    tpu: TPUSliceSpec | None = None

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {}
        if self.replicas is not None:
            d["replicas"] = self.replicas
        if self.template:
            d["template"] = copy.deepcopy(self.template)
        if self.restart_policy is not None:
            d["restartPolicy"] = self.restart_policy
        if self.tpu is not None:
            d["tpu"] = self.tpu.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ReplicaSpec":
        return cls(
            replicas=d.get("replicas"),
            template=copy.deepcopy(d.get("template", {})),
            restart_policy=d.get("restartPolicy"),
            tpu=TPUSliceSpec.from_dict(d["tpu"]) if d.get("tpu") else None,
        )


@dataclass
class SchedulingPolicy:
    """Gang-scheduling knobs (the reference exposes only an operator-level
    --enable-gang-scheduling flag + kube-arbitrator schedulerName on pods;
    jobcontroller.go:196-249). Promoted to the job spec here because on TPU
    gang semantics are per-slice correctness, not an optional optimization."""

    gang: bool | None = None  # None → auto: true iff any multi-host slice
    scheduler_name: str | None = None
    # Priority class propagated to pods, useful for preemption experiments.
    priority_class: str | None = None

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {}
        if self.gang is not None:
            d["gang"] = self.gang
        if self.scheduler_name:
            d["schedulerName"] = self.scheduler_name
        if self.priority_class:
            d["priorityClass"] = self.priority_class
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "SchedulingPolicy":
        return cls(
            gang=d.get("gang"),
            scheduler_name=d.get("schedulerName"),
            priority_class=d.get("priorityClass"),
        )


@dataclass
class TPUJobSpec:
    """Parity: v1alpha2/types.go:40-66 (TFJobSpec)."""

    replica_specs: dict[str, ReplicaSpec] = field(default_factory=dict)
    clean_pod_policy: str | None = None
    ttl_seconds_after_finished: int | None = None
    scheduling: SchedulingPolicy = field(default_factory=SchedulingPolicy)
    # Backoff limit for whole-job restarts under Restarting (slice-granular
    # restarts count); None = unlimited, as the reference behaves.
    max_restarts: int | None = None

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "replicaSpecs": {t: r.to_dict() for t, r in self.replica_specs.items()},
        }
        if self.clean_pod_policy is not None:
            d["cleanPodPolicy"] = self.clean_pod_policy
        if self.ttl_seconds_after_finished is not None:
            d["ttlSecondsAfterFinished"] = self.ttl_seconds_after_finished
        sched = self.scheduling.to_dict()
        if sched:
            d["scheduling"] = sched
        if self.max_restarts is not None:
            d["maxRestarts"] = self.max_restarts
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TPUJobSpec":
        return cls(
            replica_specs={
                t: ReplicaSpec.from_dict(r)
                for t, r in d.get("replicaSpecs", {}).items()
            },
            clean_pod_policy=d.get("cleanPodPolicy"),
            ttl_seconds_after_finished=d.get("ttlSecondsAfterFinished"),
            scheduling=SchedulingPolicy.from_dict(d.get("scheduling", {})),
            max_restarts=d.get("maxRestarts"),
        )


# ---------------------------------------------------------------------------
# Status
# ---------------------------------------------------------------------------

@dataclass
class JobCondition:
    """Parity: v1alpha2/types.go:172-216 (TFJobCondition)."""

    type: str = ""
    status: str = "True"  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_update_time: str = ""
    last_transition_time: str = ""

    def to_dict(self) -> dict[str, Any]:
        return {
            "type": self.type,
            "status": self.status,
            "reason": self.reason,
            "message": self.message,
            "lastUpdateTime": self.last_update_time,
            "lastTransitionTime": self.last_transition_time,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "JobCondition":
        return cls(
            type=d.get("type", ""),
            status=d.get("status", "True"),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            last_update_time=d.get("lastUpdateTime", ""),
            last_transition_time=d.get("lastTransitionTime", ""),
        )


@dataclass
class ReplicaStatus:
    """Parity: v1alpha2/types.go:159-169 (TFReplicaStatus)."""

    active: int = 0
    succeeded: int = 0
    failed: int = 0

    def to_dict(self) -> dict[str, Any]:
        return {"active": self.active, "succeeded": self.succeeded, "failed": self.failed}

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ReplicaStatus":
        return cls(
            active=int(d.get("active", 0)),
            succeeded=int(d.get("succeeded", 0)),
            failed=int(d.get("failed", 0)),
        )


@dataclass
class TPUJobStatus:
    """Parity: v1alpha2/types.go:134-169 (TFJobStatus)."""

    conditions: list[JobCondition] = field(default_factory=list)
    replica_statuses: dict[str, ReplicaStatus] = field(default_factory=dict)
    start_time: str | None = None
    completion_time: str | None = None
    last_reconcile_time: str | None = None
    restart_count: int = 0
    # Latest checkpoint step acked by the job's workers (ckpt/registry.py
    # roll-up); None until the first durable save is reported.
    last_checkpoint_step: int | None = None

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "conditions": [c.to_dict() for c in self.conditions],
            "replicaStatuses": {t: s.to_dict() for t, s in self.replica_statuses.items()},
        }
        if self.start_time:
            d["startTime"] = self.start_time
        if self.completion_time:
            d["completionTime"] = self.completion_time
        if self.last_reconcile_time:
            d["lastReconcileTime"] = self.last_reconcile_time
        if self.restart_count:
            d["restartCount"] = self.restart_count
        if self.last_checkpoint_step is not None:
            d["lastCheckpointStep"] = self.last_checkpoint_step
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TPUJobStatus":
        return cls(
            conditions=[JobCondition.from_dict(c) for c in d.get("conditions", [])],
            replica_statuses={
                t: ReplicaStatus.from_dict(s)
                for t, s in d.get("replicaStatuses", {}).items()
            },
            start_time=d.get("startTime"),
            completion_time=d.get("completionTime"),
            last_reconcile_time=d.get("lastReconcileTime"),
            restart_count=int(d.get("restartCount", 0)),
            last_checkpoint_step=(
                int(d["lastCheckpointStep"])
                if d.get("lastCheckpointStep") is not None
                else None
            ),
        )


# ---------------------------------------------------------------------------
# Top-level object
# ---------------------------------------------------------------------------

@dataclass
class ObjectMeta:
    """The metadata subset the framework relies on (mirrors metav1.ObjectMeta)."""

    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    creation_timestamp: str = ""
    deletion_timestamp: str | None = None
    owner_references: list[dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"name": self.name, "namespace": self.namespace}
        if self.uid:
            d["uid"] = self.uid
        if self.resource_version:
            d["resourceVersion"] = self.resource_version
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        if self.creation_timestamp:
            d["creationTimestamp"] = self.creation_timestamp
        if self.deletion_timestamp:
            d["deletionTimestamp"] = self.deletion_timestamp
        if self.owner_references:
            d["ownerReferences"] = copy.deepcopy(self.owner_references)
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ObjectMeta":
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            uid=d.get("uid", ""),
            resource_version=str(d.get("resourceVersion", "")),
            labels=dict(d.get("labels", {})),
            annotations=dict(d.get("annotations", {})),
            creation_timestamp=d.get("creationTimestamp", ""),
            deletion_timestamp=d.get("deletionTimestamp"),
            owner_references=copy.deepcopy(d.get("ownerReferences", [])),
        )


@dataclass
class TPUJob:
    """The TPUJob custom resource. Parity: v1alpha2/types.go:28-38 (TFJob)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUJobSpec = field(default_factory=TPUJobSpec)
    status: TPUJobStatus = field(default_factory=TPUJobStatus)

    api_version: str = constants.API_VERSION
    kind: str = constants.KIND

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def to_dict(self) -> dict[str, Any]:
        return {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TPUJob":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata", {})),
            spec=TPUJobSpec.from_dict(d.get("spec", {})),
            status=TPUJobStatus.from_dict(d.get("status", {})),
            api_version=d.get("apiVersion", constants.API_VERSION),
            kind=d.get("kind", constants.KIND),
        )

    def deepcopy(self) -> "TPUJob":
        return TPUJob.from_dict(self.to_dict())
