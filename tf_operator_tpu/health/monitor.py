"""FleetHealthMonitor: signals in, cordons + migrations out.

The fleet-health authority that makes host/chip health a first-class
scheduling input. Four signal sources feed one per-cell state machine
(health/model.py):

1. **Heartbeats** — node objects on either cluster backend (memcluster
   `heartbeat_node`, or `PUT /api/v1/nodes/{name}/status` on the wire
   stub / a real apiserver). A node labeled with its generation and cell
   block that goes NotReady (or whose heartbeat goes stale) marks its
   cells Suspect, and Cordoned after a grace window.
2. **Exit reports** — exit-138/SIGUSR1 "TPU health check failed" pod
   exits, attributed back to the cells the gang occupied (the controller
   forwards them via ``record_pod_exit``; placements come from the
   scheduler). The workload measuring its own chips is the strongest
   evidence, so a report cordons immediately by default.
3. **Restart churn** — repeated retryable exits on the same cells score
   suspicion that decays over time; crossing the threshold cordons.
4. **Maintenance notices** — injected drains with a deadline
   (`tpuctl drain --at` / POST /debug/health/drain), standing in for GCE
   maintenance events: cordon now, migrate ahead of the failure, start
   the repair probe only after the deadline passes.

Acting on a cordon is a three-step discipline whose ORDER is the crash
contract (mirroring scheduler/core.py's annotation-first admissions):

    a. commit the cordon to the placer (in-memory: placement stops
       handing out these cells immediately),
    b. persist the cordon record (a ConfigMap-shaped object in the
       store) — BEFORE any eviction,
    c. migrate admitted gangs off the cells (scheduler.migrate_gang:
       checkpoint-signal annotation persisted, pods deleted whole,
       gang requeued with an aging credit, re-placed on healthy cells).
       With a checkpoint grace configured the eviction inside (c) is
       NOT fire-and-forget: the scheduler holds the pod deletions until
       every pod acks the signal or the grace deadline passes
       (ckpt/registry.py; the poll's migration sweep keeps re-entering
       the pending barrier and completes it when the ack/deadline
       allows), and the re-placed pods resume from the last acked step.

A controller dying between (b) and (c) — or mid-(c) — recovers: the
successor's monitor reads the persisted cordons back into the placer,
and the scheduler's reconcile-time cordon check (reconcile_gang) migrates
any recovered gang still sitting on cordoned cells; a half-finished
eviction is completed by the existing queued-gang-with-pods cleanup. If
(b) itself fails the migration is deferred (cells stay cordoned in this
incarnation, so no NEW placement can land on them) and retried by the
next poll.

Auto-repair: a non-manual cordon older than ``repair_after`` enters the
Repairing probe window; ``probe_window`` quiet seconds uncordon the cell
(and re-pump the queue — healed capacity admits waiting gangs), while any
fresh signal re-cordons.

Lock ordering: monitor lock → scheduler lock, always. The scheduler never
calls into the monitor.
"""

from __future__ import annotations

import json
import threading
from typing import Any

from tf_operator_tpu.health.model import (
    SOURCE_EXIT_REPORT,
    SOURCE_HEARTBEAT,
    SOURCE_MAINTENANCE,
    SOURCE_MANUAL,
    SOURCE_RESTART_CHURN,
    STATE_CORDONED,
    STATE_HEALTHY,
    STATE_REPAIRING,
    STATE_SUSPECT,
    STATES,
    CellHealth,
    HealthConfig,
)
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ApiError, ClusterClient, NotFound
from tf_operator_tpu.runtime.metrics import (
    HEALTH_CELLS,
    HEALTH_CORDONS_TOTAL,
    HEALTH_SIGNALS_TOTAL,
    HEALTH_UNCORDONS_TOTAL,
)
from tf_operator_tpu.utils import exit_codes, logger
from tf_operator_tpu.utils.times import parse_rfc3339

# The persisted cordon record: one ConfigMap-shaped object. Suspect scores
# are soft state (losing them on restart only delays a cordon); cordons and
# in-probe repairs are durable — a restarted controller must never place a
# gang on a cell its predecessor withdrew.
RECORD_NAME = "tpu-fleet-health"
RECORD_NAMESPACE = "default"

# Bound for the (job, pod-uid) exit dedupe set — a failed pod can be
# observed by several syncs before its deletion lands, and each observation
# must score its cells at most once.
_SEEN_EXITS_CAP = 4096


def _time_now() -> float:
    import time

    return time.time()


class FleetHealthMonitor:
    def __init__(
        self,
        scheduler: Any,
        client: ClusterClient | None = None,
        config: HealthConfig | None = None,
        recorder: Any | None = None,
    ) -> None:
        self.scheduler = scheduler
        scheduler.health = self
        self.client = client if client is not None else scheduler.client
        self.config = config or HealthConfig()
        self.recorder = recorder
        # Shared node informer (controller-owned), when one was attached:
        # the heartbeat sweep reads this cache once it has synced, so the
        # steady-state poll costs zero API round-trips. Monitors built
        # without one (tests, standalone) keep the direct LIST.
        self.node_lister: Any | None = None
        self._lock = threading.RLock()
        self._cells: dict[tuple[str, tuple[int, ...]], CellHealth] = {}
        self._seen_exits: set[tuple[str, str]] = set()
        # job key -> last time a restart-churn signal was scored for it
        # (the one-incident-one-signal collapse; config.churn_interval).
        self._last_churn: dict[str, float] = {}
        # Generations ever exported to the cells gauge, so a generation
        # whose last tracked cell healed still gets its series zeroed.
        self._gauge_gens: set[str] = set()
        self._last_tick: float | None = None
        self._dirty = False  # a persist failed; retry on the next poll
        self._recovered = False
        self.log = logger.with_fields(component="fleet-health")
        if self.client is not None:
            self.recover()

    # -- wiring ---------------------------------------------------------------

    def attach(
        self,
        client: ClusterClient,
        recorder: Any | None = None,
        node_lister: Any | None = None,
    ) -> None:
        """Late binding, mirroring GangScheduler.attach (the operator main
        builds the monitor from flags before any client exists)."""
        if self.client is None:
            self.client = client
        if self.recorder is None:
            self.recorder = recorder
        if node_lister is not None:
            self.node_lister = node_lister
        if not self._recovered:
            self.recover()

    def start(self, stop: threading.Event, interval: float = 2.0) -> None:
        """Background poll loop: node heartbeats + clock transitions +
        deferred-migration retries."""

        def loop() -> None:
            while not stop.wait(interval):
                try:
                    self.poll()
                except Exception:
                    self.log.exception("health poll failed")

        threading.Thread(target=loop, name="fleet-health", daemon=True).start()

    # -- signal ingestion -----------------------------------------------------

    def record_pod_exit(
        self,
        job_key: str,
        pod_uid: str,
        exit_code: int | None,
        now: float | None = None,
    ) -> None:
        """Attribute a failed pod's exit back to the cells its gang runs
        on. Exit 138 (SIGUSR1, the reserved "TPU health check failed"
        self-report) is a strong signal; other retryable exits score
        restart churn. Permanent exits are app bugs, not cell evidence.
        Deduped per pod incarnation — syncs can re-observe a failed pod."""
        if exit_code is None or exit_codes.is_success(exit_code):
            return
        is_report = exit_code == exit_codes.SIGUSR1_EXIT
        if not is_report and not exit_codes.is_retryable(exit_code):
            return
        now = now if now is not None else _time_now()
        with self._lock:
            if pod_uid:
                if (job_key, pod_uid) in self._seen_exits:
                    return
                if len(self._seen_exits) >= _SEEN_EXITS_CAP:
                    self._seen_exits.clear()
                self._seen_exits.add((job_key, pod_uid))
            if not is_report:
                # One incident = one signal: a gang failing as a unit
                # drops several member pods at once, all attributed to the
                # same cells — collapsing the burst keeps the threshold
                # meaning "repeated incidents", not "big gang".
                last = self._last_churn.get(job_key)
                if last is not None and now - last < self.config.churn_interval:
                    return
                self._last_churn[job_key] = now
            cells = [
                (p.generation, cell)
                for p in self.scheduler.placements_of(job_key)
                for cell in p.cells()
            ]
            source = SOURCE_EXIT_REPORT if is_report else SOURCE_RESTART_CHURN
            weight = (
                self.config.exit_report_weight
                if is_report
                else self.config.restart_weight
            )
            HEALTH_SIGNALS_TOTAL.inc(source=source)
            if cells:
                self._signal(cells, source, weight, now)

    def observe_nodes(self, now: float | None = None) -> None:
        """Heartbeat sweep: list node objects, mark cells of NotReady (or
        heartbeat-stale) TPU hosts, recover cells whose host came back."""
        now = now if now is not None else _time_now()
        lister = self.node_lister
        if lister is not None and lister.has_synced():
            # Watch-maintained cache: the poll issues no API round-trip.
            nodes = lister.list()
        else:
            if self.client is None:
                return
            try:
                nodes = self.client.list(objects.NODES, None)
            except ApiError:
                return
        with self._lock:
            for node in nodes:
                gen = objects.node_generation(node)
                cells = objects.node_cells(node)
                if not gen or not cells:
                    continue
                ready = objects.node_ready(node)
                if ready:
                    hb = parse_rfc3339(objects.node_heartbeat_time(node) or "")
                    if hb is not None and now - hb > self.config.heartbeat_timeout:
                        ready = False  # stale heartbeat: lost, not healthy
                if ready:
                    self._node_recovered(gen, cells, now)
                else:
                    self._node_lost(gen, cells, now)

    def drain(
        self,
        generation: str,
        cells: list[tuple[int, ...]],
        deadline: float | None = None,
        now: float | None = None,
    ) -> list[str]:
        """Maintenance notice: cordon the cells NOW (migrating gangs ahead
        of the failure) and hold the cordon at least until ``deadline``
        (epoch seconds; the repair probe starts after it). Returns the
        keys of gangs migrated off the cells."""
        now = now if now is not None else _time_now()
        with self._lock:
            HEALTH_SIGNALS_TOTAL.inc(source=SOURCE_MAINTENANCE)
            return self._cordon(
                generation,
                cells,
                SOURCE_MAINTENANCE,
                now,
                deadline=deadline,
            )

    def cordon(
        self,
        generation: str,
        cells: list[tuple[int, ...]],
        now: float | None = None,
    ) -> list[str]:
        """Operator-pinned cordon: never auto-uncordons."""
        now = now if now is not None else _time_now()
        with self._lock:
            HEALTH_SIGNALS_TOTAL.inc(source=SOURCE_MANUAL)
            return self._cordon(
                generation, cells, SOURCE_MANUAL, now, manual=True
            )

    def uncordon(
        self,
        generation: str,
        cells: list[tuple[int, ...]],
        now: float | None = None,
    ) -> None:
        """Return cells to service (manual; also clears suspicion)."""
        with self._lock:
            self._uncordon(generation, [tuple(c) for c in cells])

    # -- clock ---------------------------------------------------------------

    def poll(self, now: float | None = None) -> None:
        """One monitor pass: heartbeat sweep, state-machine clock, persist
        retry, and the migration sweep (admitted gangs on cordoned cells —
        normally empty; non-empty after a deferred persist or a recovery)."""
        now = now if now is not None else _time_now()
        self.observe_nodes(now)
        self.tick(now)
        with self._lock:
            if self._dirty:
                self._persist()
            if self._dirty:
                # The cordon record STILL is not durable: evicting now
                # would break the persist-before-evict crash contract (a
                # successor with no record would re-place gangs straight
                # onto the bad cells). Keep deferring; the cells stay
                # excluded in-memory meanwhile.
                return
            for key in self.scheduler.gangs_on_cordoned_cells():
                self._migrate(key)

    def tick(self, now: float | None = None) -> None:
        """Advance time-driven transitions: score decay, NotReady grace
        expiry, cordon → repair probe, probe → healthy."""
        now = now if now is not None else _time_now()
        with self._lock:
            dt = max(0.0, now - self._last_tick) if self._last_tick else 0.0
            self._last_tick = now
            cordon: dict[str, list[tuple[int, ...]]] = {}
            uncordon: dict[str, list[tuple[int, ...]]] = {}
            drop: list[tuple[str, tuple[int, ...]]] = []
            for (gen, cell), ch in self._cells.items():
                ch.score = max(0.0, ch.score - self.config.suspect_decay * dt)
                if ch.state == STATE_SUSPECT:
                    if ch.score <= 0.0 and ch.notready_since is None:
                        drop.append((gen, cell))  # forgiven
                    elif (
                        ch.notready_since is not None
                        and now - ch.notready_since
                        >= self.config.notready_cordon_after
                    ):
                        cordon.setdefault(gen, []).append(cell)
                elif ch.state == STATE_CORDONED and not ch.manual:
                    if ch.notready_since is not None:
                        continue  # host still dark: no point probing
                    base = ch.cordoned_at or now
                    if ch.deadline is not None:
                        base = max(base, ch.deadline)
                    if now - base >= self.config.repair_after:
                        ch.state = STATE_REPAIRING
                        ch.repairing_since = now
                        self._dirty = True
                elif ch.state == STATE_REPAIRING:
                    since = ch.repairing_since or now
                    if ch.last_signal_at > since or ch.notready_since is not None:
                        ch.state = STATE_CORDONED
                        ch.cordoned_at = now
                        ch.repairing_since = None
                        self._dirty = True
                    elif now - since >= self.config.probe_window:
                        uncordon.setdefault(gen, []).append(cell)
            for gen, cell in drop:
                del self._cells[(gen, cell)]
            for gen, cells in cordon.items():
                self._cordon(gen, cells, SOURCE_HEARTBEAT, now)
            for gen, cells in uncordon.items():
                self._uncordon(gen, cells)
            if self._dirty:
                self._persist()
            self._export_gauges()

    # -- controller-facing lookups -------------------------------------------

    def degraded_cells_for(self, job_key: str) -> list[str]:
        """Human-readable list of non-Healthy cells under this admitted
        gang's placements — what the SliceDegraded condition names."""
        with self._lock:
            out = []
            for p in self.scheduler.placements_of(job_key):
                for cell in p.cells():
                    ch = self._cells.get((p.generation, cell))
                    if ch is not None and ch.state != STATE_HEALTHY:
                        out.append(
                            f"{p.generation}:{','.join(map(str, cell))}"
                            f"({ch.state})"
                        )
            return sorted(out)

    def snapshot(self) -> dict[str, Any]:
        """JSON-friendly view for /debug/health and `tpuctl health`."""
        with self._lock:
            cells = sorted(
                (ch.to_dict() for ch in self._cells.values()),
                key=lambda d: (d["generation"], d["cell"]),
            )
            counts: dict[str, int] = {}
            for ch in self._cells.values():
                counts[ch.state] = counts.get(ch.state, 0) + 1
            return {
                "cells": cells,
                "counts": counts,
                "config": {
                    "suspectThreshold": self.config.suspect_threshold,
                    "notreadyCordonAfter": self.config.notready_cordon_after,
                    "repairAfter": self.config.repair_after,
                    "probeWindow": self.config.probe_window,
                },
            }

    # -- internals (lock held) ------------------------------------------------

    def _cell(self, gen: str, cell: tuple[int, ...]) -> CellHealth:
        key = (gen, tuple(int(x) for x in cell))
        ch = self._cells.get(key)
        if ch is None:
            ch = CellHealth(generation=gen, cell=key[1])
            self._cells[key] = ch
        return ch

    def _signal(
        self,
        cells: list[tuple[str, tuple[int, ...]]],
        source: str,
        weight: float,
        now: float | None,
    ) -> None:
        now = now if now is not None else _time_now()
        to_cordon: dict[str, list[tuple[int, ...]]] = {}
        for gen, cell in cells:
            ch = self._cell(gen, cell)
            ch.score += weight
            ch.last_signal_at = now
            if ch.state == STATE_HEALTHY:
                ch.state = STATE_SUSPECT
                ch.source = source
            if (
                ch.state == STATE_SUSPECT
                and ch.score >= self.config.suspect_threshold
            ):
                to_cordon.setdefault(gen, []).append(ch.cell)
            # Repairing cells re-cordon on the next tick (last_signal_at
            # advanced past repairing_since).
        for gen, cs in to_cordon.items():
            self._cordon(gen, cs, source, now)
        self._export_gauges()

    def _node_lost(
        self, gen: str, cells: list[tuple[int, ...]], now: float
    ) -> None:
        fresh = []
        for cell in cells:
            ch = self._cell(gen, cell)
            if ch.notready_since is None:
                ch.notready_since = now
                fresh.append((gen, tuple(cell)))
        if fresh:
            HEALTH_SIGNALS_TOTAL.inc(source=SOURCE_HEARTBEAT)
            self._signal(fresh, SOURCE_HEARTBEAT, self.config.notready_weight, now)

    def _node_recovered(
        self, gen: str, cells: list[tuple[int, ...]], now: float
    ) -> None:
        changed = False
        for cell in cells:
            ch = self._cells.get((gen, tuple(cell)))
            if ch is None or ch.notready_since is None:
                continue
            ch.notready_since = None
            if (
                ch.state == STATE_CORDONED
                and ch.source == SOURCE_HEARTBEAT
                and not ch.manual
            ):
                # Host is back: skip straight to the repair probe rather
                # than waiting out the full repair_after window.
                ch.state = STATE_REPAIRING
                ch.repairing_since = now
                changed = True
        if changed:
            self._dirty = True
            self._persist()
            self._export_gauges()

    def _cordon(
        self,
        gen: str,
        cells: list[tuple[int, ...]],
        source: str,
        now: float,
        manual: bool = False,
        deadline: float | None = None,
    ) -> list[str]:
        cells = [tuple(int(x) for x in c) for c in cells]
        newly = []
        for cell in cells:
            ch = self._cell(gen, cell)
            if ch.state not in (STATE_CORDONED, STATE_REPAIRING):
                newly.append(cell)
            ch.state = STATE_CORDONED
            ch.cordoned_at = now
            ch.repairing_since = None
            ch.source = source
            ch.manual = ch.manual or manual
            if deadline is not None:
                ch.deadline = deadline
        if newly:
            HEALTH_CORDONS_TOTAL.inc(len(newly), source=source)
        # (a) placement stops handing out these cells immediately.
        victims = self.scheduler.cordon_cells(gen, cells)
        # (b) persist BEFORE migrating: a crash after this point recovers
        # the cordon, and reconcile_gang finishes the migration. A failed
        # persist defers the eviction (cells stay excluded in-memory; the
        # next poll retries) rather than evicting a gang whose successor
        # controller would happily re-place right back on the bad cells.
        self._dirty = True
        if not self._persist():
            self.log.warning(
                "cordon persisted only in memory; migration deferred "
                "(gen=%s cells=%s)", gen, cells,
            )
            self._export_gauges()
            return []
        # (c) migrate admitted gangs off the cells, whole.
        migrated = [key for key in victims if self._migrate(key)]
        self._export_gauges()
        return migrated

    def _uncordon(self, gen: str, cells: list[tuple[int, ...]]) -> None:
        returned = 0
        for cell in cells:
            key = (gen, tuple(cell))
            ch = self._cells.pop(key, None)
            if ch is not None and ch.state in (STATE_CORDONED, STATE_REPAIRING):
                returned += 1
        if returned:
            HEALTH_UNCORDONS_TOTAL.inc(returned)
        self._dirty = True
        self._persist()
        # Pumps the queue: healed capacity may admit waiting gangs.
        self.scheduler.uncordon_cells(gen, list(cells))
        self._export_gauges()

    def _migrate(self, key: str) -> bool:
        """Drive one gang's migration. True covers both a completed
        eviction and an in-flight checkpoint barrier (signaled, pods held
        for the ack/deadline) — the sweep re-enters pending barriers each
        poll, which is what expires them even with no sync traffic."""
        try:
            return self.scheduler.migrate_gang(key)
        except ApiError:
            # Apiserver hiccup mid-eviction: the job annotations either
            # landed (queued-with-pods cleanup finishes it) or did not
            # (the gang stays admitted and the next poll's migration
            # sweep retries). Either way the cordon already excludes the
            # cells from any new placement.
            self.log.warning("migration of %s interrupted; will retry", key)
            return False

    # -- persistence / recovery ----------------------------------------------

    def _persist(self) -> bool:
        """Write the durable cordon record (Cordoned + Repairing cells).
        Returns False on failure, leaving _dirty set for the poll retry."""
        if self.client is None:
            self._dirty = False
            return True
        durable = [
            ch.to_dict()
            for ch in self._cells.values()
            if ch.state in (STATE_CORDONED, STATE_REPAIRING)
        ]
        body = {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": RECORD_NAME, "namespace": RECORD_NAMESPACE},
            "data": {"cells": json.dumps(durable)},
        }
        try:
            try:
                self.client.patch_merge(
                    objects.CONFIGMAPS,
                    RECORD_NAMESPACE,
                    RECORD_NAME,
                    {"data": {"cells": body["data"]["cells"]}},
                )
            except NotFound:
                self.client.create(objects.CONFIGMAPS, body)
        except ApiError:
            self.log.warning("fleet-health record persist failed")
            self._dirty = True
            return False
        self._dirty = False
        return True

    def recover(self) -> None:
        """Rebuild cordons from the persisted record (controller restart):
        re-commit them to the placer so recovered admissions re-arbitrate
        against the true healthy fleet, then let reconcile_gang's cordon
        check migrate any recovered gang still sitting on withdrawn cells."""
        self._recovered = True
        if self.client is None:
            return
        try:
            record = self.client.get(
                objects.CONFIGMAPS, RECORD_NAMESPACE, RECORD_NAME
            )
        except NotFound:
            return
        except ApiError:
            self.log.warning("fleet-health record read failed; starting empty")
            return
        try:
            cells = [
                CellHealth.from_dict(d)
                for d in json.loads(record.get("data", {}).get("cells", "[]"))
            ]
        except (ValueError, KeyError, TypeError):
            self.log.warning("fleet-health record unparseable; starting empty")
            return
        with self._lock:
            by_gen: dict[str, list[tuple[int, ...]]] = {}
            for ch in cells:
                self._cells[(ch.generation, ch.cell)] = ch
                by_gen.setdefault(ch.generation, []).append(ch.cell)
            for gen, cs in by_gen.items():
                self.scheduler.cordon_cells(gen, cs)
            self._export_gauges()

    # -- metrics --------------------------------------------------------------

    def _export_gauges(self) -> None:
        counts: dict[tuple[str, str], int] = {}
        gens = set()
        for ch in self._cells.values():
            gens.add(ch.generation)
            counts[(ch.generation, ch.state)] = (
                counts.get((ch.generation, ch.state), 0) + 1
            )
        # Gauge series persist their last value: a generation whose last
        # tracked cell was dropped (healed) must be written back to 0, or
        # /metrics would report the old cordon forever.
        for gen in gens | self._gauge_gens:
            for state in STATES:
                HEALTH_CELLS.set(
                    counts.get((gen, state), 0), generation=gen, state=state
                )
        self._gauge_gens = gens
