"""Fleet health & auto-repair: cordon/drain, maintenance-aware migration,
and degraded-slice detection.

The reference operator only ever reacts to failures after a container dies
(exit-code → retry); on real TPU fleets most capacity loss is announced or
observable before the crash — maintenance events, ICI link degradation,
hosts going NotReady. This subsystem makes host/chip health a first-class
scheduling input: per-cell health states over the same mesh coordinates
the placer allocates from, multi-source signal ingestion, cordon-aware
placement, and checkpoint-signaled whole-gang migration ahead of failures.

See docs/health.md for the state machine, signal sources, and the
migration flow; tools/health_smoke.py runs the marked test subset.
"""

from tf_operator_tpu.health.model import (
    SOURCE_EXIT_REPORT,
    SOURCE_HEARTBEAT,
    SOURCE_MAINTENANCE,
    SOURCE_MANUAL,
    SOURCE_RESTART_CHURN,
    STATE_CORDONED,
    STATE_HEALTHY,
    STATE_REPAIRING,
    STATE_SUSPECT,
    CellHealth,
    HealthConfig,
    MaintenanceNotice,
)
from tf_operator_tpu.health.monitor import FleetHealthMonitor

__all__ = [
    "CellHealth",
    "FleetHealthMonitor",
    "HealthConfig",
    "MaintenanceNotice",
    "SOURCE_EXIT_REPORT",
    "SOURCE_HEARTBEAT",
    "SOURCE_MAINTENANCE",
    "SOURCE_MANUAL",
    "SOURCE_RESTART_CHURN",
    "STATE_CORDONED",
    "STATE_HEALTHY",
    "STATE_REPAIRING",
    "STATE_SUSPECT",
]
