"""Fleet-health model: per-cell state machine + tuning knobs.

The unit of health is the same unit the placer allocates — one mesh cell
(one chip) of a generation's installed torus (scheduler/placement.py).
Hosts, slices and jobs all project onto cells: a NotReady host marks its
cells, an exit-138 health report marks the cells of the gang that raised
it, a maintenance notice names cells directly. Keying health on cells is
what lets every signal source feed the same scheduling decision: a cell
that is not Healthy is excluded from placement.

State machine (driven by health/monitor.py):

    Healthy ──signal──► Suspect ──score≥threshold / NotReady-grace──►
    Cordoned ──repair_after quiet──► Repairing ──probe_window quiet──►
    Healthy
       ▲                                │
       └──────── new signal ────────────┘   (re-cordon)

- *Suspect*: accumulating evidence (suspect scoring decays over time —
  one flaky restart does not brick a cell). Suspect cells still place,
  but jobs sitting on them surface a SliceDegraded condition.
- *Cordoned*: excluded from placement; gangs on the cells are migrated.
  Manual cordons (`tpuctl cordon`) never auto-uncordon; maintenance
  cordons hold at least until their deadline.
- *Repairing*: the repair probe window — still excluded from placement;
  one more signal re-cordons, a quiet window returns the cell to service.

The ISSUE's parity anchors: MLPerf-scale pod runs (arXiv:1909.09756) and
the TPU concurrency study (arXiv:2011.03641) both treat whole-slice health
as the scheduling unit — one bad host strands the slice, so health must
feed the placer, not just the restart loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field

STATE_HEALTHY = "Healthy"
STATE_SUSPECT = "Suspect"
STATE_CORDONED = "Cordoned"
STATE_REPAIRING = "Repairing"

STATES = (STATE_HEALTHY, STATE_SUSPECT, STATE_CORDONED, STATE_REPAIRING)

# Signal sources (metric label + cordon attribution).
SOURCE_HEARTBEAT = "heartbeat"      # node NotReady / stale heartbeat
SOURCE_EXIT_REPORT = "exit-report"  # exit-138 "TPU health check failed"
SOURCE_RESTART_CHURN = "restart-churn"  # repeated retryable exits on a cell
SOURCE_MAINTENANCE = "maintenance"  # injected drain notice with deadline
SOURCE_MANUAL = "manual"            # tpuctl cordon


@dataclass
class HealthConfig:
    """Tuning for the fleet-health state machine. The defaults are test-
    and-demo scale (seconds); production deployments stretch them via the
    operator's --health-* flags."""

    # Suspect score at which a cell auto-cordons.
    suspect_threshold: float = 3.0
    # Score points decayed per second — the forgiveness valve that keeps
    # one flaky restart from eventually bricking a cell.
    suspect_decay: float = 1.0 / 60.0
    # Signal weights. An explicit exit-138 health-check report is the
    # workload measuring its own chips (the strongest evidence we have),
    # so it cordons immediately by default; one retryable restart is weak
    # evidence and needs repeats.
    exit_report_weight: float = 3.0
    restart_weight: float = 1.0
    notready_weight: float = 1.0
    # Churn signals for the SAME job within this window collapse into one:
    # a multi-host gang failing as one incident produces one failed pod
    # per member, and attributing every member's exit to the shared cells
    # would cross the threshold in a single sweep — one incident is one
    # piece of evidence, however many pods it took down. Distinct
    # incidents are separated by a full restart cycle, which takes longer
    # than this window. Explicit exit-138 reports are exempt (each is the
    # workload deliberately measuring its own chips).
    churn_interval: float = 5.0
    # Seconds a node may stay NotReady before its cells cordon (suspect in
    # the meantime — a kubelet blip must not evict a healthy gang).
    notready_cordon_after: float = 10.0
    # Seconds with no fresh heartbeat before a node counts as NotReady
    # even when its last written Ready condition still says True.
    heartbeat_timeout: float = 60.0
    # Auto-repair: a (non-manual) cordon older than repair_after enters
    # the Repairing probe; probe_window quiet seconds return it to
    # service, any new signal re-cordons.
    repair_after: float = 30.0
    probe_window: float = 30.0


@dataclass
class CellHealth:
    """One mesh cell's health record. Cells with no open suspicion are
    not tracked at all — absence means Healthy."""

    generation: str
    cell: tuple[int, ...]
    state: str = STATE_HEALTHY
    score: float = 0.0
    source: str = ""                 # what pushed it out of Healthy
    last_signal_at: float = 0.0
    notready_since: float | None = None
    cordoned_at: float | None = None
    repairing_since: float | None = None
    deadline: float | None = None    # maintenance: earliest repair start
    manual: bool = False             # operator-pinned: no auto-uncordon

    @property
    def placeable(self) -> bool:
        """Whether the placer may use this cell (Suspect still places —
        cordoning on a single weak signal would thrash the fleet)."""
        return self.state in (STATE_HEALTHY, STATE_SUSPECT)

    def to_dict(self) -> dict:
        d: dict = {
            "generation": self.generation,
            "cell": list(self.cell),
            "state": self.state,
            "score": round(self.score, 3),
            "source": self.source,
        }
        for key, val in (
            ("cordonedAt", self.cordoned_at),
            ("repairingSince", self.repairing_since),
            ("deadline", self.deadline),
        ):
            if val is not None:
                d[key] = val
        if self.manual:
            d["manual"] = True
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "CellHealth":
        return cls(
            generation=d["generation"],
            cell=tuple(int(x) for x in d["cell"]),
            state=d.get("state", STATE_CORDONED),
            score=float(d.get("score", 0.0)),
            source=d.get("source", ""),
            cordoned_at=d.get("cordonedAt"),
            repairing_since=d.get("repairingSince"),
            deadline=d.get("deadline"),
            manual=bool(d.get("manual", False)),
        )


@dataclass
class MaintenanceNotice:
    """An injected drain: these cells will be serviced at ``deadline``
    (epoch seconds). Stands in for GCE maintenance events; arrives via
    `tpuctl drain --at` or POST /debug/health/drain."""

    generation: str
    cells: list[tuple[int, ...]] = field(default_factory=list)
    deadline: float | None = None
