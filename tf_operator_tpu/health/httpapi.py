"""/debug/health HTTP surface: the fleet-health snapshot plus the
cordon/uncordon/drain verbs `tpuctl health|cordon|uncordon|drain` drive.

Mounts on the operator's ApiServer via its extra-handler hook (the same
mechanism the dashboard and /metrics use). Mutating verbs are POSTs, so
they ride the server's bearer-token write gate automatically.

    GET  /debug/health            → FleetHealthMonitor.snapshot()
    POST /debug/health/cordon     {"generation": "v4", "cells": [[0,0,0],…]}
    POST /debug/health/uncordon   same body
    POST /debug/health/drain      same body + "deadlineSeconds": 3600
                                  (maintenance deadline relative to now —
                                  relative so client clock skew is moot)

The drain endpoint is the injection point standing in for GCE maintenance
events: anything that learns of upcoming maintenance (a cloud-ops webhook,
a cron, an operator) POSTs the notice and the fleet migrates ahead of it.
"""

from __future__ import annotations

import json
import time
from typing import Any

from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="health-api")


def _parse_cells(body: dict[str, Any]) -> list[tuple[int, ...]]:
    cells = body.get("cells")
    if not isinstance(cells, list) or not cells:
        raise ValueError("body must carry a non-empty 'cells' list")
    return [tuple(int(x) for x in c) for c in cells]


class HealthApiHandler:
    def __init__(self, monitor: Any) -> None:
        self._monitor = monitor

    def __call__(self, req: Any) -> bool:
        path = req.path.split("?", 1)[0]
        if not path.startswith("/debug/health"):
            return False
        if req.command == "GET" and path == "/debug/health":
            body = json.dumps(self._monitor.snapshot(), indent=2).encode()
            req.send_response(200)
            req.send_header("Content-Type", "application/json")
            req.send_header("Content-Length", str(len(body)))
            req.end_headers()
            req.wfile.write(body)
            return True
        if req.command != "POST":
            return False
        verb = path[len("/debug/health/"):] if len(path) > len(
            "/debug/health/"
        ) else ""
        if verb not in ("cordon", "uncordon", "drain"):
            return False
        try:
            body = req.read_json_body()
            generation = str(body.get("generation", "")).strip()
            if not generation:
                raise ValueError("body must carry a 'generation'")
            cells = _parse_cells(body)
            if verb == "cordon":
                migrated = self._monitor.cordon(generation, cells)
            elif verb == "uncordon":
                self._monitor.uncordon(generation, cells)
                migrated = []
            else:
                deadline = None
                rel = body.get("deadlineSeconds")
                if rel is not None:
                    deadline = time.time() + float(rel)
                migrated = self._monitor.drain(generation, cells, deadline)
            req.send_json(
                {
                    "ok": True,
                    "generation": generation,
                    "cells": [list(c) for c in cells],
                    "migrated": migrated,
                }
            )
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            req.send_json({"error": "BadRequest", "message": str(e)}, 400)
        return True


def mount_health(api_server: Any, monitor: Any) -> HealthApiHandler:
    handler = HealthApiHandler(monitor)
    api_server.add_handler(handler)
    LOG.info("fleet-health API mounted at /debug/health")
    return handler
