"""/metrics (Prometheus text), /debug/traces (Chrome trace) and
/debug/scheduler (gang-admission snapshot) endpoints.

Mounts on the operator's ApiServer via its extra-handler hook (the same
mechanism the dashboard uses). The reference exposes neither metrics nor
traces (SURVEY.md §5); here every operator process is scrapeable and
traceable out of the box, and the admission queue (scheduler/core.py) is
inspectable live — `tpuctl queue` renders /debug/scheduler.
"""

from __future__ import annotations

import json
from typing import Any

from tf_operator_tpu.runtime.metrics import REGISTRY, Registry
from tf_operator_tpu.runtime.tracing import TRACER, Tracer
from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="observability")


class ObservabilityHandler:
    def __init__(
        self,
        registry: Registry = REGISTRY,
        tracer: Tracer = TRACER,
        scheduler: Any | None = None,
    ):
        self._registry = registry
        self._tracer = tracer
        self._scheduler = scheduler

    def __call__(self, req: Any) -> bool:
        path = req.path.split("?", 1)[0]
        if req.command != "GET":
            return False
        if path == "/metrics":
            body = self._registry.render().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/debug/traces":
            body = self._tracer.export_chrome_trace().encode()
            ctype = "application/json"
        elif path == "/debug/scheduler" and self._scheduler is not None:
            body = json.dumps(self._scheduler.snapshot(), indent=2).encode()
            ctype = "application/json"
        else:
            return False
        req.send_response(200)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)
        return True


def mount_observability(api_server: Any, registry: Registry = REGISTRY,
                        tracer: Tracer = TRACER,
                        scheduler: Any | None = None,
                        health: Any | None = None,
                        ckpt: Any | None = None,
                        fleet: Any | None = None) -> ObservabilityHandler:
    handler = ObservabilityHandler(registry, tracer, scheduler)
    api_server.add_handler(handler)
    if health is not None:
        # /debug/health (+ the cordon/uncordon/drain verbs) rides the same
        # extra-handler hook; kept in the health package so the endpoint
        # schema lives next to the monitor it exposes.
        from tf_operator_tpu.health.httpapi import mount_health

        mount_health(api_server, health)
    if ckpt is not None:
        # /debug/ckpt: the checkpoint registry snapshot, same pattern.
        from tf_operator_tpu.ckpt.httpapi import mount_ckpt

        mount_ckpt(api_server, ckpt)
    if fleet is not None:
        # /debug/fleet: the TPUServe controller's per-fleet membership/
        # target/autoscale snapshot, same pattern.
        from tf_operator_tpu.fleet.httpapi import mount_fleet

        mount_fleet(api_server, fleet)
    LOG.info(
        "observability mounted at /metrics and /debug/traces%s%s%s%s",
        " and /debug/scheduler" if scheduler is not None else "",
        " and /debug/health" if health is not None else "",
        " and /debug/ckpt" if ckpt is not None else "",
        " and /debug/fleet" if fleet is not None else "",
    )
    return handler
