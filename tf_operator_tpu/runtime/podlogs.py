"""Pod log spool: where the local executor writes container output and
where the dashboard reads it back.

On a real cluster pod logs live with the kubelet and are served through the
apiserver (the reference dashboard calls CoreV1 GetLogs,
dashboard/backend/handler/api_handler.go:240). The local runtime's analog is
a spool directory: one file per pod incarnation, newest wins.
"""

from __future__ import annotations

import os
import tempfile


def log_dir() -> str:
    d = os.environ.get("TPU_OPERATOR_LOG_DIR") or os.path.join(
        tempfile.gettempdir(), "tpu-operator-logs"
    )
    os.makedirs(d, exist_ok=True)
    return d


def log_path(namespace: str, name: str, uid: str) -> str:
    safe_uid = (uid or "nouid")[:8]
    return os.path.join(log_dir(), f"{namespace}_{name}_{safe_uid}.log")


def _newest_spool(namespace: str, name: str) -> str | None:
    prefix = f"{namespace}_{name}_"
    d = log_dir()
    candidates = [
        os.path.join(d, f)
        for f in os.listdir(d)
        if f.startswith(prefix) and f.endswith(".log")
    ]
    return max(candidates, key=os.path.getmtime) if candidates else None


def read_log(namespace: str, name: str, max_bytes: int = 1 << 20) -> str | None:
    """Newest incarnation's log tail, or None if nothing was spooled."""
    newest = _newest_spool(namespace, name)
    if newest is None:
        return None
    with open(newest, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(0, size - max_bytes))
        return f.read().decode(errors="replace")


def read_log_stream(
    namespace: str, name: str, offset: int, spool: str = "",
    max_bytes: int = 1 << 20,
) -> tuple[str, int, str] | None:
    """Incremental read for log following: (chunk, next_offset, spool_id).

    ``offset`` is an ABSOLUTE byte position in the spool identified by
    ``spool`` (the basename a previous call returned — it embeds the pod
    uid, so a controller-recreated pod is a different id). A changed or
    unknown spool id, or an offset past EOF (rotation/truncation), resets
    to 0 so the caller reprints the new incarnation from its start —
    tail-window length heuristics cannot distinguish any of these cases
    (the old `tpuctl logs -f` stalled permanently once a spool crossed
    the 1 MiB read_log cap). None when nothing is spooled yet."""
    newest = _newest_spool(namespace, name)
    if newest is None:
        return None
    spool_id = os.path.basename(newest)
    if spool != spool_id:
        offset = 0
    with open(newest, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if offset > size:
            offset = 0
        f.seek(offset)
        chunk = f.read(max_bytes)
    return chunk.decode(errors="replace"), offset + len(chunk), spool_id
