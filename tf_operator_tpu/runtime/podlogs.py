"""Pod log spool: where the local executor writes container output and
where the dashboard reads it back.

On a real cluster pod logs live with the kubelet and are served through the
apiserver (the reference dashboard calls CoreV1 GetLogs,
dashboard/backend/handler/api_handler.go:240). The local runtime's analog is
a spool directory: one file per pod incarnation, newest wins.
"""

from __future__ import annotations

import os
import tempfile


def log_dir() -> str:
    d = os.environ.get("TPU_OPERATOR_LOG_DIR") or os.path.join(
        tempfile.gettempdir(), "tpu-operator-logs"
    )
    os.makedirs(d, exist_ok=True)
    return d


def log_path(namespace: str, name: str, uid: str) -> str:
    safe_uid = (uid or "nouid")[:8]
    return os.path.join(log_dir(), f"{namespace}_{name}_{safe_uid}.log")


def read_log(namespace: str, name: str, max_bytes: int = 1 << 20) -> str | None:
    """Newest incarnation's log tail, or None if nothing was spooled."""
    prefix = f"{namespace}_{name}_"
    d = log_dir()
    candidates = [
        os.path.join(d, f)
        for f in os.listdir(d)
        if f.startswith(prefix) and f.endswith(".log")
    ]
    if not candidates:
        return None
    newest = max(candidates, key=os.path.getmtime)
    with open(newest, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(0, size - max_bytes))
        return f.read().decode(errors="replace")
