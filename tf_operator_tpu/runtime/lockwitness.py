"""Runtime lock-order witness: pin the static lock graph to reality.

tpulint's ``lock-order`` pass (harness/lint/lockorder.py) extracts the
"acquired while holding" graph from the source. This module is the
other half of the pin: an **opt-in** instrumented Lock/Condition layer
that records, per thread, the set of held locks at every acquisition in
the running system — so the chaos suites can assert

    observed acquisition-order edges  ⊆  transitive closure of the
                                         static graph, and acyclic.

If the static model drifts from the code (a new lock, a new nesting),
the witness fails the chaos suite instead of letting the gap grow.

Mechanics
---------
``install()`` replaces ``threading.Lock/RLock/Condition`` with
factories that wrap locks **created from tf_operator_tpu code only**
(the creating frame's module name is checked; stdlib and test-local
locks come back untouched). Each wrapped lock remembers its creation
site ``(file, line)`` — the same key the static pass exports in
``LockGraph.sites`` — so observed edges map back onto static nodes.

Gating: inert unless ``TPU_LOCK_WITNESS=1`` is set or ``force=True``
is passed (what the chaos suites do). When not installed this module
touches nothing — ``threading.Lock`` stays the builtin, so disabled
runs are bit-for-bit identical.

Re-entrant acquisitions (RLock / Condition, whose default inner lock
is an RLock) are not edges. Condition waiters release through the
wrapper, so held-sets stay truthful across ``wait()``.
"""

from __future__ import annotations

import os
import sys
import threading
from dataclasses import dataclass, field

WITNESS_ENV = "TPU_LOCK_WITNESS"

_PKG_PREFIX = "tf_operator_tpu"

# the real factories, captured at import (before any patching)
_real_Lock = threading.Lock
_real_RLock = threading.RLock
_real_Condition = threading.Condition


def _caller_module(depth: int = 2) -> str:
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return ""
    return frame.f_globals.get("__name__", "") or ""


def _caller_site(depth: int = 2) -> tuple[str, int]:
    frame = sys._getframe(depth)
    return frame.f_code.co_filename, frame.f_lineno


class _WitnessLock:
    """Wraps a real lock; reports acquisitions to the witness."""

    __slots__ = ("_inner", "site", "_witness", "kind")

    def __init__(self, inner, site: tuple[str, int], witness: "Witness",
                 kind: str) -> None:
        self._inner = inner
        self.site = site
        self._witness = witness
        self.kind = kind

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._witness._on_acquire(self)
        return got

    def release(self) -> None:
        self._inner.release()
        self._witness._on_release(self)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition compatibility and anything else (e.g. _at_fork_reinit,
    # RLock's _is_owned/_release_save/_acquire_restore) delegates to the
    # inner lock. Bookkeeping during wait() stays truthful because the
    # default Condition _release_save/_acquire_restore for non-RLock
    # locks go through our release()/acquire().
    def __getattr__(self, name: str):
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<witnessed {self.kind} @ {self.site[0]}:{self.site[1]}>"


@dataclass
class Witness:
    """Recorded acquisition-order facts (process-global singleton while
    installed)."""

    # ((file, line) of held lock) -> ((file, line) of acquired lock)
    edges: set[tuple[tuple[str, int], tuple[str, int]]] = \
        field(default_factory=set)
    sites: set[tuple[str, int]] = field(default_factory=set)
    acquisitions: int = 0
    wrapped: int = 0

    def __post_init__(self) -> None:
        self._mutex = _real_Lock()
        self._tls = threading.local()
        # per-thread acquisition counters (single-element lists mutated
        # lock-free by their owning thread, summed at report time)
        self._counters: list[list[int]] = []

    @property
    def total_acquisitions(self) -> int:
        with self._mutex:
            return self.acquisitions + sum(c[0] for c in self._counters)

    # -- hot path --------------------------------------------------------
    #
    # No global mutex per acquisition: the per-thread held stack and
    # acquisition counter live in thread-local state (registered once
    # per thread), and the edge set is only written under the mutex for
    # a NEW edge — after the first few hundred acquisitions the steady
    # state is a held-list append plus a set-membership probe, cheap
    # enough that chaos-suite watchdog budgets (2.5s stall thresholds)
    # are unaffected.

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
            counter = [0]
            self._tls.counter = counter
            with self._mutex:
                self._counters.append(counter)
        return held

    def _on_acquire(self, lock: _WitnessLock) -> None:
        held = self._held()
        self._tls.counter[0] += 1
        if held and not any(h is lock for h in held):
            for h in held:
                pair = (h.site, lock.site)
                if pair not in self.edges:  # racy pre-check: set adds
                    with self._mutex:       # are idempotent anyway
                        self.edges.add(pair)
        held.append(lock)

    def _on_release(self, lock: _WitnessLock) -> None:
        held = getattr(self._tls, "held", None)
        if not held:
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    # -- factories -------------------------------------------------------

    def _make_lock(self):
        if not _caller_module().startswith(_PKG_PREFIX):
            return _real_Lock()
        site = _caller_site()
        with self._mutex:
            self.wrapped += 1
            self.sites.add(site)
        return _WitnessLock(_real_Lock(), site, self, "lock")

    def _make_rlock(self):
        if not _caller_module().startswith(_PKG_PREFIX):
            return _real_RLock()
        site = _caller_site()
        with self._mutex:
            self.wrapped += 1
            self.sites.add(site)
        return _WitnessLock(_real_RLock(), site, self, "rlock")

    def _make_condition(self, lock=None):
        if not _caller_module().startswith(_PKG_PREFIX):
            return _real_Condition(lock)
        if lock is None:
            site = _caller_site()
            with self._mutex:
                self.wrapped += 1
                self.sites.add(site)
            lock = _WitnessLock(_real_RLock(), site, self, "condition")
        return _real_Condition(lock)

    # -- reporting -------------------------------------------------------

    def named_edges(self, root: str) -> tuple[
            set[tuple[str, str]], set[tuple[tuple[str, int],
                                            tuple[str, int]]]]:
        """Map observed edges onto static lock nodes.

        Returns ``(named, unmapped)``: edges whose BOTH creation sites
        exist in the static graph's site map, named by node id, plus the
        raw edges at least one of whose sites the static model does not
        know (those are themselves a model gap worth looking at)."""
        graph = _static_graph(root)

        def node_of(site: tuple[str, int]) -> str | None:
            rel = os.path.relpath(site[0], root).replace(os.sep, "/")
            return graph.sites.get((rel, site[1]))

        named: set[tuple[str, str]] = set()
        unmapped: set[tuple[tuple[str, int], tuple[str, int]]] = set()
        self_site: set[str] = set()
        with self._mutex:
            edges = set(self.edges)
        for a, b in edges:
            na, nb = node_of(a), node_of(b)
            if na is None or nb is None:
                unmapped.add((a, b))
            elif na != nb:
                named.add((na, nb))
            else:
                # two DIFFERENT locks from one creation site nested in
                # one thread (intra-instance re-entry is filtered by
                # identity in _on_acquire): a cross-instance ordering
                # the instance-agnostic static model cannot rank
                self_site.add(na)
        return named, unmapped, self_site

    def check_against_static(self, root: str) -> dict:
        """The chaos-suite assertion payload: observed named edges must
        be a subgraph of the closure of the static graph, and the
        observed graph must be acyclic."""
        graph = _static_graph(root)
        closure = graph.closure()
        named, unmapped, self_site = self.named_edges(root)
        violations = sorted(e for e in named if e not in closure)
        cycles = _find_cycles(named)
        return {
            "observed": sorted(named),
            "violations": violations,
            "cycles": cycles,
            "unmapped": sorted(unmapped),
            "self_site": sorted(self_site),
            "static_edges": len(graph.edges),
            "acquisitions": self.total_acquisitions,
            "wrapped": self.wrapped,
        }

    def assert_subgraph(self, root: str) -> dict:
        """THE chaos-suite pin, in one place (both chaos modules call
        this from their final test): the witness saw traffic, every
        observed ordering edge maps onto the static model and lies
        inside its transitive closure, the observed graph is acyclic,
        and there are no edges the model cannot name — an unmapped
        creation site or a cross-instance same-site nesting is a model
        gap to teach, not to ignore. Returns the report for logging."""
        report = self.check_against_static(root)
        assert report["acquisitions"] > 0, "witness saw no lock traffic"
        assert report["observed"], "witness recorded no ordering edges"
        assert report["cycles"] == [], (
            f"observed lock-order cycle: {report['cycles']}"
        )
        assert report["violations"] == [], (
            "runtime acquisition orders missing from the static lock "
            f"graph (extend the model or fix the code): "
            f"{report['violations']}"
        )
        assert report["unmapped"] == [], (
            "witness saw locks created at sites the static model cannot "
            f"name (teach classmodel the idiom): {report['unmapped']}"
        )
        assert report["self_site"] == [], (
            "two instances from one creation site nested in one thread "
            "— an ordering the instance-agnostic model cannot rank; "
            f"restructure or rank the instances: {report['self_site']}"
        )
        return report


# Static graphs are pure functions of the tree on disk; both chaos
# suites (and any other witness consumer in one pytest process) share
# one build instead of re-parsing ~200 files each.
_GRAPH_CACHE: dict[str, object] = {}


def _static_graph(root: str):
    graph = _GRAPH_CACHE.get(root)
    if graph is None:
        from tf_operator_tpu.harness.checks import DEFAULT_PATHS, _py_files
        from tf_operator_tpu.harness.lint import load_source_file
        from tf_operator_tpu.harness.lint.lockorder import static_lock_graph
        files = [load_source_file(p, root)
                 for p in _py_files(DEFAULT_PATHS, root)]
        graph = static_lock_graph(files)
        _GRAPH_CACHE[root] = graph
    return graph


def _find_cycles(edges: set[tuple[str, str]]) -> list[list[str]]:
    adj: dict[str, list[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    out: list[list[str]] = []
    WHITE, GRAY, BLACK = 0, 1, 2
    color: dict[str, int] = {}
    path: list[str] = []

    def visit(v: str) -> None:
        color[v] = GRAY
        path.append(v)
        for w in sorted(adj.get(v, ())):
            c = color.get(w, WHITE)
            if c == GRAY:
                out.append(path[path.index(w):] + [w])
            elif c == WHITE:
                visit(w)
        path.pop()
        color[v] = BLACK

    for v in sorted(adj):
        if color.get(v, WHITE) == WHITE:
            visit(v)
    return out


def probe() -> tuple[object, object]:
    """Test helper: create and nest two locks FROM INSIDE the package
    (this module's frame), so witness-recording coverage does not
    depend on which tf_operator_tpu modules were imported before
    install(). Returns the two lock objects (wrapped when installed)."""
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass
    return a, b


_installed: Witness | None = None


def enabled() -> bool:
    return os.environ.get(WITNESS_ENV, "") == "1"


def install(force: bool = False) -> Witness | None:
    """Patch the threading factories; returns the witness, or None when
    the gate is off (and nothing was touched). Idempotent."""
    global _installed
    if not (force or enabled()):
        return None
    if _installed is not None:
        return _installed
    wit = Witness()
    threading.Lock = wit._make_lock                 # type: ignore[misc]
    threading.RLock = wit._make_rlock               # type: ignore[misc]
    threading.Condition = wit._make_condition       # type: ignore[misc]
    _installed = wit
    return wit


def uninstall() -> Witness | None:
    """Restore the real factories; recorded data stays readable."""
    global _installed
    wit = _installed
    if wit is None:
        return None
    threading.Lock = _real_Lock                     # type: ignore[misc]
    threading.RLock = _real_RLock                   # type: ignore[misc]
    threading.Condition = _real_Condition           # type: ignore[misc]
    _installed = None
    return wit


def current() -> Witness | None:
    return _installed
