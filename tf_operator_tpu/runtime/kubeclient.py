"""Real-Kubernetes ClusterClient: the adapter that makes the operator a K8s operator.

The controller stack is written against ClusterClient (runtime/client.py);
this implementation speaks the Kubernetes REST API the way the reference's
client-go stack does:

- kubeconfig / in-cluster config resolution (reference:
  pkg/util/k8sutil/k8sutil.go:52-76 — GetClusterConfig falls back from
  in-cluster to $HOME/.kube/config),
- group/version path mapping for core v1 resources, policy/v1 PDBs,
  coordination.k8s.io/v1 Leases, and the TPUJob CRD
  (apis/tpuflow.org/v1/namespaces/{ns}/tpujobs),
- the status subresource (PUT .../status) the controller's conflict-retried
  status writes need (SURVEY.md §7 "status-subresource + patch + retry"),
- label-selector lists and watch streams with resourceVersion resume
  (reconnect from the last seen RV; relist on 410 Gone),
- apimachinery Status errors mapped onto the ApiError hierarchy the
  controllers branch on (NotFound/AlreadyExists/Conflict/Invalid), like the
  reference's error predicates in pkg/util/k8sutil.

Auth supported: bearer token (inline / file / service-account), client
certificates (inline base64 data or files), CA bundle or
insecure-skip-tls-verify, and exec credential plugins
(client.authentication.k8s.io ExecCredential — the mechanism a stock GKE
kubeconfig uses via gke-gcloud-auth-plugin; client-go's exec auth provider
is the model). Plugin tokens are cached until expirationTimestamp and
re-minted on expiry or a 401.
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import subprocess
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any
from urllib import error as urlerror
from urllib import parse as urlparse_mod
from urllib import request as urlrequest

from tf_operator_tpu.api import constants
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import (
    AlreadyExists,
    ApiError,
    ClusterClient,
    Conflict,
    Invalid,
    NotFound,
    Watch,
    WatchEvent,
)
from tf_operator_tpu.runtime.metrics import API_REQUESTS_TOTAL, REGISTRY
from tf_operator_tpu.utils import logger
from tf_operator_tpu.utils.times import parse_rfc3339

LOG = logger.with_fields(component="kubeclient")

# Rest-client observability (the client-go restclient metrics the
# reference gets for free): request latency by method and exact HTTP
# status code (code="error" for transport failures that never got a
# status — connect refused, timeouts, bad JSON), and watch stream
# restarts by reason — a reconnect storm or 410 churn is an operations
# signal, not just a log line.
REQUEST_SECONDS = REGISTRY.histogram(
    "tpu_operator_kube_request_seconds",
    "Kubernetes API request latency by method and status code "
    "(code=error: transport failure with no HTTP status)",
    labelnames=("method", "code"),
)
WATCH_RESTARTS = REGISTRY.counter(
    "tpu_operator_kube_watch_restarts_total",
    "Watch stream restarts by cause (expired=server budget elapsed "
    "cleanly, gone=410 relist, auth=401 re-mint, error=server watch "
    "error/HTTP failure, eof=stream died mid-read)",
    labelnames=("kind", "reason"),
)

# Service-account mount used for in-cluster config (what client-go's
# rest.InClusterConfig reads).
SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


# ---------------------------------------------------------------------------
# API path mapping
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class _Resource:
    prefix: str  # e.g. "/api/v1" or "/apis/policy/v1"
    plural: str
    namespaced: bool = True
    api_version: str = "v1"  # value to stamp into body apiVersion
    kind: str = ""  # body kind to stamp when missing


# Framework collection name (runtime/objects.py) -> K8s REST coordinates.
_RESOURCES: dict[str, _Resource] = {
    objects.PODS: _Resource("/api/v1", "pods", True, "v1", "Pod"),
    objects.SERVICES: _Resource("/api/v1", "services", True, "v1", "Service"),
    objects.EVENTS: _Resource("/api/v1", "events", True, "v1", "Event"),
    objects.NAMESPACES: _Resource("/api/v1", "namespaces", False, "v1", "Namespace"),
    # Nodes are cluster-scoped; the stub (and the mem store behind it)
    # files them under the "default" namespace, the convention the fleet-
    # health monitor's heartbeat sweep relies on.
    objects.NODES: _Resource("/api/v1", "nodes", False, "v1", "Node"),
    objects.CONFIGMAPS: _Resource("/api/v1", "configmaps", True, "v1", "ConfigMap"),
    objects.PDBS: _Resource(
        "/apis/policy/v1", "poddisruptionbudgets", True, "policy/v1",
        "PodDisruptionBudget",
    ),
    objects.LEASES: _Resource(
        "/apis/coordination.k8s.io/v1", "leases", True, "coordination.k8s.io/v1",
        "Lease",
    ),
    objects.TPUJOBS: _Resource(
        f"/apis/{constants.GROUP_NAME}/{constants.VERSION}", constants.PLURAL, True,
        constants.API_VERSION, constants.KIND,
    ),
}


def _resource_for(kind: str) -> _Resource:
    try:
        return _RESOURCES[kind]
    except KeyError:
        # Unknown collections are assumed to be CRDs in our group, so new
        # resource kinds keep working without touching this table.
        return _Resource(
            f"/apis/{constants.GROUP_NAME}/{constants.VERSION}", kind, True,
            constants.API_VERSION, "",
        )


# ---------------------------------------------------------------------------
# Config resolution
# ---------------------------------------------------------------------------

class KubeConfigError(Exception):
    pass


# Re-mint this long before expirationTimestamp so a token never dies on the
# wire mid-request (client-go uses a similar expiry delta).
_EXEC_EXPIRY_MARGIN_S = 120.0


@dataclass
class ExecConfig:
    """users[].user.exec block: how to mint credentials via a plugin
    (client.authentication.k8s.io; gke-gcloud-auth-plugin is the canonical
    instance — reference auth stack: client-go exec.Authenticator)."""

    command: str
    args: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)  # additive to os.environ
    api_version: str = "client.authentication.k8s.io/v1beta1"
    provide_cluster_info: bool = False
    install_hint: str = ""
    # Cluster block forwarded via KUBERNETES_EXEC_INFO when
    # provide_cluster_info is set (server + CA the plugin may need).
    cluster_info: dict[str, Any] | None = None


@dataclass
class KubeConfig:
    """Resolved connection parameters for one cluster+user pair."""

    server: str
    token: str | None = None
    token_file: str | None = None
    ca_file: str | None = None
    ca_data: bytes | None = None  # PEM
    client_cert_file: str | None = None
    client_key_file: str | None = None
    client_cert_data: bytes | None = None  # PEM
    client_key_data: bytes | None = None  # PEM
    insecure_skip_tls_verify: bool = False
    exec_config: ExecConfig | None = None
    exec_timeout: float = 60.0  # plugin subprocess budget
    _tmpfiles: list[str] = field(default_factory=list, repr=False)
    _exec_token: str | None = field(default=None, repr=False)
    _exec_expiry: float | None = field(default=None, repr=False)
    _exec_lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def bearer_token(self) -> str | None:
        if self.token:
            return self.token
        if self.token_file:
            with open(self.token_file) as f:
                return f.read().strip()
        if self.exec_config is not None:
            return self._exec_bearer_token()
        return None

    # -- exec credential plugin ---------------------------------------------

    def invalidate_exec_token(self) -> None:
        """Drop the cached plugin token (called on a 401 so the next request
        re-mints; client-go's exec authenticator refreshes the same way)."""
        with self._exec_lock:
            self._exec_token = None
            self._exec_expiry = None

    def _exec_bearer_token(self) -> str:
        with self._exec_lock:
            if self._exec_token is not None and (
                self._exec_expiry is None
                or self._exec_expiry - time.time() > _EXEC_EXPIRY_MARGIN_S
            ):
                return self._exec_token
            cred = self._run_exec_plugin()
            status = cred.get("status") or {}
            token = status.get("token")
            if not token:
                if status.get("clientCertificateData"):
                    raise KubeConfigError(
                        "exec plugin returned TLS client-certificate "
                        "credentials; only token credentials are supported"
                    )
                raise KubeConfigError(
                    "exec plugin returned no status.token "
                    f"(command: {self.exec_config.command})"
                )
            expiry = None
            if status.get("expirationTimestamp"):
                expiry = parse_rfc3339(status["expirationTimestamp"])
            self._exec_token = token
            self._exec_expiry = expiry
            return token

    def _run_exec_plugin(self) -> dict[str, Any]:
        ec = self.exec_config
        assert ec is not None
        env = dict(os.environ)
        env.update(ec.env)
        # KUBERNETES_EXEC_INFO: the ExecCredential request object. Always
        # sent (plugins key their protocol version off it); the cluster
        # block rides along only under provideClusterInfo, as client-go does.
        spec: dict[str, Any] = {"interactive": False}
        if ec.provide_cluster_info and ec.cluster_info is not None:
            spec["cluster"] = ec.cluster_info
        env["KUBERNETES_EXEC_INFO"] = json.dumps(
            {
                "apiVersion": ec.api_version,
                "kind": "ExecCredential",
                "spec": spec,
            }
        )
        try:
            proc = subprocess.run(
                [ec.command, *ec.args],
                env=env,
                capture_output=True,
                text=True,
                timeout=self.exec_timeout,
            )
        except FileNotFoundError:
            hint = f"\n{ec.install_hint}" if ec.install_hint else ""
            raise KubeConfigError(
                f"exec credential plugin {ec.command!r} not found on PATH{hint}"
            ) from None
        except subprocess.TimeoutExpired:
            raise KubeConfigError(
                f"exec credential plugin {ec.command!r} timed out after "
                f"{self.exec_timeout:.0f}s"
            ) from None
        if proc.returncode != 0:
            raise KubeConfigError(
                f"exec credential plugin {ec.command!r} failed "
                f"(rc={proc.returncode}): {proc.stderr.strip()[:500]}"
            )
        try:
            cred = json.loads(proc.stdout)
        except ValueError as e:
            raise KubeConfigError(
                f"exec credential plugin {ec.command!r} wrote invalid JSON: {e}"
            ) from None
        if cred.get("kind") != "ExecCredential":
            raise KubeConfigError(
                f"exec credential plugin {ec.command!r} returned kind "
                f"{cred.get('kind')!r}, want ExecCredential"
            )
        return cred

    def ssl_context(self) -> ssl.SSLContext | None:
        if not self.server.startswith("https"):
            return None
        ctx = ssl.create_default_context()
        if self.insecure_skip_tls_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.ca_data is not None:
            ctx.load_verify_locations(cadata=self.ca_data.decode())
        elif self.ca_file:
            ctx.load_verify_locations(cafile=self.ca_file)
        cert, key = self.client_cert_file, self.client_key_file
        try:
            if self.client_cert_data is not None:
                cert = self._materialize(self.client_cert_data, "crt")
            if self.client_key_data is not None:
                key = self._materialize(self.client_key_data, "key")
            if cert and key:
                ctx.load_cert_chain(certfile=cert, keyfile=key)
        finally:
            # load_cert_chain reads the files synchronously; the key material
            # must not outlive the call on disk.
            for path in self._tmpfiles:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self._tmpfiles.clear()
        return ctx

    def _materialize(self, pem: bytes, suffix: str) -> str:
        # load_cert_chain only accepts file paths; inline kubeconfig data has
        # to hit disk briefly (0600; unlinked by ssl_context right after the
        # chain is loaded).
        fd, path = tempfile.mkstemp(suffix=f".{suffix}", prefix="kubecfg-")
        try:
            os.write(fd, pem)
        finally:
            os.close(fd)
        os.chmod(path, 0o600)
        self._tmpfiles.append(path)
        return path


def _b64(data: str) -> bytes:
    return base64.b64decode(data)


def load_kubeconfig(path: str | None = None, context: str | None = None) -> KubeConfig:
    """Parse a kubeconfig file into a KubeConfig.

    Resolution order for ``path``: explicit arg → $KUBECONFIG →
    ~/.kube/config, matching client-go's loading rules (and the reference's
    KUBECONFIG override, cmd/tf-operator.v2/app/server.go:76-80).
    """
    import yaml

    path = path or os.environ.get("KUBECONFIG") or os.path.expanduser("~/.kube/config")
    if not os.path.exists(path):
        raise KubeConfigError(f"kubeconfig not found at {path}")
    with open(path) as f:
        doc = yaml.safe_load(f) or {}

    ctx_name = context or doc.get("current-context")
    if not ctx_name:
        raise KubeConfigError(f"{path}: no current-context and none given")

    def _named(section: str, name: str) -> dict[str, Any]:
        for entry in doc.get(section, []) or []:
            if entry.get("name") == name:
                return entry.get(section.rstrip("s"), {}) or {}
        raise KubeConfigError(f"{path}: {section} entry {name!r} not found")

    ctx = _named("contexts", ctx_name)
    cluster = _named("clusters", ctx.get("cluster", ""))
    user = _named("users", ctx.get("user", "")) if ctx.get("user") else {}

    server = cluster.get("server")
    if not server:
        raise KubeConfigError(f"{path}: cluster {ctx.get('cluster')!r} has no server")

    def _rel(p: str | None) -> str | None:
        # Relative file references in a kubeconfig resolve against the
        # kubeconfig's own directory, as client-go does (kind/minikube configs
        # commonly use relative CA paths).
        if p and not os.path.isabs(p):
            return os.path.join(os.path.dirname(os.path.abspath(path)), p)
        return p

    cfg = KubeConfig(
        server=server,
        token=user.get("token"),
        token_file=_rel(user.get("tokenFile")),
        ca_file=_rel(cluster.get("certificate-authority")),
        insecure_skip_tls_verify=bool(cluster.get("insecure-skip-tls-verify", False)),
        client_cert_file=_rel(user.get("client-certificate")),
        client_key_file=_rel(user.get("client-key")),
    )
    if cluster.get("certificate-authority-data"):
        cfg.ca_data = _b64(cluster["certificate-authority-data"])
    if user.get("client-certificate-data"):
        cfg.client_cert_data = _b64(user["client-certificate-data"])
    if user.get("client-key-data"):
        cfg.client_key_data = _b64(user["client-key-data"])
    if user.get("auth-provider"):
        raise KubeConfigError(
            f"{path}: user {ctx.get('user')!r} uses the legacy auth-provider "
            "mechanism (removed from client-go in v1.26); migrate to an exec "
            "credential plugin (GKE: gke-gcloud-auth-plugin)"
        )
    if user.get("exec"):
        ex = user["exec"] or {}
        if not ex.get("command"):
            raise KubeConfigError(
                f"{path}: user {ctx.get('user')!r} exec block has no command"
            )
        cluster_info: dict[str, Any] = {"server": server}
        if cluster.get("certificate-authority-data"):
            cluster_info["certificate-authority-data"] = cluster[
                "certificate-authority-data"
            ]
        elif cfg.ca_file:
            cluster_info["certificate-authority"] = cfg.ca_file
        if cluster.get("insecure-skip-tls-verify"):
            cluster_info["insecure-skip-tls-verify"] = True
        cfg.exec_config = ExecConfig(
            command=ex["command"],
            args=list(ex.get("args") or []),
            env={
                e["name"]: e.get("value", "")
                for e in (ex.get("env") or [])
                if e.get("name")
            },
            api_version=ex.get(
                "apiVersion", "client.authentication.k8s.io/v1beta1"
            ),
            provide_cluster_info=bool(ex.get("provideClusterInfo", False)),
            install_hint=ex.get("installHint", ""),
            cluster_info=cluster_info,
        )
    return cfg


def in_cluster_config(sa_dir: str = SERVICEACCOUNT_DIR) -> KubeConfig:
    """In-cluster config from the service-account mount + KUBERNETES_SERVICE_*
    env (client-go rest.InClusterConfig; reference k8sutil.go:52-60)."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        raise KubeConfigError("KUBERNETES_SERVICE_HOST not set; not in a cluster")
    token_file = os.path.join(sa_dir, "token")
    if not os.path.exists(token_file):
        raise KubeConfigError(f"service-account token not found at {token_file}")
    ca_file = os.path.join(sa_dir, "ca.crt")
    if not os.path.exists(ca_file):
        # Fail loudly rather than silently disabling TLS verification — a
        # missing CA with a live bearer token is exactly the setup where a
        # MITM could steal the token (client-go errors here too).
        raise KubeConfigError(f"in-cluster CA bundle not found at {ca_file}")
    return KubeConfig(
        server=f"https://{host}:{port}",
        token_file=token_file,
        ca_file=ca_file,
    )


def resolve_config(
    kubeconfig: str | None = None, context: str | None = None
) -> KubeConfig:
    """In-cluster first, then kubeconfig — the reference's fallback order
    (k8sutil.go GetClusterConfig)."""
    if kubeconfig is None:
        try:
            return in_cluster_config()
        except KubeConfigError:
            pass
    return load_kubeconfig(kubeconfig, context)


# ---------------------------------------------------------------------------
# Error mapping
# ---------------------------------------------------------------------------

_REASONS = {
    "NotFound": NotFound,
    "AlreadyExists": AlreadyExists,
    "Conflict": Conflict,
    "Invalid": Invalid,
}
_CODES = {404: NotFound, 409: Conflict, 422: Invalid}


def _raise_status(err: urlerror.HTTPError) -> None:
    """Translate an apimachinery Status body into our error hierarchy."""
    reason, message = "", str(err)
    try:
        status = json.loads(err.read() or b"{}")
        reason = status.get("reason", "")
        message = status.get("message", message)
    except (ValueError, AttributeError):
        pass
    cls = _REASONS.get(reason) or _CODES.get(err.code, ApiError)
    exc = cls(message)
    exc.code = err.code
    raise exc from None


# ---------------------------------------------------------------------------
# The client
# ---------------------------------------------------------------------------

class KubeClusterClient(ClusterClient):
    """ClusterClient over a real (or wire-compatible) Kubernetes apiserver."""

    def __init__(
        self,
        config: KubeConfig,
        timeout: float = 30.0,
        list_page_size: int = 500,
        watch_timeout_seconds: float = 300.0,
    ) -> None:
        """``list_page_size``: LIST pagination chunk (limit+continue loop; 0
        disables and fetches whole collections in one response).
        ``watch_timeout_seconds``: server-side watch budget (the apiserver
        ends the stream after it, forcing a reconnect); the client also arms
        a read deadline slightly past it so a silently-dead TCP connection
        can never wedge the watch thread — the client-go reflector behavior
        the reference inherits."""
        self._cfg = config
        self._base = config.server.rstrip("/")
        self._timeout = timeout
        self._list_page_size = list_page_size
        self._watch_timeout_seconds = watch_timeout_seconds
        self._ssl = config.ssl_context()
        self._watch_stops: dict[Watch, threading.Event] = {}
        self._lock = threading.Lock()

    # -- plumbing -----------------------------------------------------------

    def _headers(self, content_type: str | None = None) -> dict[str, str]:
        h: dict[str, str] = {"Accept": "application/json"}
        token = self._cfg.bearer_token()
        if token:
            h["Authorization"] = f"Bearer {token}"
        if content_type:
            h["Content-Type"] = content_type
        return h

    def _open(self, req: urlrequest.Request, timeout: float | None):
        return urlrequest.urlopen(req, timeout=timeout, context=self._ssl)

    def _call(
        self,
        method: str,
        path: str,
        body: dict[str, Any] | None = None,
        content_type: str = "application/json",
    ) -> dict[str, Any]:
        data = json.dumps(body).encode() if body is not None else None
        retried_auth = False
        while True:
            req = urlrequest.Request(
                self._base + path,
                data=data,
                method=method,
                headers=self._headers(content_type if data is not None else None),
            )
            t0 = time.monotonic()
            try:
                with self._open(req, self._timeout) as resp:
                    code = str(resp.status)
                    out = json.loads(resp.read() or b"{}")
                REQUEST_SECONDS.observe(
                    time.monotonic() - t0, method=method, code=code
                )
                return out
            except urlerror.HTTPError as e:
                REQUEST_SECONDS.observe(
                    time.monotonic() - t0, method=method, code=str(e.code)
                )
                if (
                    e.code == 401
                    and not retried_auth
                    and self._cfg.exec_config is not None
                ):
                    # Expired/revoked plugin token: re-mint once and retry
                    # (client-go's exec authenticator refresh-on-401).
                    LOG.info("401 from apiserver; re-minting exec credential")
                    self._cfg.invalidate_exec_token()
                    retried_auth = True
                    continue
                _raise_status(e)
                raise  # unreachable
            except Exception:
                # Transport failures without an HTTP status (connect
                # refused, socket timeout, corrupt JSON): the slowest and
                # most alert-worthy requests — they must land in the
                # histogram, not vanish from it.
                REQUEST_SECONDS.observe(
                    time.monotonic() - t0, method=method, code="error"
                )
                raise

    def _collection(self, kind: str, namespace: str | None) -> str:
        r = _resource_for(kind)
        if not r.namespaced or namespace is None:
            return f"{r.prefix}/{r.plural}"
        return f"{r.prefix}/namespaces/{urlparse_mod.quote(namespace)}/{r.plural}"

    def _item(self, kind: str, namespace: str, name: str) -> str:
        return f"{self._collection(kind, namespace)}/{urlparse_mod.quote(name)}"

    @staticmethod
    def _stamp_gvk(kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        r = _resource_for(kind)
        out = dict(obj)
        out.setdefault("apiVersion", r.api_version)
        if r.kind:
            out.setdefault("kind", r.kind)
        return out

    # -- ClusterClient ------------------------------------------------------

    def create(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="create", kind=kind)
        ns = objects.namespace_of(obj)
        objects.meta(obj).setdefault("namespace", ns)
        return self._call(
            "POST", self._collection(kind, ns), self._stamp_gvk(kind, obj)
        )

    def get(self, kind: str, namespace: str, name: str) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="get", kind=kind)
        return self._call("GET", self._item(kind, namespace, name))

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict[str, Any]]:
        return self._list_raw(kind, namespace, label_selector)["items"] or []

    def _list_raw(
        self,
        kind: str,
        namespace: str | None,
        label_selector: dict[str, str] | None = None,
    ) -> dict[str, Any]:
        """Paginated LIST: limit+continue loop (client-go reflector style) so
        a 10k-pod collection never lands in one response body. The returned
        metadata is the FINAL page's — its resourceVersion is the collection
        RV as of the first page's snapshot, which is what watch resume needs."""
        API_REQUESTS_TOTAL.inc(verb="list", kind=kind)
        base_params: dict[str, str] = {}
        if label_selector:
            base_params["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items())
            )
        if self._list_page_size:
            base_params["limit"] = str(self._list_page_size)
        items: list[dict[str, Any]] = []
        cont: str | None = None
        while True:
            params = dict(base_params)
            if cont:
                params["continue"] = cont
            qs = ("?" + urlparse_mod.urlencode(params)) if params else ""
            try:
                out = self._call("GET", self._collection(kind, namespace) + qs)
            except ApiError as e:
                if cont and getattr(e, "code", None) == 410:
                    # Continue token expired mid-pagination (etcd compacted
                    # the list snapshot). client-go's reflector falls back to
                    # one unpaginated full list; restarting the limit loop
                    # from page 1 could expire again forever on a slow walk.
                    LOG.warning(
                        "list %s continue token expired; falling back to "
                        "unpaginated list", kind,
                    )
                    fallback = {
                        k: v for k, v in base_params.items() if k != "limit"
                    }
                    qs = ("?" + urlparse_mod.urlencode(fallback)) if fallback else ""
                    out = self._call("GET", self._collection(kind, namespace) + qs)
                    out.setdefault("items", [])
                    return out
                raise
            items.extend(out.get("items") or [])
            cont = (out.get("metadata") or {}).get("continue")
            if not cont:
                out["items"] = items
                return out

    def update(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="update", kind=kind)
        ns, name = objects.namespace_of(obj), objects.name_of(obj)
        return self._call(
            "PUT", self._item(kind, ns, name), self._stamp_gvk(kind, obj)
        )

    def update_status(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="update_status", kind=kind)
        ns, name = objects.namespace_of(obj), objects.name_of(obj)
        return self._call(
            "PUT", self._item(kind, ns, name) + "/status", self._stamp_gvk(kind, obj)
        )

    def patch_merge(
        self, kind: str, namespace: str, name: str, patch: dict[str, Any]
    ) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="patch", kind=kind)
        return self._call(
            "PATCH",
            self._item(kind, namespace, name),
            patch,
            content_type="application/merge-patch+json",
        )

    def delete(self, kind: str, namespace: str, name: str) -> None:
        API_REQUESTS_TOTAL.inc(verb="delete", kind=kind)
        self._call("DELETE", self._item(kind, namespace, name))

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, namespace: str | None = None) -> Watch:
        """Streamed watch with resourceVersion resume.

        Semantics match the in-memory cluster (and the informer's needs):
        events start flowing from "now". Internally: LIST to pin the
        collection RV, then WATCH from it; on disconnect reconnect from the
        last delivered RV; on 410 Gone relist for a fresh RV (the informer's
        periodic resync repairs anything missed during the gap).
        """
        API_REQUESTS_TOTAL.inc(verb="watch", kind=kind)
        watch = Watch()
        stopped = threading.Event()
        with self._lock:
            self._watch_stops[watch] = stopped
        t = threading.Thread(
            target=self._watch_loop,
            args=(kind, namespace, watch, stopped),
            name=f"kubewatch-{kind}",
            daemon=True,
        )
        t.start()
        return watch

    def _watch_loop(
        self, kind: str, namespace: str | None, watch: Watch, stopped: threading.Event
    ) -> None:
        rv: str | None = None
        while not stopped.is_set():
            try:
                if rv is None:
                    rv = str(
                        self._list_raw(kind, namespace)
                        .get("metadata", {})
                        .get("resourceVersion", "")
                    )
                params = {"watch": "true", "allowWatchBookmarks": "true"}
                if self._watch_timeout_seconds:
                    # Server-side budget: the apiserver ends the stream after
                    # this, so each watch request is finite and reconnects
                    # re-authenticate (exec tokens rotate naturally).
                    params["timeoutSeconds"] = str(
                        max(1, int(self._watch_timeout_seconds))
                    )
                if rv:
                    params["resourceVersion"] = rv
                url = (
                    self._base
                    + self._collection(kind, namespace)
                    + "?"
                    + urlparse_mod.urlencode(params)
                )
                req = urlrequest.Request(url, headers=self._headers())
                # Read deadline slightly past the server budget: a
                # silently-dead TCP connection (no FIN, no data) raises
                # timeout instead of wedging this thread forever. Heartbeat
                # chunks from the server reset the socket timer, so an idle
                # but LIVE stream is unaffected.
                read_deadline = (
                    self._watch_timeout_seconds + 30.0
                    if self._watch_timeout_seconds
                    else None
                )
                resp = self._open(req, read_deadline)
                watch._resp = resp  # stop_watch closes it to unblock the read
                for raw in resp:
                    if stopped.is_set():
                        break
                    line = raw.strip()
                    if not line:
                        continue
                    payload = json.loads(line)
                    etype, obj = payload.get("type"), payload.get("object", {})
                    if etype == "BOOKMARK":
                        rv = objects.meta(obj).get("resourceVersion", rv)
                        continue
                    if etype == "ERROR":
                        if obj.get("code") == 410:  # Gone: RV too old, relist
                            WATCH_RESTARTS.inc(kind=kind, reason="gone")
                            rv = None
                            break
                        raise ApiError(obj.get("message", "watch error"))
                    new_rv = objects.meta(obj).get("resourceVersion")
                    if new_rv:
                        rv = str(new_rv)
                    watch.push(WatchEvent(etype, obj))
                else:
                    # The server ended the stream cleanly (timeoutSeconds
                    # budget): the healthy reconnect cadence, counted so
                    # operators can tell it apart from a wedged watch.
                    if not stopped.is_set():
                        WATCH_RESTARTS.inc(kind=kind, reason="expired")
            except urlerror.HTTPError as e:
                if e.code == 410:
                    WATCH_RESTARTS.inc(kind=kind, reason="gone")
                    rv = None
                elif e.code == 401 and self._cfg.exec_config is not None:
                    # Revoked/rotated plugin token: without this the watch
                    # would retry the same stale cached token forever while
                    # _call re-mints (the informer silently serving stale
                    # state the whole time).
                    LOG.info("watch %s got 401; re-minting exec credential", kind)
                    WATCH_RESTARTS.inc(kind=kind, reason="auth")
                    self._cfg.invalidate_exec_token()
                    stopped.wait(0.2)
                elif not stopped.is_set():
                    LOG.warning("watch %s failed: %s; reconnecting", kind, e)
                    WATCH_RESTARTS.inc(kind=kind, reason="error")
                    stopped.wait(1.0)
            except Exception as e:
                if not stopped.is_set():
                    LOG.debug("watch %s stream ended (%s); reconnecting", kind, e)
                    # Server-sent watch ERRORs (ApiError, raised above) are
                    # genuine errors; everything else here is a died stream.
                    WATCH_RESTARTS.inc(
                        kind=kind,
                        reason="error" if isinstance(e, ApiError) else "eof",
                    )
                    stopped.wait(1.0)
        watch.stop()

    def stop_watch(self, watch: Watch) -> None:
        with self._lock:
            stopped = self._watch_stops.pop(watch, None)
        if stopped is not None:
            stopped.set()
        resp = getattr(watch, "_resp", None)
        if resp is not None:
            try:
                resp.close()
            except Exception:
                pass
        watch.stop()
