"""Leader election over a Lease object with optimistic concurrency.

Parity: cmd/tf-operator.v2/app/server.go:140-152 — the reference runs the
controller under an Endpoints-lock leader election (lease 15 s / renew 5 s /
retry 3 s) so multiple operator replicas are HA without double-reconciling.
This implementation uses the modern coordination Lease shape over the
framework's ClusterClient: acquisition and renewal are compare-and-swap
updates guarded by resourceVersion, so two candidates racing on the same
lease cannot both win (the in-memory cluster and a real apiserver both
enforce the Conflict).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable

from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import (
    AlreadyExists,
    ClusterClient,
    Conflict,
    NotFound,
)
from tf_operator_tpu.utils import logger


@dataclass
class LeaderElectionConfig:
    """Defaults match the reference's constants (server.go:49-52)."""

    lease_name: str = "tpu-operator"
    namespace: str = "default"
    lease_duration: float = 15.0
    renew_deadline: float = 5.0
    retry_period: float = 3.0


def _lease_obj(cfg: LeaderElectionConfig, identity: str) -> dict:
    return {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {"name": cfg.lease_name, "namespace": cfg.namespace},
        "spec": {
            "holderIdentity": identity,
            "leaseDurationSeconds": int(cfg.lease_duration),
            "acquireTime": objects.now_iso(),
            "renewTime": time.time(),
        },
    }


class LeaderElector:
    """run() blocks until stop; on_started_leading is called (in a worker
    thread) each time leadership is acquired, on_stopped_leading when it is
    lost or released."""

    def __init__(
        self,
        client: ClusterClient,
        identity: str,
        on_started_leading: Callable[[threading.Event], None],
        on_stopped_leading: Callable[[], None] | None = None,
        config: LeaderElectionConfig | None = None,
    ) -> None:
        self._client = client
        self.identity = identity
        self._on_started = on_started_leading
        self._on_stopped = on_stopped_leading
        self.cfg = config or LeaderElectionConfig()
        self._log = logger.with_fields(component="leader-election", id=identity)
        self.is_leader = threading.Event()

    # -- lease CAS ----------------------------------------------------------

    def _try_acquire_or_renew(self) -> bool:
        cfg = self.cfg
        now = time.time()
        try:
            lease = self._client.get(objects.LEASES, cfg.namespace, cfg.lease_name)
        except NotFound:
            try:
                self._client.create(objects.LEASES, _lease_obj(cfg, self.identity))
                return True
            except AlreadyExists:
                return False

        spec = lease.setdefault("spec", {})
        holder = spec.get("holderIdentity")
        renew = float(spec.get("renewTime", 0) or 0)
        expired = now - renew > cfg.lease_duration
        if holder != self.identity and not expired:
            return False
        # Ours to renew, or expired and up for grabs — CAS on resourceVersion.
        spec["holderIdentity"] = self.identity
        spec["renewTime"] = now
        if holder != self.identity:
            spec["acquireTime"] = objects.now_iso()
            spec["leaseTransitions"] = int(spec.get("leaseTransitions", 0)) + 1
        try:
            self._client.update(objects.LEASES, lease)
            return True
        except (Conflict, NotFound):
            return False

    def release(self) -> None:
        """Give up the lease voluntarily (clean shutdown)."""
        cfg = self.cfg
        try:
            lease = self._client.get(objects.LEASES, cfg.namespace, cfg.lease_name)
            if lease.get("spec", {}).get("holderIdentity") == self.identity:
                lease["spec"]["renewTime"] = 0  # instantly expired
                self._client.update(objects.LEASES, lease)
        except (NotFound, Conflict):
            pass

    # -- loop ---------------------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        cfg = self.cfg
        leading_stop: threading.Event | None = None
        worker: threading.Thread | None = None
        while not stop.is_set():
            got = self._try_acquire_or_renew()
            if got and not self.is_leader.is_set():
                self._log.info("became leader")
                self.is_leader.set()
                leading_stop = threading.Event()
                worker = threading.Thread(
                    target=self._on_started, args=(leading_stop,), daemon=True
                )
                worker.start()
            elif not got and self.is_leader.is_set():
                self._log.warning("lost leadership")
                self.is_leader.clear()
                if leading_stop is not None:
                    leading_stop.set()
                if self._on_stopped:
                    self._on_stopped()
            interval = cfg.renew_deadline if self.is_leader.is_set() else cfg.retry_period
            stop.wait(interval)
        if self.is_leader.is_set():
            if leading_stop is not None:
                leading_stop.set()
            self.release()
            self.is_leader.clear()
            if self._on_stopped:
                self._on_stopped()
