"""Shared HTTP handler plumbing for the framework's JSON servers.

Both in-process servers — the framework-native REST apiserver
(runtime/apiserver.py) and the K8s wire-protocol stub (runtime/kubestub.py) —
speak JSON over BaseHTTPRequestHandler; this mixin holds the response/body/
query helpers so fixes to e.g. Content-Length handling land in both.
"""

from __future__ import annotations

import json
from typing import Any


class JsonHandlerMixin:
    """Helpers for BaseHTTPRequestHandler subclasses serving JSON APIs."""

    def send_json(self, payload: Any, code: int = 200) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)  # type: ignore[attr-defined]
        self.send_header("Content-Type", "application/json")  # type: ignore[attr-defined]
        self.send_header("Content-Length", str(len(body)))  # type: ignore[attr-defined]
        self.end_headers()  # type: ignore[attr-defined]
        self.wfile.write(body)  # type: ignore[attr-defined]

    def read_json_body(self) -> dict[str, Any]:
        length = int(self.headers.get("Content-Length", 0))  # type: ignore[attr-defined]
        if length == 0:
            return {}
        return json.loads(self.rfile.read(length))  # type: ignore[attr-defined]

    @staticmethod
    def first_query_value(query: dict[str, list[str]], key: str) -> str | None:
        vals = query.get(key)
        return vals[0] if vals else None

    def write_chunk(self, data: bytes) -> None:
        """One chunk of a Transfer-Encoding: chunked response."""
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")  # type: ignore[attr-defined]
        self.wfile.flush()  # type: ignore[attr-defined]
