"""Helpers over unstructured (dict-form) core/v1-style objects.

The runtime stores every resource — pods, services, TPUJobs, disruption
budgets, events, leases — as plain dicts shaped like their Kubernetes
counterparts, so the same controller code drives both the in-memory cluster
(tests, local E2E) and a real apiserver (runtime/kubeclient.py). This module
is the accessor layer the controllers use instead of typed structs.
"""

from __future__ import annotations

import copy
import time
from typing import Any

# Resource "kinds" as store collection names (lowercase plural, like REST paths).
PODS = "pods"
SERVICES = "services"
TPUJOBS = "tpujobs"
# Long-running serving fleets (tf_operator_tpu/fleet/): stored like any
# other CRD in the group — both backends treat unknown collections
# generically, so no store/stub changes ride this kind.
TPUSERVES = "tpuserves"
PDBS = "poddisruptionbudgets"
EVENTS = "events"
LEASES = "leases"
NAMESPACES = "namespaces"
# Nodes are cluster-scoped in Kubernetes; this runtime stores them under the
# "default" namespace (the kubestub routes /api/v1/nodes there), which both
# backends and the health monitor agree on.
NODES = "nodes"
CONFIGMAPS = "configmaps"

# TPU host labeling: a node declares which generation mesh it belongs to and
# which unit cells of that mesh its chips occupy, so fleet health can map a
# NotReady host back to scheduler coordinates (health/monitor.py).
LABEL_NODE_GENERATION = "tpu.tpuflow.org/generation"
ANNOTATION_NODE_CELLS = "tpu.tpuflow.org/cells"  # JSON: [[x,y,...], ...]

# Pod phases (core/v1).
PENDING = "Pending"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"
UNKNOWN = "Unknown"


def now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def meta(obj: dict[str, Any]) -> dict[str, Any]:
    return obj.setdefault("metadata", {})


def name_of(obj: dict[str, Any]) -> str:
    return meta(obj).get("name", "")


def namespace_of(obj: dict[str, Any]) -> str:
    return meta(obj).get("namespace", "default")


def uid_of(obj: dict[str, Any]) -> str:
    return meta(obj).get("uid", "")


def labels_of(obj: dict[str, Any]) -> dict[str, str]:
    return meta(obj).get("labels", {}) or {}


def annotations_of(obj: dict[str, Any]) -> dict[str, str]:
    return meta(obj).get("annotations", {}) or {}


def key_of(obj: dict[str, Any]) -> str:
    return f"{namespace_of(obj)}/{name_of(obj)}"


def is_deleted(obj: dict[str, Any]) -> bool:
    return bool(meta(obj).get("deletionTimestamp"))


def new_pod(
    name: str,
    namespace: str = "default",
    labels: dict[str, str] | None = None,
    containers: list[dict[str, Any]] | None = None,
    owner_references: list[dict[str, Any]] | None = None,
    **spec_extra: Any,
) -> dict[str, Any]:
    pod: dict[str, Any] = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"containers": copy.deepcopy(containers or [])},
        "status": {"phase": PENDING},
    }
    if labels:
        pod["metadata"]["labels"] = dict(labels)
    if owner_references:
        pod["metadata"]["ownerReferences"] = copy.deepcopy(owner_references)
    pod["spec"].update(spec_extra)
    return pod


def new_service(
    name: str,
    namespace: str = "default",
    labels: dict[str, str] | None = None,
    selector: dict[str, str] | None = None,
    ports: list[dict[str, Any]] | None = None,
    owner_references: list[dict[str, Any]] | None = None,
    headless: bool = True,
) -> dict[str, Any]:
    svc: dict[str, Any] = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "selector": dict(selector or {}),
            "ports": copy.deepcopy(ports or []),
        },
    }
    if headless:
        # Headless service: DNS resolves straight to the pod IP — the
        # rendezvous fabric (reference: replicas.go:151-162).
        svc["spec"]["clusterIP"] = "None"
    if labels:
        svc["metadata"]["labels"] = dict(labels)
    if owner_references:
        svc["metadata"]["ownerReferences"] = copy.deepcopy(owner_references)
    return svc


def new_pdb(
    name: str,
    namespace: str,
    min_available: int,
    selector_labels: dict[str, str],
    owner_references: list[dict[str, Any]] | None = None,
) -> dict[str, Any]:
    """Gang-scheduling PodDisruptionBudget (jobcontroller.go:196-232 analog)."""
    pdb: dict[str, Any] = {
        "apiVersion": "policy/v1",
        "kind": "PodDisruptionBudget",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "minAvailable": min_available,
            "selector": {"matchLabels": dict(selector_labels)},
        },
    }
    if owner_references:
        pdb["metadata"]["ownerReferences"] = copy.deepcopy(owner_references)
    return pdb


def new_node(
    name: str,
    generation: str | None = None,
    cells: list[tuple[int, ...]] | None = None,
    ready: bool = True,
) -> dict[str, Any]:
    """A core/v1-shaped Node for the runtime store. TPU hosts carry the
    generation label + cells annotation the health monitor attributes
    heartbeats through; plain nodes omit both."""
    import json as _json

    node: dict[str, Any] = {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "namespace": "default", "labels": {}},
        "status": {},
    }
    if generation:
        node["metadata"]["labels"][LABEL_NODE_GENERATION] = generation
    if cells is not None:
        node["metadata"].setdefault("annotations", {})[
            ANNOTATION_NODE_CELLS
        ] = _json.dumps([list(c) for c in cells])
    set_node_ready(node, ready)
    return node


def node_ready(node: dict[str, Any]) -> bool:
    for cond in node.get("status", {}).get("conditions", []) or []:
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False  # no Ready condition = the kubelet never reported in


def set_node_ready(node: dict[str, Any], ready: bool) -> None:
    """Stamp the Ready condition + lastHeartbeatTime, kubelet-style."""
    now = now_iso()
    conds = node.setdefault("status", {}).setdefault("conditions", [])
    for cond in conds:
        if cond.get("type") == "Ready":
            cond["status"] = "True" if ready else "False"
            cond["lastHeartbeatTime"] = now
            break
    else:
        conds.append(
            {
                "type": "Ready",
                "status": "True" if ready else "False",
                "lastHeartbeatTime": now,
            }
        )
    node["status"]["lastHeartbeatTime"] = now


def node_heartbeat_time(node: dict[str, Any]) -> str | None:
    return node.get("status", {}).get("lastHeartbeatTime") or None


def node_generation(node: dict[str, Any]) -> str | None:
    return labels_of(node).get(LABEL_NODE_GENERATION) or None


def node_cells(node: dict[str, Any]) -> list[tuple[int, ...]]:
    import json as _json

    raw = (meta(node).get("annotations") or {}).get(ANNOTATION_NODE_CELLS)
    if not raw:
        return []
    try:
        return [tuple(int(x) for x in c) for c in _json.loads(raw)]
    except (ValueError, TypeError):
        return []


def pod_phase(pod: dict[str, Any]) -> str:
    return pod.get("status", {}).get("phase", PENDING)


def set_pod_phase(pod: dict[str, Any], phase: str) -> None:
    pod.setdefault("status", {})["phase"] = phase


def container_statuses(pod: dict[str, Any]) -> list[dict[str, Any]]:
    return pod.get("status", {}).get("containerStatuses", [])


def _terminated_state(
    pod: dict[str, Any], container_name: str
) -> dict[str, Any] | None:
    for cs in container_statuses(pod):
        if cs.get("name") == container_name:
            return cs.get("state", {}).get("terminated")
    return None


def terminated_exit_code(pod: dict[str, Any], container_name: str) -> int | None:
    """Exit code of a terminated container, or None if not terminated.

    Mirrors how the reference reads pod.Status.ContainerStatuses[i].State
    .Terminated.ExitCode for the default container (controller_pod.go:93-99).
    """
    term = _terminated_state(pod, container_name)
    return int(term.get("exitCode", 0)) if term is not None else None


def terminated_reason(pod: dict[str, Any], container_name: str) -> str | None:
    """Kubelet's termination reason ("OOMKilled", "Error", ...) for a
    terminated container, or None."""
    term = _terminated_state(pod, container_name)
    if term is None:
        return None
    return str(term.get("reason", "")) or None


def set_container_terminated(
    pod: dict[str, Any], container_name: str, exit_code: int, reason: str = ""
) -> None:
    statuses = pod.setdefault("status", {}).setdefault("containerStatuses", [])
    for cs in statuses:
        if cs.get("name") == container_name:
            cs["state"] = {"terminated": {"exitCode": exit_code, "reason": reason}}
            return
    statuses.append(
        {
            "name": container_name,
            "state": {"terminated": {"exitCode": exit_code, "reason": reason}},
            "restartCount": 0,
        }
    )


def get_container(pod_or_template: dict[str, Any], name: str) -> dict[str, Any] | None:
    for c in pod_or_template.get("spec", {}).get("containers", []):
        if c.get("name") == name:
            return c
    return None
