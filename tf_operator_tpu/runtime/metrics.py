"""Prometheus-style metrics registry (text exposition format).

The reference has **no metrics at all** (SURVEY.md §5: "Metrics: none");
status conditions and K8s Events are its only observables. This framework
keeps those surfaces and adds a real scrape endpoint: counters/gauges/
histograms with labels, rendered in the Prometheus text format at /metrics
on the operator's API server. Dependency-free (the environment does not
ship prometheus_client; the text format is trivial to emit).

Thread-safe; all mutation is under one lock per metric family.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Iterable

DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0,
)


def _fmt_labels(names: tuple[str, ...], values: tuple[str, ...],
                extra: str = "") -> str:
    pairs = [f'{n}="{_escape(v)}"' for n, v in zip(names, values)]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    return repr(float(v)) if not float(v).is_integer() else str(int(v))


class _Family:
    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 labelnames: Iterable[str] = ()) -> None:
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._series: dict[tuple[str, ...], object] = {}

    def _key(self, labels: dict[str, str]) -> tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)


class Counter(_Family):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))

    def render(self) -> list[str]:
        with self._lock:
            snap = sorted(self._series.items())
        return [
            f"{self.name}{_fmt_labels(self.labelnames, key)} {_fmt_value(v)}"
            for key, v in snap
        ]


class Gauge(_Family):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._series[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))

    render = Counter.render


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name: str, help_text: str,
                 labelnames: Iterable[str] = (),
                 buckets: Iterable[float] = DEFAULT_BUCKETS) -> None:
        super().__init__(name, help_text, labelnames)
        self.buckets = tuple(sorted(buckets))

    def observe(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = {"counts": [0] * (len(self.buckets) + 1),
                          "sum": 0.0, "n": 0}
                self._series[key] = series
            # First bucket whose upper bound (le) admits the value; values
            # beyond the last bound land in the +Inf overflow slot.
            series["counts"][bisect_left(self.buckets, value)] += 1
            series["sum"] += value
            series["n"] += 1

    def snapshot(self, **labels: str) -> list[int]:
        """Merged per-bucket counts now — pass to quantile(since=...) to
        measure only observations made after this point (the registry is
        process-global, so long-lived tests must window their reads)."""
        with self._lock:
            if labels:
                series = [self._series.get(self._key(labels))]
                series = [s for s in series if s]
            else:
                series = list(self._series.values())
            counts = [0] * (len(self.buckets) + 1)
            for s in series:
                for i, c in enumerate(s["counts"]):
                    counts[i] += c
        return counts

    def quantile(self, q: float, since: list[int] | None = None,
                 **labels: str) -> float:
        """Upper bucket bound holding the q-th observation (conservative).

        With labels: that series only; without: all series merged. ``since``
        (a snapshot() result) subtracts earlier observations. Returns 0.0
        with no observations, +inf when the quantile lands in the overflow
        bucket.
        """
        counts = self.snapshot(**labels)
        if since is not None:
            counts = [max(0, c - s) for c, s in zip(counts, since)]
        total = sum(counts)
        if not total:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                return self.buckets[i] if i < len(self.buckets) else float("inf")
        return float("inf")

    def render(self) -> list[str]:
        out = []
        with self._lock:
            snap = sorted(
                (k, {"counts": list(s["counts"]), "sum": s["sum"], "n": s["n"]})
                for k, s in self._series.items()
            )
        for key, s in snap:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += s["counts"][i]
                # The le label is built outside the f-string: a backslash in
                # an f-string expression part is a SyntaxError before 3.12.
                le = 'le="%s"' % _fmt_value(b)
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(self.labelnames, key, le)}"
                    f" {cum}"
                )
            cum += s["counts"][-1]
            inf = 'le="+Inf"'
            out.append(
                f"{self.name}_bucket"
                f"{_fmt_labels(self.labelnames, key, inf)} {cum}"
            )
            out.append(
                f"{self.name}_sum{_fmt_labels(self.labelnames, key)} "
                f"{repr(float(s['sum']))}"
            )
            out.append(
                f"{self.name}_count{_fmt_labels(self.labelnames, key)} {s['n']}"
            )
        return out


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _register(self, fam: _Family) -> _Family:
        with self._lock:
            existing = self._families.get(fam.name)
            if existing is not None:
                # Re-registration must be an exact match — a silent return
                # of a differently-shaped family would defer the error to
                # emission time, far from the offending registration.
                if type(existing) is not type(fam):
                    raise ValueError(f"{fam.name} already registered as "
                                     f"{existing.kind}")
                if existing.labelnames != fam.labelnames:
                    raise ValueError(
                        f"{fam.name} already registered with labels "
                        f"{existing.labelnames}, got {fam.labelnames}"
                    )
                if (
                    isinstance(existing, Histogram)
                    and existing.buckets != fam.buckets  # type: ignore[attr-defined]
                ):
                    raise ValueError(
                        f"{fam.name} already registered with buckets "
                        f"{existing.buckets}"
                    )
                return existing
            self._families[fam.name] = fam
            return fam

    def counter(self, name: str, help_text: str = "",
                labelnames: Iterable[str] = ()) -> Counter:
        return self._register(Counter(name, help_text, labelnames))  # type: ignore[return-value]

    def gauge(self, name: str, help_text: str = "",
              labelnames: Iterable[str] = ()) -> Gauge:
        return self._register(Gauge(name, help_text, labelnames))  # type: ignore[return-value]

    def histogram(self, name: str, help_text: str = "",
                  labelnames: Iterable[str] = (),
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram(name, help_text, labelnames, buckets))  # type: ignore[return-value]

    def render(self) -> str:
        lines: list[str] = []
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()


# ---------------------------------------------------------------------------
# Control-plane traffic observability (consumed by runtime/memcluster.py,
# runtime/kubeclient.py and controller/informer.py). Declared here so every
# process exposes the full schema from the first scrape, and so the scale
# benchmark (tools/bench_control_plane.py) can assert "steady-state
# reconcile waves issue zero API list calls" against real counters rather
# than log scraping.
# ---------------------------------------------------------------------------

API_REQUESTS_TOTAL = REGISTRY.counter(
    "tpu_api_requests_total",
    "Cluster API requests issued through a ClusterClient implementation, "
    "by verb and resource kind — LOGICAL requests: memcluster counts "
    "in-process store calls; kubeclient counts one per call (a paginated "
    "LIST still counts once, not per page); over the wire stub both "
    "sides count, one hop each",
    ("verb", "kind"),
)
INFORMER_CACHE_SIZE = REGISTRY.gauge(
    "tpu_informer_cache_size",
    "Objects resident in the informer cache, by resource kind",
    ("kind",),
)
INFORMER_INDEX_HITS = REGISTRY.counter(
    "tpu_informer_index_hits_total",
    "Informer cache reads served by a secondary index (namespace / owner "
    "uid / label term) instead of a full cache scan",
    ("kind", "index"),
)


# ---------------------------------------------------------------------------
# Gang-scheduler metric families (consumed by tf_operator_tpu/scheduler/).
# Declared here rather than in the scheduler so every process that imports
# the registry exposes the full schema on /metrics from the first scrape —
# a dashboard pointed at a freshly-started, still-idle operator sees the
# queue series at 0 instead of absent.
# ---------------------------------------------------------------------------

SCHED_QUEUE_DEPTH = REGISTRY.gauge(
    "tpu_scheduler_queue_depth", "Gangs waiting for admission",
)
SCHED_ADMITTED_GANGS = REGISTRY.gauge(
    "tpu_scheduler_admitted_gangs", "Gangs currently holding capacity",
)
SCHED_CHIPS_IN_USE = REGISTRY.gauge(
    "tpu_scheduler_chips_in_use",
    "TPU chips committed to admitted gangs", ("generation",),
)
SCHED_ADMISSION_SECONDS = REGISTRY.histogram(
    "tpu_scheduler_admission_latency_seconds",
    "Enqueue-to-admission wall time per gang",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0,
             1800.0),
)
SCHED_ADMISSIONS_TOTAL = REGISTRY.counter(
    "tpu_scheduler_admissions_total", "Gang admissions",
)
SCHED_PREEMPTIONS_TOTAL = REGISTRY.counter(
    "tpu_scheduler_preemptions_total", "Whole-gang preemption evictions",
)
SCHED_RELEASES_TOTAL = REGISTRY.counter(
    "tpu_scheduler_gate_releases_total",
    "Pods whose admission gate was lifted",
)


# ---------------------------------------------------------------------------
# Fleet-health metric families (consumed by tf_operator_tpu/health/ and the
# scheduler's migration path). Same rationale as above: declared at import
# so /metrics exposes the full schema before the first signal arrives.
# ---------------------------------------------------------------------------

HEALTH_CELLS = REGISTRY.gauge(
    "tpu_health_cells",
    "Fleet cells by health state (Healthy cells with no open suspicion "
    "are not tracked individually and read 0)",
    ("generation", "state"),
)
HEALTH_SIGNALS_TOTAL = REGISTRY.counter(
    "tpu_health_signals_total",
    "Health signals ingested, by source",
    ("source",),
)
HEALTH_CORDONS_TOTAL = REGISTRY.counter(
    "tpu_health_cordons_total",
    "Cells withdrawn from placement, by triggering source",
    ("source",),
)
HEALTH_UNCORDONS_TOTAL = REGISTRY.counter(
    "tpu_health_uncordons_total",
    "Cells returned to service (manual or repair-probe auto-uncordon)",
)
HEALTH_MIGRATIONS_TOTAL = REGISTRY.counter(
    "tpu_health_migrations_total",
    "Gangs checkpoint-signaled and evicted off draining/cordoned cells",
)


# ---------------------------------------------------------------------------
# Checkpoint-coordination metric families (consumed by tf_operator_tpu/ckpt/,
# the scheduler's eviction barrier, and the pod reconciler's resume
# injection). Declared at import for the same full-schema-on-first-scrape
# reason as the scheduler and health families above.
# ---------------------------------------------------------------------------

CKPT_SIGNALS_TOTAL = REGISTRY.counter(
    "tpu_checkpoint_signals_total",
    "Eviction checkpoint signals sent to gangs, by eviction reason",
    ("reason",),
)
CKPT_ACKS_TOTAL = REGISTRY.counter(
    "tpu_checkpoint_acks_total",
    "Job-level checkpoint roll-up advances (a new step became the durable "
    "resume point)",
)
CKPT_BARRIER_SECONDS = REGISTRY.histogram(
    "tpu_checkpoint_barrier_seconds",
    "Signal-to-eviction wall time of the graceful-eviction barrier",
    ("result",),  # acked | expired
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0),
)
CKPT_SKIPPED_TOTAL = REGISTRY.counter(
    "tpu_checkpoint_skipped_total",
    "Evictions that proceeded past the grace deadline without an ack",
)
CKPT_RESUME_INJECTIONS_TOTAL = REGISTRY.counter(
    "tpu_checkpoint_resume_injections_total",
    "Pods created with a TPU_RESUME_STEP resume contract injected",
)
CKPT_GC_STEPS_TOTAL = REGISTRY.counter(
    "tpu_checkpoint_gc_steps_total",
    "Checkpoint step directories removed by the retention sweeper",
)
CKPT_JOBS_REPORTING = REGISTRY.gauge(
    "tpu_checkpoint_jobs_reporting",
    "Jobs with a durable checkpoint record in the registry",
)
CKPT_STALE_JOBS = REGISTRY.gauge(
    "tpu_checkpoint_stale_jobs",
    "Running jobs whose checkpoint roll-up exceeds the staleness threshold",
)


# ---------------------------------------------------------------------------
# Continuous-batching serving metric families (consumed by
# tf_operator_tpu/serve/scheduler.py and rendered by serve_lm's /metrics).
# Declared at import for the same full-schema-on-first-scrape reason as the
# families above: a dashboard pointed at a just-started, still-idle server
# sees the queue/occupancy series at 0 instead of absent.
# ---------------------------------------------------------------------------

SERVE_QUEUE_DEPTH = REGISTRY.gauge(
    "tpu_serve_queue_depth",
    "Requests waiting for a free decode slot",
)
SERVE_SLOTS_ACTIVE = REGISTRY.gauge(
    "tpu_serve_active_slots",
    "Decode slots currently occupied by in-flight requests",
)
SERVE_SLOT_CAPACITY = REGISTRY.gauge(
    "tpu_serve_slot_capacity",
    "Preallocated decode slots (the engine's max batch)",
)
SERVE_REQUESTS_TOTAL = REGISTRY.counter(
    "tpu_serve_requests_total",
    "Requests finished by the continuous engine, by outcome "
    "(ok | error | rejected — rejected is the drain-time 503)",
    ("outcome",),
)
SERVE_TOKENS_TOTAL = REGISTRY.counter(
    "tpu_serve_generated_tokens_total",
    "Tokens generated across all slots (the tokens/sec numerator)",
)
SERVE_PREFILL_TOKENS_TOTAL = REGISTRY.counter(
    "tpu_serve_prefill_tokens_total",
    "Prompt tokens prefilled into slots",
)
SERVE_TTFT_SECONDS = REGISTRY.histogram(
    "tpu_serve_ttft_seconds",
    "Submit-to-first-generated-token wall time per request",
)
SERVE_ITL_SECONDS = REGISTRY.histogram(
    "tpu_serve_itl_seconds",
    "Inter-token latency: gap between consecutive generated tokens of "
    "one request, observed per retired request from its decode-step "
    "timestamps (the tail a streaming client actually feels; prefill "
    "interference on decode slots shows up HERE first)",
)
SERVE_PHASE_SECONDS = REGISTRY.counter(
    "tpu_serve_phase_seconds_total",
    "Cumulative host-observed device time by serving phase: prefill = "
    "prompt prefill slices, decode = batched decode steps, cow = "
    "copy-on-write block copies, prefill_interference = the subset of "
    "prefill time that ran WHILE decode slots were active (every such "
    "second is a second stolen from live decodes — the ROADMAP item-2 "
    "disaggregation pin reads this)",
    ("phase",),
)
SERVE_STEP_SECONDS = REGISTRY.histogram(
    "tpu_serve_step_seconds",
    "Serving-loop device iterations by phase: one decode step over the "
    "slot tensor, or one token-budgeted prefill slice",
    ("phase",),  # prefill | decode
)
SERVE_KV_BLOCKS = REGISTRY.gauge(
    "tpu_serve_kv_blocks",
    "Paged KV-cache pool blocks by state: free = allocatable now, "
    "used = held by live slots (the pinned garbage block 0 is excluded), "
    "shared = refcount >= 2 via prefix sharing",
    ("state",),
)
SERVE_KV_COW_TOTAL = REGISTRY.counter(
    "tpu_serve_kv_cow_copies_total",
    "Copy-on-write block copies: a slot's first decode write into a "
    "shared partial block copied it to a privately-owned block first",
)
SERVE_PREFILL_SAVED_TOTAL = REGISTRY.counter(
    "tpu_serve_prefill_tokens_saved_total",
    "Prompt tokens whose prefill was skipped because a shared prefix "
    "already held their K/V blocks",
)
SERVE_WATCHDOG_RESTARTS = REGISTRY.counter(
    "tpu_serve_watchdog_restarts_total",
    "Engine teardown + rebuild cycles performed by the serving watchdog, "
    "by trigger (stall = heartbeat silence past --watchdog-stall, "
    "crash = uncaught decode-loop exception)",
    ("reason",),
)
SERVE_DEADLINE_TOTAL = REGISTRY.counter(
    "tpu_serve_deadline_exceeded_total",
    "Requests resolved by a deadline instead of completion, by kind: "
    "queue = expired waiting for a slot (typed 408), decode = decode "
    "deadline hit mid-generation (200 + partial tokens + flag), drain = "
    "cut by the bounded SIGTERM drain (--drain-timeout, same partial "
    "path)",
    ("kind",),
)
SERVE_SHED_TOTAL = REGISTRY.counter(
    "tpu_serve_shed_total",
    "Requests rejected at submit because the bounded queue was at its "
    "watermark (reject-newest load shedding; typed 503 + Retry-After)",
)
SERVE_DEGRADED = REGISTRY.gauge(
    "tpu_serve_degraded",
    "1 while the engine admits in degraded mode (free KV blocks below "
    "the --degraded-blocks watermark caps admitted max_tokens), else 0",
)
SERVE_MESH_DEVICES = REGISTRY.gauge(
    "tpu_serve_mesh_devices",
    "Devices in the continuous engine's SPMD decode mesh (1 = "
    "single-chip; >1 = one compiled step drives the whole slice, KV "
    "storage head-sharded over the tp axis)",
)
SERVE_OCCUPANCY = REGISTRY.histogram(
    "tpu_serve_batch_occupancy",
    "Fraction of decode slots active, observed at every decode step — "
    "the quantity decode throughput is proportional to",
    buckets=(0.0625, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
)
SERVE_SHIP_INGEST_TOTAL = REGISTRY.counter(
    "tpu_serve_kv_ship_ingest_total",
    "Shipped-KV ingest attempts on a decode replica, by outcome (ok: "
    "blocks written + prefix registered; exhausted: no free blocks — "
    "the request requeued; unsupported: dense engine, shipment dropped "
    "and prefill ran locally; failed: malformed/mismatched payload, "
    "local-prefill fallback)",
    ("outcome",),
)
SERVE_SPEC_ACCEPT_TOKENS = REGISTRY.histogram(
    "tpu_serve_spec_accept_tokens",
    "Tokens emitted per slot per speculative round (the incoming pend "
    "token plus the accepted draft prefix, 1..k+1) — the distribution "
    "behind the engine's accept rate: mean/(k+1) near 1 means the draft "
    "is riding, near 1/(k+1) means every round falls back to one token",
    buckets=(1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 8.0, 12.0, 16.0),
)
SERVE_SPEC_ROUNDS_TOTAL = REGISTRY.counter(
    "tpu_serve_spec_rounds_total",
    "Speculative decode rounds executed (one per-slot draft of k tokens "
    "+ one batched k+1-position verify forward each) — tokens/round = "
    "tpu_serve_generated_tokens_total over this counter while the spec "
    "engine serves",
)
SERVE_SHIP_TOKENS_TOTAL = REGISTRY.counter(
    "tpu_serve_ship_tokens_total",
    "Prompt tokens whose K/V arrived as shipped block-pool rows from a "
    "dedicated prefill replica instead of local prefill (the "
    "disaggregation win: these tokens never time-shared the decode "
    "device)",
)
SERVE_KV_TIER_BYTES = REGISTRY.gauge(
    "tpu_serve_kv_tier_bytes",
    "Host-RAM KV tier occupancy by tier label (host = decoded bytes of "
    "spilled prefix payloads currently stored, host_free = remaining "
    "byte budget) — the second level of the KV memory hierarchy "
    "(docs/kv-tiering.md)",
    ("tier",),
)
SERVE_KV_TIER_RESTORES = REGISTRY.counter(
    "tpu_serve_kv_tier_restores_total",
    "Host-tier KV restore attempts on admission/prefetch, by outcome "
    "(ok: payload uploaded into pool blocks + prefix registered; "
    "exhausted: tier hit but no free HBM blocks — the request waits; "
    "miss: no stored prefix deeper than the hot HBM hit; failed: "
    "stored payload no longer decodes — dropped, local prefill runs)",
    ("outcome",),
)
SERVE_KV_TIER_SPILLS = REGISTRY.counter(
    "tpu_serve_kv_tier_spills_total",
    "Prefix entries spilled from the HBM block pool into the host-RAM "
    "KV tier when their last pool holder freed (retention reclaim, "
    "retire, CoW source release) instead of vanishing",
)
SERVE_CONSTRAINED_REQUESTS = REGISTRY.counter(
    "tpu_serve_constrained_requests_total",
    "Requests admitted with a compiled constraint program, by spec kind "
    "(json_schema/regex/choices) — unconstrained traffic never touches "
    "this counter (docs/constrained-decoding.md)",
    ("kind",),
)
SERVE_CONSTRAINED_STOPS = REGISTRY.counter(
    "tpu_serve_constrained_stops_total",
    "Completions finished by the host-side stop machinery, by reason "
    "(stop_sequence: a multi-token stop matched and the tail was "
    "trimmed; grammar_complete: the constraint DFA reached a state "
    "with nothing left to emit and the slot retired)",
    ("reason",),
)
SERVE_CONSTRAIN_PROGRAMS = REGISTRY.gauge(
    "tpu_serve_constrain_programs",
    "Compiled constraint programs resident in the device-side paged "
    "constraint pool (row ranges of the batch-wide allow/next tables); "
    "refcount-0 residents are reuse candidates, not leaks",
)
SERVE_CONSTRAIN_EVICTIONS = REGISTRY.counter(
    "tpu_serve_constrain_evictions_total",
    "Constraint-program evictions by tier (cache: host LRU of compiled "
    "DFAs outgrew its bound; pool: a refcount-0 resident gave up its "
    "device rows to an incoming bind) — steady growth under a stable "
    "program set means the cache/pool knobs are undersized",
    ("tier",),
)

# -- fleet serving (tf_operator_tpu/fleet/): TPUServe membership, the
# occupancy-aware router, and queue-depth autoscaling -----------------------

FLEET_REPLICAS = REGISTRY.gauge(
    "tpu_fleet_replicas",
    "Serve replicas by membership state (joining/ready/draining/"
    "cordoned/dead), per fleet — the gauges are process-global and one "
    "operator reconciles many fleets", ("fleet", "state"),
)
FLEET_ROUTER_REQUESTS = REGISTRY.counter(
    "tpu_fleet_router_requests_total",
    "Routed /generate requests by terminal outcome (ok: a replica "
    "answered 200; typed: a typed error survived the retry budget; "
    "no_replica: nothing routable; transport: unreachable past budget)",
    ("outcome",),
)
FLEET_ROUTER_RETRIES = REGISTRY.counter(
    "tpu_fleet_router_retries_total",
    "Retries on a DIFFERENT replica after a typed retryable error, by "
    "the error code that triggered them (PR 7's taxonomy is the router "
    "contract; the replica label in the payload attributes the failure)",
    ("code",),
)
FLEET_ROUTER_FAILOVERS = REGISTRY.counter(
    "tpu_fleet_router_failovers_total",
    "Transport-level failovers: the replica did not answer at all and "
    "the request moved to another one",
)
FLEET_AUTOSCALE_TOTAL = REGISTRY.counter(
    "tpu_fleet_autoscale_total",
    "Autoscaler target changes by direction (up/down)", ("direction",),
)
FLEET_QUEUE_DEPTH = REGISTRY.gauge(
    "tpu_fleet_queue_depth",
    "Aggregate queued requests across routable replicas, per fleet, as "
    "of the last membership probe sweep", ("fleet",),
)
FLEET_SHIP_TOTAL = REGISTRY.counter(
    "tpu_fleet_ship_total",
    "Two-stage (prefill pool -> decode pool) dispatch outcomes at the "
    "disaggregation router: shipped = KV prefilled remotely and "
    "attached to the decode send; prefill_pool_empty = no routable "
    "prefill replica, decode pool prefilled locally; local_fallback = "
    "the prefill stage failed typed/transport past its retry budget; "
    "ship_failed = a decode replica rejected the payload and the "
    "request re-ran with local prefill",
    ("outcome",),
)
FLEET_PREFIX_HITS = REGISTRY.counter(
    "tpu_fleet_prefix_hits_total",
    "Requests the prefix-aware router landed on a replica already "
    "advertising a prefix of the prompt's digest chain (scoring hits; "
    "pulls are counted separately in tpu_fleet_prefix_pulls_total)",
)
FLEET_PREFIX_PULLS = REGISTRY.counter(
    "tpu_fleet_prefix_pulls_total",
    "Cross-replica prefix pulls (GET /prefix/<digest>) by outcome: "
    "ok = shipment attached to the dispatch; prefix_not_found = the "
    "advertisement raced the holder's LRU (degraded to local prefill); "
    "transport_error = holder unreachable; ship_failed = the decode "
    "replica rejected the pulled bytes and re-ran with local prefill",
    ("outcome",),
)
FLEET_PREFIX_TOKENS_SAVED = REGISTRY.counter(
    "tpu_fleet_prefix_tokens_saved_total",
    "Router-side estimate of prefill tokens avoided by prefix-aware "
    "routing (exact hits and pulls save the whole prompt, partial "
    "chain hits the covered blocks); the replicas' "
    "tpu_serve_kv_prefill_tokens_saved_total is the ground truth",
)

# -- tracing (runtime/tracing.py): declared here, not there, so the
# registry module stays import-leaf and the tracer can import it --------------

TRACE_SPANS_DROPPED = REGISTRY.counter(
    "tpu_trace_spans_dropped_total",
    "Spans evicted from a tracer's bounded ring before export, by "
    "tracer process name — a non-zero rate means /debug/traces starts "
    "mid-story and --trace-capacity should grow",
    ("tracer",),
)
