"""REST ClusterClient: talks to a runtime/apiserver.py over HTTP.

The remote half of the process boundary: TPUJobClient, genjob, the E2E
harness, and out-of-process controllers construct a RestClusterClient with
the operator's URL and get the exact ClusterClient semantics the in-memory
store provides — same error types (NotFound/AlreadyExists/Conflict/Invalid
reconstructed from status codes + error names), same watch stream (chunked
JSON lines pumped into a Watch by a reader thread).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any
from urllib import error as urlerror
from urllib import parse as urlparse_mod
from urllib import request as urlrequest

from tf_operator_tpu.runtime.client import (
    AlreadyExists,
    ApiError,
    ClusterClient,
    Conflict,
    Invalid,
    NotFound,
    Watch,
    WatchEvent,
)
from tf_operator_tpu.runtime.metrics import API_REQUESTS_TOTAL

_ERRORS = {
    "NotFound": NotFound,
    "AlreadyExists": AlreadyExists,
    "Conflict": Conflict,
    "Invalid": Invalid,
}


def _raise_for(err: urlerror.HTTPError) -> None:
    try:
        payload = json.loads(err.read())
        cls = _ERRORS.get(payload.get("error", ""), ApiError)
        raise cls(payload.get("message", str(err)))
    except (ValueError, KeyError):
        raise ApiError(str(err)) from err


class RestClusterClient(ClusterClient):
    def __init__(
        self, base_url: str, timeout: float = 10.0, token: str | None = None
    ) -> None:
        self._base = base_url.rstrip("/")
        self._timeout = timeout
        # Bearer token for servers running with write auth
        # (--serve-token-file); defaults from the environment so every
        # --master consumer (client, genjob, harness) picks it up without
        # plumbing a flag through each CLI.
        self._token = token or os.environ.get("TPU_OPERATOR_API_TOKEN")
        self._watches: dict[Watch, threading.Event] = {}
        self._lock = threading.Lock()

    # -- plumbing -----------------------------------------------------------

    def _call(
        self, method: str, path: str, body: dict[str, Any] | None = None
    ) -> dict[str, Any]:
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"} if data else {}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        req = urlrequest.Request(
            self._base + path,
            data=data,
            method=method,
            headers=headers,
        )
        try:
            with urlrequest.urlopen(req, timeout=self._timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urlerror.HTTPError as e:
            _raise_for(e)
            raise  # unreachable; keeps type-checkers happy

    # -- ClusterClient ------------------------------------------------------

    def create(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="create", kind=kind)
        return self._call("POST", f"/api/{kind}", obj)

    def get(self, kind: str, namespace: str, name: str) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="get", kind=kind)
        return self._call("GET", f"/api/{kind}/{namespace}/{name}")

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict[str, Any]]:
        API_REQUESTS_TOTAL.inc(verb="list", kind=kind)
        params: dict[str, str] = {}
        if namespace is not None:
            params["namespace"] = namespace
        if label_selector:
            params["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items())
            )
        qs = ("?" + urlparse_mod.urlencode(params)) if params else ""
        return self._call("GET", f"/api/{kind}{qs}")["items"]

    def update(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="update", kind=kind)
        meta = obj.get("metadata", {})
        ns, name = meta.get("namespace", "default"), meta.get("name", "")
        return self._call("PUT", f"/api/{kind}/{ns}/{name}", obj)

    def update_status(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="update_status", kind=kind)
        meta = obj.get("metadata", {})
        ns, name = meta.get("namespace", "default"), meta.get("name", "")
        return self._call("PUT", f"/api/{kind}/{ns}/{name}/status", obj)

    def patch_merge(
        self, kind: str, namespace: str, name: str, patch: dict[str, Any]
    ) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="patch", kind=kind)
        return self._call("PATCH", f"/api/{kind}/{namespace}/{name}", patch)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        API_REQUESTS_TOTAL.inc(verb="delete", kind=kind)
        self._call("DELETE", f"/api/{kind}/{namespace}/{name}")

    def watch(self, kind: str, namespace: str | None = None) -> Watch:
        API_REQUESTS_TOTAL.inc(verb="watch", kind=kind)
        params: dict[str, str] = {"watch": "1"}
        if namespace is not None:
            params["namespace"] = namespace
        url = f"{self._base}/api/{kind}?{urlparse_mod.urlencode(params)}"
        watch = Watch()
        stopped = threading.Event()
        with self._lock:
            self._watches[watch] = stopped

        def reader() -> None:
            try:
                # No timeout: the server heartbeats; closing the response in
                # stop_watch unblocks the read.
                resp = urlrequest.urlopen(url)
                watch._resp = resp  # for stop_watch to close
                for raw in resp:
                    if stopped.is_set():
                        break
                    line = raw.strip()
                    if not line:
                        continue  # heartbeat
                    payload = json.loads(line)
                    watch.push(WatchEvent(payload["type"], payload["object"]))
            except Exception:
                pass  # connection closed (stop_watch or server shutdown)
            finally:
                watch.stop()

        threading.Thread(target=reader, daemon=True).start()
        return watch

    def stop_watch(self, watch: Watch) -> None:
        with self._lock:
            stopped = self._watches.pop(watch, None)
        if stopped is not None:
            stopped.set()
        resp = getattr(watch, "_resp", None)
        if resp is not None:
            try:
                resp.close()
            except Exception:
                pass
        watch.stop()
