"""Tracing: timed spans with Chrome-trace (catapult) export.

SURVEY.md §5 calls tracing out as absent from the reference (its only
latency observable is a per-sync wall-time log line); the rebuild adds it
for real. Spans are cheap (one monotonic clock pair + a deque append), keep
a bounded in-memory ring, and export in the `chrome://tracing` /
ui.perfetto.dev JSON format via /debug/traces on the operator API server.

Usage:
    from tf_operator_tpu.runtime.tracing import TRACER
    with TRACER.span("sync_job", job="ns/name"):
        ...

Spans record wall-clock microseconds (Chrome's "ts") from the tracer's
epoch, thread id as "tid", and keyword attributes as "args".
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Iterator


@dataclass
class Span:
    name: str
    start_us: float
    duration_us: float
    thread: int
    attrs: dict[str, Any] = field(default_factory=dict)


class Tracer:
    def __init__(self, capacity: int = 8192, process_name: str = "tpu-operator"):
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._epoch = time.monotonic()
        self.process_name = process_name
        self.enabled = True

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.monotonic()
        try:
            yield
        finally:
            t1 = time.monotonic()
            s = Span(
                name=name,
                start_us=(t0 - self._epoch) * 1e6,
                duration_us=(t1 - t0) * 1e6,
                thread=threading.get_ident() % 2**31,
                attrs=attrs,
            )
            with self._lock:
                self._spans.append(s)

    def spans(self, name: str | None = None) -> list[Span]:
        with self._lock:
            snap = list(self._spans)
        return [s for s in snap if name is None or s.name == name]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def export_chrome_trace(self) -> str:
        """Catapult JSON: load at chrome://tracing or ui.perfetto.dev."""
        events: list[dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": 1,
                "args": {"name": self.process_name},
            }
        ]
        for s in self.spans():
            events.append(
                {
                    "name": s.name,
                    "ph": "X",  # complete event
                    "pid": 1,
                    "tid": s.thread,
                    "ts": round(s.start_us, 3),
                    "dur": round(s.duration_us, 3),
                    "args": {k: str(v) for k, v in s.attrs.items()},
                }
            )
        return json.dumps({"traceEvents": events})


TRACER = Tracer()
