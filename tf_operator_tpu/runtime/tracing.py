"""Tracing: timed spans with Chrome-trace (catapult) export.

SURVEY.md §5 calls tracing out as absent from the reference (its only
latency observable is a per-sync wall-time log line); the rebuild adds it
for real. Spans are cheap (one monotonic clock pair + a deque append), keep
a bounded in-memory ring, and export in the `chrome://tracing` /
ui.perfetto.dev JSON format via /debug/traces on the operator API server.

Usage:
    from tf_operator_tpu.runtime.tracing import TRACER
    with TRACER.span("sync_job", job="ns/name"):
        ...

Spans record wall-clock microseconds (Chrome's "ts") from the tracer's
epoch, thread id as "tid", and keyword attributes as "args".

Two process-wide rings: ``TRACER`` is the control plane's
(process_name "tpu-operator", /debug/traces on the operator API server);
``SERVE_TRACER`` is the serving DATA plane's (process_name "tpu-serve",
/debug/traces on the serve HTTP surfaces — serve_lm, fleet replicas,
and the fleet router). Keeping them separate means a fleet trace never
interleaves reconcile-loop spans into a request timeline. Both are
process-global on purpose: a supervisor engine rebuild swaps the
scheduler/engine generation underneath but the ring (and every span the
dead generation recorded) survives, exactly like the /debug/serve
aggregates.

Cross-process merging (``merge_chrome_traces``): each tracer pairs its
monotonic epoch with a wall-clock stamp taken at the same instant and
exports it as ``epochUnixUs``, so traces fetched from N processes can be
rebased onto one timeline (the fleet router's /debug/traces and
``tpuctl trace`` both merge this way, keyed by the ``request_id`` span
attribute).

The ring is bounded and evictions are COUNTED (``dropped`` +
``tpu_trace_spans_dropped_total``) so "the trace ends here because the
ring wrapped" is observable, never silent; attribute values are
sanitized at export (printable, length-capped) so a weird prompt string
can never corrupt — or bloat — the JSON export. ``set_capacity`` is the
runtime knob (serve_lm ``--trace-capacity``; 0 disables tracing
entirely — the ``span``/``record`` fast path is then one attribute
read).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Iterator

from tf_operator_tpu.runtime.metrics import TRACE_SPANS_DROPPED

# Attr-value cap: long enough for ids/prompts-prefixes, short enough
# that a pathological attr cannot bloat the export.
_MAX_ATTR_CHARS = 256


def _sanitize_attr(value: Any) -> str:
    """Render one span attribute export-safe: stringified, control and
    other non-printable characters (incl. lone surrogates, which break
    strict JSON consumers) replaced, length-capped."""
    s = str(value)
    if not s.isprintable():
        s = "".join(
            ch if (ch.isprintable() or ch == " ") else "\\u%04x" % ord(ch)
            for ch in s
        )
    if len(s) > _MAX_ATTR_CHARS:
        s = s[:_MAX_ATTR_CHARS] + "..."
    return s


@dataclass
class Span:
    name: str
    start_us: float
    duration_us: float
    thread: int
    attrs: dict[str, Any] = field(default_factory=dict)


class Tracer:
    def __init__(self, capacity: int = 8192, process_name: str = "tpu-operator"):
        self._spans: deque[Span] = deque(maxlen=max(0, capacity))
        self._lock = threading.Lock()
        # The monotonic epoch and its wall-clock twin are captured
        # back-to-back: ts values are monotonic-relative (immune to
        # clock steps), epochUnixUs lets a merger rebase rings from
        # different processes onto one timeline.
        self._epoch = time.monotonic()
        self._epoch_unix = time.time()
        self.process_name = process_name
        self.enabled = capacity > 0
        self.dropped = 0

    @property
    def capacity(self) -> int:
        # lint: ok guarded-attr — atomic deque-reference read; maxlen is immutable per deque
        return self._spans.maxlen or 0

    def set_capacity(self, capacity: int) -> None:
        """Resize the ring (newest spans kept). 0 disables tracing —
        the span/record fast path becomes one attribute read."""
        with self._lock:
            if capacity <= 0:
                self.enabled = False
                self._spans = deque(maxlen=0)
            else:
                self.enabled = True
                self._spans = deque(self._spans, maxlen=capacity)

    def _append(self, s: Span) -> None:
        with self._lock:
            if (self._spans.maxlen is not None
                    and len(self._spans) == self._spans.maxlen):
                # deque(maxlen) evicts silently; the counter makes the
                # wrap observable ("the trace starts mid-story HERE").
                self.dropped += 1
                TRACE_SPANS_DROPPED.inc(tracer=self.process_name)
            self._spans.append(s)

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[None]:
        # lint: ok guarded-attr — hot-path volatile flag; set_capacity flips it under the GIL, a stale read mistraces one span
        if not self.enabled:
            yield
            return
        t0 = time.monotonic()
        try:
            yield
        finally:
            t1 = time.monotonic()
            self._append(Span(
                name=name,
                start_us=(t0 - self._epoch) * 1e6,
                duration_us=(t1 - t0) * 1e6,
                thread=threading.get_ident() % 2**31,
                attrs=attrs,
            ))

    def record(self, name: str, start_mono: float, end_mono: float,
               **attrs: Any) -> None:
        """Record a span from explicit ``time.monotonic()`` stamps — for
        phases measured across threads or assembled after the fact
        (queue wait from the enqueue stamp, decode intervals aggregated
        over many steps)."""
        # lint: ok guarded-attr — hot-path volatile flag, same contract as span() above
        if not self.enabled:
            return
        self._append(Span(
            name=name,
            start_us=(start_mono - self._epoch) * 1e6,
            duration_us=max(0.0, (end_mono - start_mono)) * 1e6,
            thread=threading.get_ident() % 2**31,
            attrs=attrs,
        ))

    def size(self) -> int:
        """Current ring depth — O(1), unlike ``len(spans())`` which
        copies the whole ring (debug snapshots poll this)."""
        with self._lock:
            return len(self._spans)

    def spans(self, name: str | None = None) -> list[Span]:
        with self._lock:
            snap = list(self._spans)
        return [s for s in snap if name is None or s.name == name]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def export_doc(self) -> dict[str, Any]:
        """The catapult document as a dict: ``traceEvents`` plus the
        merge metadata (``epochUnixUs``, ``droppedSpans``, ``process``).
        Extra top-level keys are legal in the Chrome trace JSON object
        format and ignored by viewers."""
        events: list[dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": 1,
                "args": {"name": self.process_name},
            }
        ]
        # one locked read: the span snapshot and the dropped counter
        # describe the same instant (a racy ``dropped`` read could claim
        # a wrap the exported events don't show)
        with self._lock:
            snap = list(self._spans)
            dropped = self.dropped
        for s in snap:
            events.append(
                {
                    "name": s.name,
                    "ph": "X",  # complete event
                    "pid": 1,
                    "tid": s.thread,
                    "ts": round(s.start_us, 3),
                    "dur": round(s.duration_us, 3),
                    "args": {
                        k: _sanitize_attr(v) for k, v in s.attrs.items()
                    },
                }
            )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "epochUnixUs": round(self._epoch_unix * 1e6, 1),
            "droppedSpans": dropped,
            "process": self.process_name,
        }

    def export_chrome_trace(self) -> str:
        """Catapult JSON: load at chrome://tracing or ui.perfetto.dev."""
        return json.dumps(self.export_doc())


def mint_request_id() -> str:
    """A fleet-unique request id (16 hex chars) — minted at the FIRST
    hop that sees a request (fleet router, replica server, serve_lm
    handler, or the scheduler itself) unless the client supplied one
    (``X-Request-Id`` header / ``request_id`` body field). Every span a
    request generates anywhere in the fleet carries it as the
    ``request_id`` arg; the merge below keys on it."""
    return uuid.uuid4().hex[:16]


def merge_chrome_traces(docs) -> dict[str, Any]:
    """Merge per-process catapult documents into ONE fleet timeline.

    ``docs`` is an iterable of ``(source_name, doc)`` pairs where each
    doc is a parsed ``export_doc`` result (or any catapult object-format
    dict). Each source becomes one pid (its ``process_name`` metadata
    row names it), timestamps are rebased onto the EARLIEST source's
    wall-clock epoch via ``epochUnixUs``, and events identical up to
    pid are deduplicated — several in-process replicas share one ring,
    so fetching each replica's /debug/traces returns overlapping copies.
    Request-scoped spans carry a ``request_id`` arg; filtering on it in
    ui.perfetto.dev follows one request across the fleet hop."""
    docs = [(name, doc) for name, doc in docs
            if doc and doc.get("traceEvents")]
    if not docs:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    # Only docs that CARRY an epoch participate in the base: a foreign
    # catapult doc without epochUnixUs must not drag the base to 0 and
    # shift every real source by ~the full unix epoch. Epoch-less docs
    # keep their raw timestamps (shift 0).
    known = [float(doc.get("epochUnixUs") or 0.0) for _, doc in docs]
    known = [e for e in known if e]
    base = min(known) if known else 0.0
    events: list[dict[str, Any]] = []
    seen: set[tuple] = set()
    dropped = 0
    for pid, (name, doc) in enumerate(docs, start=1):
        epoch = float(doc.get("epochUnixUs") or 0.0)
        shift_us = (epoch - base) if epoch else 0.0
        dropped += int(doc.get("droppedSpans") or 0)
        events.append({
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": str(name)},
        })
        for e in doc.get("traceEvents", ()):
            if e.get("ph") == "M":
                continue  # re-emitted per source above
            ts = round(float(e.get("ts", 0.0)) + shift_us, 3)
            key = (
                e.get("name"), ts, e.get("dur"), e.get("tid"),
                json.dumps(e.get("args", {}), sort_keys=True),
            )
            if key in seen:
                continue
            seen.add(key)
            events.append({**e, "ts": ts, "pid": pid})
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "epochUnixUs": base,
        "droppedSpans": dropped,
        "sources": [name for name, _ in docs],
    }


TRACER = Tracer()

# The serving data plane's ring: request-scoped spans (queue wait,
# admission, prefill chunks, CoW copies, decode intervals, watchdog
# restarts, drain) recorded by serve/scheduler.py + serve/engine.py and
# exported at /debug/traces on every serve HTTP surface. Process-global
# so supervisor engine rebuilds carry the ring across generations.
SERVE_TRACER = Tracer(process_name="tpu-serve")
