"""Local process executor: a single-node "kubelet" for the in-memory cluster.

Pods become real OS processes. This is what turns the framework's local mode
into a true end-to-end system (the reference needs a GKE cluster for its
tier-4 tests; here the same lifecycle semantics — env injection, restart
policies, exit-code classification, GC — are exercised against genuine
subprocesses and real HTTP on localhost).

Semantics implemented:
- pod ADDED   → allocate a rendezvous port, launch the default container's
  command as a subprocess with the pod's env (+PORT), phase → Running
- process exit → phase Succeeded (0) / Failed (≠0) with containerStatuses
  .state.terminated.exitCode, honoring pod restartPolicy Always/OnFailure
  by relaunching in place (restartCount++), Never by going terminal
- pod DELETED → SIGTERM, escalate to SIGKILL

Service "DNS": sibling pod references inside injected env values
("{pod-name}:{port}") are rewritten to 127.0.0.1:{assigned-port}, the
localhost analog of the headless-service DNS fabric (replicas.go:151-162).
The port map is exposed via ``resolve()`` so harnesses can reach a replica
the way test_runner.py reaches one through the apiserver proxy.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
from dataclasses import dataclass
from typing import Any

from tf_operator_tpu.api import constants
from tf_operator_tpu.ckpt import protocol as ckpt_protocol
from tf_operator_tpu.runtime import objects, podlogs
from tf_operator_tpu.runtime.client import (
    ADDED,
    DELETED,
    MODIFIED,
    ApiError,
    ClusterClient,
    NotFound,
)
from tf_operator_tpu.utils import exit_codes, logger


# prctl(PR_SET_PDEATHSIG, SIGTERM) is armed by a tiny exec shim INSIDE
# the child, not a preexec_fn: preexec_fn forces CPython's subprocess
# down the raw fork() path, and in a process where JAX is initialized
# (the executor runs in-process with training in several E2Es) every pod
# launch then fires JAX's at-fork RuntimeWarning — with a real deadlock
# risk behind it, since fork-children of a multithreaded parent may only
# run async-signal-safe code. The shim lets the parent use the
# posix_spawn fast path; the child arms pdeathsig and execs the real
# command. The shim window (parent dying between spawn and prctl) is the
# same race preexec_fn had.
_PDEATHSIG_SHIM = (
    "import os, sys\n"
    "try:\n"
    "    import ctypes, signal\n"
    "    ctypes.CDLL(None, use_errno=True).prctl("
    "1, signal.SIGTERM.value, 0, 0, 0)\n"
    "except Exception:\n"
    "    pass  # no prctl (non-Linux): plain exec\n"
    "try:\n"
    "    os.execvp(sys.argv[1], sys.argv[1:])\n"
    "except OSError as e:\n"
    "    print(f'spawn failed: {e}', file=sys.stderr)\n"
    "    sys.exit(127)  # the kubelet-convention 'command not found'\n"
)


def _with_pdeathsig(command: list) -> list:
    """Wrap a pod argv so the child dies with the executor even when the
    executor is SIGKILLed (a real kubelet's containers die with their
    node agent too). Best-effort: Linux-only semantics; the shim is a
    plain exec elsewhere. ``-I`` (isolated) skips site processing — the
    operator venv's sitecustomize must not boot a TPU runtime inside
    every pod child — and an unexecutable command exits 127 like the
    old parent-side spawn-failure path."""
    return [sys.executable, "-I", "-c", _PDEATHSIG_SHIM, *command]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class _Running:
    process: subprocess.Popen
    port: int
    uid: str = ""
    restart_count: int = 0
    deleted: bool = False
    # Checkpoint-coordination hook (ckpt/protocol.py): where this pod's
    # workload writes durable-save acks, the last file mtime the relay
    # lifted into pod annotations, and the eviction-signal generation
    # delivered to the process (plus the ack-file mtime at delivery — a
    # save that lands AFTER delivery is the ack the barrier waits for).
    ack_path: str = ""
    ack_mtime: float = 0.0
    delivered_gen: int = 0
    delivered_mtime: float = 0.0


class LocalProcessExecutor:
    def __init__(self, client: ClusterClient, namespace: str | None = None) -> None:
        self._client = client
        self._namespace = namespace
        self._procs: dict[str, _Running] = {}  # pod key -> process
        self._ports: dict[str, int] = {}  # pod name -> port
        # Second per-pod port for the cross-slice (DCN) rendezvous of
        # multislice jobs — in-container "{pod}:{port+DCN_PORT_OFFSET}"
        # contracts rewrite to this (see cluster_spec.gen_tpu_env).
        self._dcn_ports: dict[str, int] = {}
        self._lock = threading.RLock()
        self._log = logger.with_fields(component="local-executor")
        self._stop: threading.Event | None = None

    # -- public --------------------------------------------------------------

    def start(self, stop: threading.Event) -> None:
        self._stop = stop
        threading.Thread(target=self._run, name="local-executor", daemon=True).start()
        # Checkpoint ack relay: lifts workload ack files into pod
        # annotations (the worker→operator leg of ckpt/protocol.py).
        threading.Thread(
            target=self._poll_acks, name="local-executor-acks", daemon=True
        ).start()

    def resolve(self, pod_name: str) -> tuple[str, int] | None:
        """The harness's service-proxy analog: pod name → (host, port)."""
        with self._lock:
            port = self._ports.get(pod_name)
        return ("127.0.0.1", port) if port is not None else None

    def resolve_dcn(self, pod_name: str) -> tuple[str, int] | None:
        """The pod's cross-slice (DCN) rendezvous address — what a
        multislice contract's "{pod}:{port+DCN_PORT_OFFSET}" rewrites to."""
        with self._lock:
            port = self._dcn_ports.get(pod_name)
        return ("127.0.0.1", port) if port is not None else None

    # -- loop ----------------------------------------------------------------

    def _run(self) -> None:
        watch = self._client.watch(objects.PODS, self._namespace)
        for pod in self._client.list(objects.PODS, self._namespace):
            self._on_added(pod)
        while self._stop is not None and not self._stop.is_set():
            event = watch.next(timeout=0.2)
            if event is None:
                continue
            if event.type == ADDED:
                self._on_added(event.object)
                # A pod can arrive already carrying an eviction signal
                # (executor restart mid-barrier): deliver it on launch.
                self._maybe_signal(event.object)
            elif event.type == MODIFIED:
                # The one spec mutation that changes runnability: the gang
                # scheduler lifting the admission gate. A pod that arrived
                # gated launches on this event instead of ADDED. Pending-only:
                # every other MODIFIED is a status echo (Running/terminal
                # writes, possibly processed after the process was reaped),
                # and launching on one would re-run a finished pod.
                if objects.pod_phase(event.object) == objects.PENDING:
                    self._on_added(event.object)
                # Eviction checkpoint signal (scheduler barrier): relay it
                # to the workload as a graceful SIGTERM — the analog of
                # kubelet's preStop grace, except the pod is NOT being
                # deleted yet; the workload saves, acks, and keeps running
                # until the barrier completes.
                self._maybe_signal(event.object)
            elif event.type == DELETED:
                self._on_deleted(event.object)
        watch.stop()
        with self._lock:
            procs = list(self._procs.values())
        for running in procs:
            self._kill(running)

    # -- pod lifecycle -------------------------------------------------------

    def _port_for(self, pod_name: str) -> int:
        with self._lock:
            if pod_name not in self._ports:
                self._ports[pod_name] = _free_port()
            return self._ports[pod_name]

    def _dcn_port_for(self, pod_name: str) -> int:
        with self._lock:
            if pod_name not in self._dcn_ports:
                # The kernel can hand back the just-released main port;
                # the two services share a pod (in-slice coordinator +
                # DCN rendezvous on slice leaders) and must not collide.
                main = self._ports.get(pod_name)
                port = _free_port()
                while port == main:
                    port = _free_port()
                self._dcn_ports[pod_name] = port
            return self._dcn_ports[pod_name]

    def _ensure_job_ports(self, pod: dict[str, Any]) -> None:
        """Allocate ports for every EXPECTED replica of the owning job before
        launch, derived from the job spec (not from currently-listed pods),
        so cross-references in env rewrite consistently even when this pod
        launches before the controller created its siblings."""
        job_name = objects.labels_of(pod).get(constants.LABEL_JOB_NAME)
        if not job_name:
            return
        try:
            job = self._client.get(
                objects.TPUJOBS, objects.namespace_of(pod), job_name
            )
            from tf_operator_tpu.utils import names as names_util

            for rtype, spec in job.get("spec", {}).get("replicaSpecs", {}).items():
                replicas = int(spec.get("replicas", 1) or 1)
                multislice = int((spec.get("tpu") or {}).get("numSlices", 1) or 1) > 1
                for i in range(replicas):
                    self._port_for(names_util.gen_name(job_name, rtype, i))
                    if multislice:
                        # Multislice contracts reference a second (DCN) port
                        # per pod; allocate it up front so MEGASCALE
                        # addresses rewrite consistently across siblings.
                        self._dcn_port_for(names_util.gen_name(job_name, rtype, i))
            return
        except NotFound:
            pass
        # Fallback: whatever siblings exist right now.
        siblings = self._client.list(
            objects.PODS,
            objects.namespace_of(pod),
            {constants.LABEL_JOB_NAME: job_name},
        )
        for sib in siblings:
            self._port_for(objects.name_of(sib))

    def _rewrite(self, value: str, default_port: int) -> str:
        """Rewrite "{pod-name}:{port}" references of known pods to their
        localhost address. Bare pod names (no port) are left untouched —
        every injected contract (TF_CONFIG, TPU_WORKER_HOSTNAMES,
        coordinator address) carries explicit ports. The DCN port
        (default_port + DCN_PORT_OFFSET, multislice contracts) rewrites
        first — its literal is longer, so the main-port replace cannot
        corrupt it."""
        with self._lock:
            ports = dict(self._ports)
            dcn_ports = dict(self._dcn_ports)
        dcn_port = default_port + constants.DCN_PORT_OFFSET
        for name, port in dcn_ports.items():
            value = value.replace(f"{name}:{dcn_port}", f"127.0.0.1:{port}")
        for name, port in ports.items():
            value = value.replace(f"{name}:{default_port}", f"127.0.0.1:{port}")
        return value

    def _on_added(self, pod: dict[str, Any]) -> None:
        if pod.get("spec", {}).get("schedulingGates"):
            # Gang-gated: this kubelet must not run the pod (real kubelets
            # never see gated pods at all — the scheduler won't bind them).
            # The gate-lifting MODIFIED event re-enters here and launches.
            return
        key = objects.key_of(pod)
        uid = objects.uid_of(pod)
        with self._lock:
            existing = self._procs.get(key)
            if existing is not None:
                if existing.uid == uid:
                    return
                # Same name, new UID: the controller deleted and recreated
                # this pod (ExitCode/slice restart) before the old process
                # finished dying. Retire the old incarnation and launch the
                # new one — keying by UID is what prevents the recreated pod
                # from being wedged Pending forever.
                existing.deleted = True
            else:
                existing = None
        if existing is not None:
            self._kill(existing)
            with self._lock:
                if self._procs.get(key) is existing:
                    self._procs.pop(key)
        self._ensure_job_ports(pod)
        self._launch(pod, restart_count=0)

    def _launch(self, pod: dict[str, Any], restart_count: int) -> None:
        key = objects.key_of(pod)
        name = objects.name_of(pod)
        container = objects.get_container(pod, constants.DEFAULT_CONTAINER_NAME)
        if container is None:
            self._fail_pod(pod, 127, "no default container")
            return
        command = list(container.get("command", [])) + list(container.get("args", []))
        if not command:
            self._fail_pod(pod, 127, "no command (local executor runs commands, not images)")
            return

        port = self._port_for(name)
        default_port = constants.DEFAULT_PORT
        for p in container.get("ports", []):
            if p.get("name") == constants.DEFAULT_PORT_NAME:
                default_port = int(p.get("containerPort", default_port))

        env = dict(os.environ)
        # Children must resolve the framework package regardless of the
        # parent's cwd (pytest may run from anywhere; stderr is DEVNULL'd so
        # an import failure would be invisible). The parent's own PYTHONPATH
        # is deliberately NOT inherited: these processes stand in for
        # containers, which see only their image + injected env (reference
        # replicas.go:202-234), and harness-environment site hooks (e.g. a
        # TPU-plugin sitecustomize on the operator's path) must not boot a
        # TPU runtime inside every fake workload — with the slice env
        # injected below, that hangs the child before it can serve.
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = repo_root
        env["PORT"] = str(port)
        # Checkpoint ack contract: the workload writes durable-save acks
        # here (train/checkpoint.py does it automatically when the var is
        # set); the relay thread lifts them into pod annotations for the
        # operator's registry and the eviction barrier.
        ack_path = ckpt_protocol.ack_path_for(
            objects.namespace_of(pod), name, objects.uid_of(pod)
        )
        env[ckpt_protocol.ENV_ACK_FILE] = ack_path
        for item in container.get("env", []):
            if "value" in item:
                env[item["name"]] = self._rewrite(str(item["value"]), default_port)

        # Container output goes to the log spool (runtime/podlogs.py) so the
        # dashboard's log endpoint and post-mortem debugging can see it.
        log_file = None
        try:
            log_file = open(
                podlogs.log_path(
                    objects.namespace_of(pod), name, objects.uid_of(pod)
                ),
                "ab",
            )
        except OSError:
            pass
        try:
            proc = subprocess.Popen(
                _with_pdeathsig(command),
                env=env,
                stdout=log_file or subprocess.DEVNULL,
                stderr=subprocess.STDOUT if log_file else subprocess.DEVNULL,
            )
        except OSError as e:
            self._fail_pod(pod, 127, f"spawn failed: {e}")
            return
        finally:
            if log_file is not None:
                log_file.close()  # the child holds its own fd

        running = _Running(
            process=proc,
            port=port,
            uid=objects.uid_of(pod),
            restart_count=restart_count,
            ack_path=ack_path,
        )
        with self._lock:
            self._procs[key] = running
        # Close the relaunch/delete race: if the pod vanished (or was
        # replaced by a new incarnation) while we were spawning, kill the
        # fresh process instead of leaking an orphan.
        gone = False
        try:
            current = self._client.get(
                objects.PODS, objects.namespace_of(pod), objects.name_of(pod)
            )
            gone = objects.uid_of(current) != running.uid
        except NotFound:
            gone = True
        if gone:
            running.deleted = True
            self._kill(running)
            with self._lock:
                if self._procs.get(key) is running:
                    self._procs.pop(key)
            return
        self._set_phase(
            pod,
            objects.RUNNING,
            restart_count=restart_count,
            expect_uid=running.uid,
            port=port,
        )
        threading.Thread(
            target=self._wait, args=(pod, running), daemon=True
        ).start()

    def _wait(self, pod: dict[str, Any], running: _Running) -> None:
        code = running.process.wait()
        key = objects.key_of(pod)
        with self._lock:
            if self._procs.get(key) is running:
                self._procs.pop(key)
        if running.deleted:
            return
        policy = pod.get("spec", {}).get("restartPolicy", "Never")
        should_restart = policy == "Always" or (policy == "OnFailure" and code != 0)
        if should_restart and self._stop is not None and not self._stop.is_set():
            if code != 0 and running.restart_count:
                # CrashLoopBackOff analog: a command that fails instantly
                # (e.g. the exec shim's 127 for a bad argv) must not spin
                # the relaunch loop hot. Capped exponential, resets with
                # each new pod incarnation like the kubelet's.
                self._stop.wait(
                    min(0.5 * 2 ** min(running.restart_count, 6), 30.0)
                )
                if self._stop.is_set():
                    return
            try:  # pod may be gone or recreated (new UID) by now
                fresh = self._client.get(
                    objects.PODS, objects.namespace_of(pod), objects.name_of(pod)
                )
            except NotFound:
                return
            if objects.uid_of(fresh) != running.uid:
                return
            self._launch(pod, restart_count=running.restart_count + 1)
            return
        phase = objects.SUCCEEDED if code == 0 else objects.FAILED
        self._set_phase(
            pod,
            phase,
            exit_code=code,
            restart_count=running.restart_count,
            expect_uid=running.uid,
        )

    # -- checkpoint coordination ---------------------------------------------

    def _maybe_signal(self, pod: dict[str, Any]) -> None:
        """Deliver an eviction checkpoint signal (pod annotation stamped by
        the scheduler's barrier) to the workload process, once per
        generation: a graceful SIGTERM the workload's signal handler turns
        into a forced save + ack (utils/signals.py + train/checkpoint.py).
        The pod itself stays up until the barrier completes."""
        gen = ckpt_protocol.pod_signal_gen(pod)
        if not gen:
            return
        key = objects.key_of(pod)
        uid = objects.uid_of(pod)
        with self._lock:
            running = self._procs.get(key)
            if (
                running is None
                or running.deleted
                or (uid and running.uid != uid)
                or running.delivered_gen >= gen
            ):
                return
            running.delivered_gen = gen
            try:
                # The mtime at delivery: a later write marks a save that
                # completed AFTER the signal — the ack the barrier wants.
                running.delivered_mtime = os.path.getmtime(running.ack_path)
            except OSError:
                running.delivered_mtime = 0.0
            proc = running.process
        if proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
            except ProcessLookupError:
                pass
            self._log.info(
                "delivered checkpoint signal gen=%d to %s", gen, key
            )

    def _poll_acks(self) -> None:
        """Relay loop: workload ack files → pod annotations. Each change
        of a pod's ack file is patched once (step + saved-at + dir, plus
        the acked generation when a save landed after a delivered
        signal); the patch's MODIFIED event is what wakes the owning
        job's sync to roll the report up."""
        while self._stop is not None and not self._stop.is_set():
            with self._lock:
                procs = list(self._procs.items())
            for key, running in procs:
                if running.deleted or not running.ack_path:
                    continue
                try:
                    mtime = os.path.getmtime(running.ack_path)
                except OSError:
                    continue
                if mtime == running.ack_mtime:
                    continue
                ack = ckpt_protocol.read_ack(running.ack_path)
                if ack is None:
                    continue  # mid-write; next tick re-reads
                ann = {
                    ckpt_protocol.POD_STEP: str(ack.step),
                    ckpt_protocol.POD_SAVED_AT: ack.saved_at,
                }
                if ack.directory:
                    ann[ckpt_protocol.POD_DIR] = ack.directory
                if running.delivered_gen and mtime > running.delivered_mtime:
                    ann[ckpt_protocol.POD_ACK] = str(running.delivered_gen)
                namespace, _, name = key.partition("/")
                try:
                    self._client.patch_merge(
                        objects.PODS, namespace, name,
                        {"metadata": {"annotations": ann}},
                    )
                except NotFound:
                    pass  # pod gone: nothing left to report to
                except ApiError:
                    continue  # apiserver hiccup: keep mtime, retry
                running.ack_mtime = mtime
            self._stop.wait(0.2)

    def _on_deleted(self, pod: dict[str, Any]) -> None:
        # NOTE: the name→port mapping is deliberately kept. A controller-
        # recreated pod (ExitCode/slice restart) must come back on the SAME
        # port because sibling pods' env was rewritten to it at their launch —
        # the stable-port mapping is the localhost analog of stable service
        # DNS names (replicas.go:151-162).
        key = objects.key_of(pod)
        uid = objects.uid_of(pod)
        with self._lock:
            running = self._procs.get(key)
            # Only retire the incarnation this DELETED event refers to; a
            # recreated same-name pod (different UID) keeps running.
            if running and uid and running.uid != uid:
                running = None
            if running:
                running.deleted = True
        if running:
            self._kill(running)

    def _kill(self, running: _Running) -> None:
        proc = running.process
        if proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=2)
                except subprocess.TimeoutExpired:
                    proc.kill()
            except ProcessLookupError:
                pass

    # -- status writes -------------------------------------------------------

    def _set_phase(
        self,
        pod: dict[str, Any],
        phase: str,
        exit_code: int | None = None,
        restart_count: int = 0,
        expect_uid: str | None = None,
        port: int | None = None,
    ) -> None:
        ns, name = objects.namespace_of(pod), objects.name_of(pod)
        try:
            fresh = self._client.get(objects.PODS, ns, name)
        except NotFound:
            return
        # Never write a dead incarnation's exit status onto a recreated pod.
        if expect_uid and objects.uid_of(fresh) != expect_uid:
            return
        objects.set_pod_phase(fresh, phase)
        if port is not None:
            # Publish reachability in status — the analog of podIP + the
            # apiserver service proxy the reference harness uses to reach a
            # replica (test_runner.py:296-303).
            fresh["status"]["podIP"] = "127.0.0.1"
            fresh["status"]["hostPort"] = port
        if exit_code is not None:
            # Exit 138 = 128+SIGUSR1, the reserved "TPU health check
            # failed" self-report (utils/exit_codes.py): stamp the kubelet-
            # style reason so the pod reconciler / health monitor can
            # attribute the report without re-deriving signal arithmetic.
            reason = (
                "TPUHealthCheckFailed"
                if exit_code == exit_codes.SIGUSR1_EXIT
                else ""
            )
            objects.set_container_terminated(
                fresh, constants.DEFAULT_CONTAINER_NAME, exit_code, reason
            )
        statuses = fresh.setdefault("status", {}).setdefault("containerStatuses", [])
        for cs in statuses:
            cs["restartCount"] = restart_count
        try:
            self._client.update_status(objects.PODS, fresh)
        except Exception:
            self._log.exception("pod status update failed for %s", name)

    def _fail_pod(self, pod: dict[str, Any], code: int, reason: str) -> None:
        self._log.warning("pod %s failed to launch: %s", objects.name_of(pod), reason)
        self._set_phase(pod, objects.FAILED, exit_code=code)
