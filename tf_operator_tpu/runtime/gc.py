"""Owner-reference garbage collector.

On a real cluster the K8s GC deletes pods/services whose owning TPUJob is
gone (the reference relies on exactly that for TFJob deletion, verified by
its e2e wait-for-GC step, test/e2e/main.go:244-252). The in-memory cluster
has no built-in GC, so this component supplies the same semantics: when a
TPUJob is deleted, every object holding a controller ownerReference to its
UID is deleted too.
"""

from __future__ import annotations

import threading

from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import DELETED, ClusterClient, NotFound
from tf_operator_tpu.utils import logger

OWNED_KINDS = (objects.PODS, objects.SERVICES, objects.PDBS)


class OwnerGarbageCollector:
    def __init__(self, client: ClusterClient, namespace: str | None = None) -> None:
        self._client = client
        self._namespace = namespace
        self._log = logger.with_fields(component="owner-gc")

    def start(self, stop: threading.Event) -> None:
        threading.Thread(target=self._run, args=(stop,), daemon=True).start()

    def _run(self, stop: threading.Event) -> None:
        watch = self._client.watch(objects.TPUJOBS, self._namespace)
        while not stop.is_set():
            event = watch.next(timeout=0.2)
            if event is None:
                continue
            if event.type == DELETED:
                self.collect(event.object)
        watch.stop()

    def collect(self, owner: dict) -> int:
        uid = objects.uid_of(owner)
        if not uid:
            return 0
        deleted = 0
        for kind in OWNED_KINDS:
            for obj in self._client.list(kind, self._namespace):
                refs = objects.meta(obj).get("ownerReferences", [])
                if any(r.get("uid") == uid and r.get("controller") for r in refs):
                    try:
                        self._client.delete(
                            kind, objects.namespace_of(obj), objects.name_of(obj)
                        )
                        deleted += 1
                    except NotFound:
                        pass
        if deleted:
            self._log.info(
                "collected %d objects owned by %s", deleted, objects.name_of(owner)
            )
        return deleted
