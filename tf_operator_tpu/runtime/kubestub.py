"""Wire-compatible Kubernetes apiserver stub.

The contract-test double for KubeClusterClient (runtime/kubeclient.py): an
HTTP server that speaks the Kubernetes REST conventions — group/version
paths (/api/v1, /apis/{group}/{version}), namespaced collections, the status
subresource, merge-patch, apimachinery Status error bodies, and ndjson watch
streams — backed by the InMemoryCluster semantics (uid/resourceVersion
assignment, optimistic concurrency).

This plays the role the reference's recorded fake clientsets play in its
tier-2 tests (tfcontroller_test.go:63-64), but at the *wire* level: the same
contract suite runs against {InMemoryCluster directly, KubeClusterClient →
this stub}, proving the adapter preserves ClusterClient semantics end to
end. Pointing KubeClusterClient at a real apiserver changes only the URL and
auth.

Optional ``validators`` emulate CRD OpenAPI admission (the reference's
examples/crd/crd-v1alpha2.yaml:24-47): a validator raising
client.Invalid makes create/update return 422 with reason=Invalid.
"""

from __future__ import annotations

import base64
import json
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from tf_operator_tpu.runtime.apiserver import parse_label_selector
from tf_operator_tpu.runtime.kubeclient import _resource_for
from tf_operator_tpu.runtime.httputil import JsonHandlerMixin
from tf_operator_tpu.runtime.client import (
    AlreadyExists,
    ApiError,
    Conflict,
    Invalid,
    NotFound,
    merge_patch,
)
from tf_operator_tpu.runtime.memcluster import InMemoryCluster
from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="kubestub")

_REASON_FOR = {
    NotFound: "NotFound",
    AlreadyExists: "AlreadyExists",
    Conflict: "Conflict",
    Invalid: "Invalid",
}

Validator = Callable[[dict[str, Any]], None]


def status_body(code: int, reason: str, message: str) -> dict[str, Any]:
    """apimachinery metav1.Status failure object."""
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "metadata": {},
        "status": "Failure",
        "message": message,
        "reason": reason,
        "code": code,
    }


class _Route:
    """Parsed K8s REST path: kind (collection), namespace, name, subresource."""

    def __init__(
        self,
        kind: str,
        namespace: str | None,
        name: str | None,
        subresource: str | None,
    ) -> None:
        self.kind = kind
        self.namespace = namespace
        self.name = name
        self.subresource = subresource


def parse_k8s_path(path: str) -> _Route | None:
    parts = [p for p in path.split("/") if p]
    if not parts:
        return None
    if parts[0] == "api":
        if len(parts) < 3 or parts[1] != "v1":
            return None
        rest = parts[2:]
    elif parts[0] == "apis":
        if len(parts) < 4:
            return None
        rest = parts[3:]  # drop apis/{group}/{version}
    else:
        return None

    # namespaces/{ns}/{plural}[/{name}[/{sub}]]  — namespaced resource
    if rest[0] == "namespaces" and len(rest) >= 3:
        ns, plural = rest[1], rest[2]
        name = rest[3] if len(rest) >= 4 else None
        sub = rest[4] if len(rest) >= 5 else None
        return _Route(plural, ns, name, sub)
    # {plural}[/{name}[/{sub}]] — cluster-scoped (namespaces itself) or
    # all-namespaces list/watch
    plural = rest[0]
    name = rest[1] if len(rest) >= 2 else None
    sub = rest[2] if len(rest) >= 3 else None
    return _Route(plural, None, name, sub)


class _Handler(JsonHandlerMixin, BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: "KubeApiStub"

    # -- plumbing (shared JSON helpers live in JsonHandlerMixin) ------------

    _send_json = JsonHandlerMixin.send_json
    _read_body = JsonHandlerMixin.read_json_body
    _q = staticmethod(JsonHandlerMixin.first_query_value)

    def _send_api_error(self, e: ApiError) -> None:
        reason = _REASON_FOR.get(type(e), "InternalError")
        code = getattr(e, "code", 500)
        self._send_json(status_body(code, reason, str(e)), code)

    def _route(self) -> tuple[_Route | None, dict[str, list[str]]]:
        from urllib.parse import parse_qs, unquote, urlparse

        url = urlparse(self.path)
        route = parse_k8s_path(unquote(url.path))
        return route, parse_qs(url.query)

    def _validate(self, kind: str, obj: dict[str, Any]) -> None:
        validator = self.server.validators.get(kind)
        if validator is not None:
            validator(obj)

    def _authorized(self) -> bool:
        """When the stub requires a bearer token, reject requests without it
        (401 Unauthorized, apimachinery-style) — the seam the exec-credential
        contract tests authenticate through."""
        required = self.server.required_token
        if required is None:
            return True
        got = self.headers.get("Authorization", "")
        if got == f"Bearer {required}":
            return True
        self._send_json(
            status_body(401, "Unauthorized", "Unauthorized"), 401
        )
        return False

    # -- methods ------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802
        if not self._authorized():
            return
        route, query = self._route()
        if route is None:
            self._send_json(status_body(404, "NotFound", self.path), 404)
            return
        try:
            if route.name is None:
                if self._q(query, "watch") in ("true", "1"):
                    self._serve_watch(route, query)
                    return
                raw_sel = self._q(query, "labelSelector")
                selector = parse_label_selector(raw_sel) if raw_sel else None
                items = self.server.cluster.list(route.kind, route.namespace, selector)
                meta: dict = {"resourceVersion": self.server.cluster.current_rv}
                limit = self._q(query, "limit")
                if limit:
                    # Chunked list (limit+continue), apiserver-style: the
                    # continue token encodes the next offset. The stub serves
                    # each page from a fresh list (a real apiserver snapshots
                    # at the first page's RV; close enough for contract
                    # tests, which hold the collection still across pages).
                    n = int(limit)
                    offset = 0
                    cont = self._q(query, "continue")
                    if cont:
                        if self.server.expire_continue_tokens:
                            # etcd compacted the list snapshot: the token is
                            # no longer honorable (apiserver 410 Expired).
                            self._send_json(
                                status_body(
                                    410, "Expired",
                                    "The provided continue parameter is too "
                                    "old to display a consistent list view.",
                                ),
                                410,
                            )
                            return
                        offset = json.loads(base64.b64decode(cont))["offset"]
                    page = items[offset : offset + n]
                    if offset + n < len(items):
                        meta["continue"] = base64.b64encode(
                            json.dumps({"offset": offset + n}).encode()
                        ).decode()
                        meta["remainingItemCount"] = len(items) - offset - n
                    self.server.list_pages_served += 1
                    items = page
                self._send_json(
                    {
                        "kind": "List",
                        "apiVersion": "v1",
                        "metadata": meta,
                        "items": items,
                    }
                )
            else:
                ns = route.namespace or "default"
                if route.kind == "namespaces" and route.namespace is None:
                    # cluster-scoped: stored under a fixed pseudo-namespace
                    ns = "_cluster"
                self._send_json(self.server.cluster.get(route.kind, ns, route.name))
        except ApiError as e:
            self._send_api_error(e)

    def do_POST(self) -> None:  # noqa: N802
        if not self._authorized():
            return
        route, _ = self._route()
        if route is None or route.name is not None:
            self._send_json(status_body(404, "NotFound", self.path), 404)
            return
        try:
            obj = self._read_body()
            self._validate(route.kind, obj)
            if route.namespace is not None:
                obj.setdefault("metadata", {})["namespace"] = route.namespace
            elif route.kind == "namespaces":
                obj.setdefault("metadata", {})["namespace"] = "_cluster"
            self._send_json(self.server.cluster.create(route.kind, obj), 201)
        except ApiError as e:
            self._send_api_error(e)
        except (ValueError, json.JSONDecodeError) as e:
            self._send_json(status_body(400, "BadRequest", str(e)), 400)

    def do_PUT(self) -> None:  # noqa: N802
        if not self._authorized():
            return
        route, _ = self._route()
        if route is None or route.name is None:
            self._send_json(status_body(404, "NotFound", self.path), 404)
            return
        try:
            obj = self._read_body()
            if route.subresource == "status":
                try:
                    self._send_json(
                        self.server.cluster.update_status(route.kind, obj)
                    )
                except Invalid:
                    # Scheduling-gate enforcement (memcluster) surfacing at
                    # the wire as a 422, the way a real apiserver's
                    # admission would refuse an impossible kubelet write.
                    # Counted so gang-chaos tests can assert the gate was
                    # actually exercised over HTTP.
                    self.server.gate_422s_served += 1
                    raise
            elif route.subresource is None:
                with self.server.mutation_lock(route.kind):
                    self._validate(route.kind, obj)
                    self._send_json(self.server.cluster.update(route.kind, obj))
            else:
                self._send_json(status_body(404, "NotFound", self.path), 404)
        except ApiError as e:
            self._send_api_error(e)
        except (ValueError, json.JSONDecodeError) as e:
            self._send_json(status_body(400, "BadRequest", str(e)), 400)

    def do_PATCH(self) -> None:  # noqa: N802
        if not self._authorized():
            return
        route, _ = self._route()
        if route is None or route.name is None or route.subresource is not None:
            self._send_json(status_body(404, "NotFound", self.path), 404)
            return
        try:
            ns = route.namespace or "default"
            patch = self._read_body()
            with self.server.mutation_lock(route.kind):
                if self.server.validators.get(route.kind) is not None:
                    # Post-merge admission under the mutation lock, as the
                    # apiserver handler does (concurrent individually-valid
                    # patches must not merge into an invalid stored object);
                    # NotFound propagates as 404.
                    current = self.server.cluster.get(route.kind, ns, route.name)
                    self._validate(route.kind, merge_patch(current, patch))
                self._send_json(
                    self.server.cluster.patch_merge(route.kind, ns, route.name, patch)
                )
        except ApiError as e:
            self._send_api_error(e)
        except (ValueError, json.JSONDecodeError) as e:
            self._send_json(status_body(400, "BadRequest", str(e)), 400)

    def do_DELETE(self) -> None:  # noqa: N802
        if not self._authorized():
            return
        route, _ = self._route()
        if route is None or route.name is None:
            self._send_json(status_body(404, "NotFound", self.path), 404)
            return
        try:
            ns = route.namespace or (
                "_cluster" if route.kind == "namespaces" else "default"
            )
            self.server.cluster.delete(route.kind, ns, route.name)
            self._send_json(status_body(200, "", "deleted") | {"status": "Success"})
        except ApiError as e:
            self._send_api_error(e)

    # -- watch --------------------------------------------------------------

    def _serve_watch(self, route: _Route, query: dict[str, list[str]]) -> None:
        """ndjson watch stream (chunked). The stub streams from "now"; the
        resourceVersion param is accepted but not replayed — history replay
        is what the informer's periodic resync compensates for.

        Apiserver behaviors emulated for the reconnect contract tests:
        ``timeoutSeconds`` ends the stream after the budget (clean EOF), and
        a resume from a resourceVersion below ``expire_watch_rv_below`` gets
        the 410-Gone ERROR event a compacted etcd would produce, forcing the
        client to relist."""
        rv_param = self._q(query, "resourceVersion")
        expire_below = self.server.expire_watch_rv_below
        gone = (
            rv_param
            and expire_below is not None
            and int(rv_param) < expire_below
        )
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        write_chunk = self.write_chunk
        if gone:
            self.server.watch_410s_served += 1
            write_chunk(
                json.dumps(
                    {
                        "type": "ERROR",
                        "object": {
                            "kind": "Status",
                            "code": 410,
                            "reason": "Expired",
                            "message": f"too old resource version: {rv_param}",
                        },
                    }
                ).encode()
                + b"\n"
            )
            write_chunk(b"")  # terminating chunk: clean stream end
            return

        object_kind = _resource_for(route.kind).kind or route.kind
        deadline = None
        timeout_s = self._q(query, "timeoutSeconds")
        if timeout_s:
            deadline = time.monotonic() + float(timeout_s)
        watch = self.server.cluster.watch(route.kind, route.namespace)
        with self.server.watch_conns_lock:
            self.server.watch_conns.append(self.connection)
        bookmarks = (
            self._q(query, "allowWatchBookmarks") in ("true", "1")
            and self.server.send_bookmarks
        )
        try:
            while not self.server.stopping.is_set():
                if deadline is not None and time.monotonic() >= deadline:
                    write_chunk(b"")  # server-side budget: clean EOF
                    return
                event = watch.next(timeout=0.5)
                if event is None:
                    if bookmarks:
                        # Periodic RV checkpoint (apiserver bookmark): lets
                        # an idle client resume from a fresh RV instead of
                        # one compacted away during a long quiet stretch.
                        # The object kind is the SINGULAR resource kind, as
                        # a real apiserver sends it (Pod, not pods).
                        write_chunk(
                            json.dumps({
                                "type": "BOOKMARK",
                                "object": {
                                    "kind": object_kind,
                                    "metadata": {
                                        "resourceVersion":
                                            self.server.cluster.current_rv
                                    },
                                },
                            }).encode() + b"\n"
                        )
                    else:
                        write_chunk(b"\n")  # heartbeat
                    continue
                write_chunk(
                    json.dumps({"type": event.type, "object": event.object}).encode()
                    + b"\n"
                )
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            with self.server.watch_conns_lock:
                try:
                    self.server.watch_conns.remove(self.connection)
                except ValueError:
                    pass
            self.server.cluster.stop_watch(watch)

    def log_message(self, fmt: str, *args) -> None:
        LOG.debug(fmt, *args)


class KubeApiStub(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(
        self,
        cluster: InMemoryCluster | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        validators: dict[str, Validator] | None = None,
    ) -> None:
        super().__init__((host, port), _Handler)
        self.cluster = cluster or InMemoryCluster()
        # Default: the TPUJob admission validator, emulating the structural
        # schema a real cluster enforces once deploy/crd.yaml is applied.
        # Pass {} to run schema-less.
        if validators is None:
            from tf_operator_tpu.runtime.apiserver import default_validators

            validators = default_validators()
        self.validators = validators
        self._mutation_lock = threading.Lock()
        self.stopping = threading.Event()
        # Contract-test knobs (client-go robustness suite):
        # watch resume below this RV gets a 410 ERROR event (etcd compaction).
        self.expire_watch_rv_below: int | None = None
        # Live watch connections, so kill_watches() can sever them abruptly
        # (dead-TCP / mid-stream-drop simulation).
        self.watch_conns: list = []
        self.watch_conns_lock = threading.Lock()
        # Observability for pagination tests.
        self.list_pages_served = 0
        # When set, every request must carry "Authorization: Bearer <this>"
        # or it gets a 401 (exec-credential contract tests rotate it).
        self.required_token: str | None = None
        # When set, any list continue token gets 410 Expired (compaction).
        self.expire_continue_tokens = False
        # Emit BOOKMARK events on idle ticks for clients that request
        # allowWatchBookmarks (the kubeclient always does).
        self.send_bookmarks = False
        # 410 ERROR events served to watch resumes (bookmark tests assert
        # this stays 0: a bookmark-advanced RV never needs the relist).
        self.watch_410s_served = 0
        # Pod status writes refused with 422 because the pod still carried
        # a scheduling gate (gang admission not released yet).
        self.gate_422s_served = 0

    def kill_watches(self) -> int:
        """Abruptly sever every active watch connection (RST-style), as a
        network partition or LB idle-timeout would. Returns count killed."""
        with self.watch_conns_lock:
            conns, self.watch_conns = self.watch_conns, []
        for conn in conns:
            try:
                conn.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
                )
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        return len(conns)

    def mutation_lock(self, kind: str):
        """Serializes PUT/PATCH of validated kinds (see ApiServer.mutation_lock)."""
        if self.validators.get(kind) is not None:
            return self._mutation_lock
        import contextlib

        return contextlib.nullcontext()

    @property
    def url(self) -> str:
        return f"http://{self.server_address[0]}:{self.server_address[1]}"

    def start(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, name="kubestub", daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self.stopping.set()
        self.shutdown()
