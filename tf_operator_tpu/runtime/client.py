"""Cluster-client interface + error model.

The controller stack is written against this interface; two implementations
exist: the in-process cluster (runtime/memcluster.py — tests + local E2E,
playing the role the fake clientsets play in the reference's tier-2 tests)
and the real Kubernetes REST client (runtime/kubeclient.py). Errors mirror
apimachinery's StatusError reasons the reference branches on
(pkg/util/k8sutil error predicates).
"""

from __future__ import annotations

import abc
import queue
from dataclasses import dataclass
from typing import Any, Iterator


class ApiError(Exception):
    code = 500


class NotFound(ApiError):
    code = 404


class AlreadyExists(ApiError):
    code = 409


class Conflict(ApiError):
    """Optimistic-concurrency failure (stale resourceVersion)."""

    code = 409


class Invalid(ApiError):
    code = 422


# Watch event types (K8s watch protocol).
ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


@dataclass
class WatchEvent:
    type: str
    object: dict[str, Any]


class Watch:
    """A cancellable stream of WatchEvents."""

    def __init__(self) -> None:
        self._q: "queue.Queue[WatchEvent | None]" = queue.Queue()
        self._stopped = False

    def push(self, event: WatchEvent) -> None:
        if not self._stopped:
            self._q.put(event)

    def stop(self) -> None:
        self._stopped = True
        self._q.put(None)

    def __iter__(self) -> Iterator[WatchEvent]:
        while True:
            item = self._q.get()
            if item is None:
                return
            yield item

    def next(self, timeout: float | None = None) -> WatchEvent | None:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None


class ClusterClient(abc.ABC):
    """CRUD + watch over namespaced collections of unstructured objects."""

    @abc.abstractmethod
    def create(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]: ...

    @abc.abstractmethod
    def get(self, kind: str, namespace: str, name: str) -> dict[str, Any]: ...

    @abc.abstractmethod
    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict[str, Any]]: ...

    @abc.abstractmethod
    def update(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        """Full replace; raises Conflict when obj.metadata.resourceVersion is stale."""

    @abc.abstractmethod
    def update_status(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        """Status-subresource update: replaces only .status (+bumps RV)."""

    @abc.abstractmethod
    def patch_merge(
        self, kind: str, namespace: str, name: str, patch: dict[str, Any]
    ) -> dict[str, Any]:
        """Strategic-merge-ish patch: dicts merge recursively, other values replace."""

    @abc.abstractmethod
    def delete(self, kind: str, namespace: str, name: str) -> None: ...

    @abc.abstractmethod
    def watch(self, kind: str, namespace: str | None = None) -> Watch: ...


def merge_patch(base: dict[str, Any], patch: dict[str, Any]) -> dict[str, Any]:
    """JSON-merge-patch (RFC 7386): None deletes, dicts recurse, rest replaces."""
    out = dict(base)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        elif isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = merge_patch(out[k], v)
        else:
            out[k] = v
    return out
