"""In-process cluster: a faithful miniature API server.

Serves three roles the reference splits across machinery:
- the fake clientset + seeded informer indexers of tier-2 controller tests
  (tfcontroller_test.go:63-64, testutil/pod.go:57-92),
- the backing store for local end-to-end runs where pods are real OS
  processes (runtime/executor.py — the "kubelet"),
- a reference implementation of the semantics the real kubeclient relies on
  (uid assignment, monotonically increasing resourceVersions, optimistic
  concurrency, label-selector lists, watch streams).

Deliberately K8s-faithful details: UID changes on recreate (the reference
UID-checks its job cache, controller.go:271-290), updates conflict on stale
resourceVersion (the status-update race SURVEY.md §7 calls out), and watch
events deliver deep copies so controllers can't mutate the store in place.
"""

from __future__ import annotations

import copy
import threading
import uuid
from typing import Any

from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExists,
    ClusterClient,
    Conflict,
    Invalid,
    NotFound,
    Watch,
    WatchEvent,
    merge_patch,
)
from tf_operator_tpu.runtime.metrics import API_REQUESTS_TOTAL


def _matches(selector: dict[str, str] | None, obj: dict[str, Any]) -> bool:
    if not selector:
        return True
    labels = objects.labels_of(obj)
    return all(labels.get(k) == v for k, v in selector.items())


def _gates_of(pod: dict[str, Any]) -> list[dict[str, Any]]:
    return pod.get("spec", {}).get("schedulingGates", []) or []


def _check_scheduling_gate(current: dict[str, Any], new_status: dict[str, Any]) -> None:
    """K8s semantics for spec.schedulingGates, enforced at the store: a
    gated pod is never scheduled, so no kubelet can legally report it
    Running (or terminal-by-execution). Rejecting the write here is what
    makes gang admission crash-safe — a controller dying between "pods
    created" and "gates released" leaves pods that CANNOT run, not a
    half-started slice (the deadlock the gang scheduler exists to prevent).
    """
    if not _gates_of(current):
        return
    phase = (new_status or {}).get("phase")
    if phase in (objects.RUNNING, objects.SUCCEEDED, objects.FAILED):
        gates = ",".join(g.get("name", "?") for g in _gates_of(current))
        raise Invalid(
            f"pod {objects.key_of(current)} has scheduling gates [{gates}] "
            f"and cannot transition to {phase}"
        )


class InMemoryCluster(ClusterClient):
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._rv = 0
        # kind -> namespace -> name -> object
        self._store: dict[str, dict[str, dict[str, dict[str, Any]]]] = {}
        # (kind, namespace|None) watchers
        self._watchers: list[tuple[str, str | None, Watch]] = []
        # Status writes refused because the pod still carried a scheduling
        # gate — chaos tests assert this is busy (the fake kubelet really
        # hammered the gate) while no gated pod ever ran.
        self.gate_rejections = 0

    # -- internals -----------------------------------------------------------

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    @property
    def current_rv(self) -> str:
        """Latest resourceVersion — the list-level RV a real apiserver returns
        (used by the K8s wire stub to pin watch starts)."""
        with self._lock:
            return str(self._rv)

    def _coll(self, kind: str, namespace: str) -> dict[str, dict[str, Any]]:
        return self._store.setdefault(kind, {}).setdefault(namespace, {})

    def _broadcast(self, kind: str, etype: str, obj: dict[str, Any]) -> None:
        ns = objects.namespace_of(obj)
        for wkind, wns, watch in list(self._watchers):
            if wkind == kind and (wns is None or wns == ns):
                watch.push(WatchEvent(etype, copy.deepcopy(obj)))

    # -- ClusterClient -------------------------------------------------------

    def create(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="create", kind=kind)
        with self._lock:
            obj = copy.deepcopy(obj)
            m = objects.meta(obj)
            ns, name = m.get("namespace", "default"), m.get("name")
            if not name:
                raise ValueError("metadata.name is required")
            m.setdefault("namespace", ns)
            coll = self._coll(kind, ns)
            if name in coll:
                raise AlreadyExists(f"{kind} {ns}/{name} already exists")
            # Honor a pre-set uid (fake-clientset behavior, relied on by test
            # fixtures that pre-wire ownerReferences); generate one otherwise.
            if not m.get("uid"):
                m["uid"] = str(uuid.uuid4())
            m["resourceVersion"] = self._next_rv()
            m.setdefault("creationTimestamp", objects.now_iso())
            coll[name] = obj
            self._broadcast(kind, ADDED, obj)
            return copy.deepcopy(obj)

    def get(self, kind: str, namespace: str, name: str) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="get", kind=kind)
        with self._lock:
            try:
                return copy.deepcopy(self._store[kind][namespace][name])
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name} not found") from None

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict[str, Any]]:
        API_REQUESTS_TOTAL.inc(verb="list", kind=kind)
        with self._lock:
            out: list[dict[str, Any]] = []
            for ns, coll in self._store.get(kind, {}).items():
                if namespace is not None and ns != namespace:
                    continue
                for obj in coll.values():
                    if _matches(label_selector, obj):
                        out.append(copy.deepcopy(obj))
            out.sort(key=objects.key_of)
            return out

    def _update(self, kind: str, obj: dict[str, Any], status_only: bool) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(
            verb="update_status" if status_only else "update", kind=kind
        )
        with self._lock:
            ns, name = objects.namespace_of(obj), objects.name_of(obj)
            coll = self._coll(kind, ns)
            if name not in coll:
                raise NotFound(f"{kind} {ns}/{name} not found")
            current = coll[name]
            sent_rv = str(objects.meta(obj).get("resourceVersion", ""))
            cur_rv = str(objects.meta(current).get("resourceVersion", ""))
            if sent_rv and sent_rv != cur_rv:
                raise Conflict(
                    f"{kind} {ns}/{name}: resourceVersion {sent_rv} is stale (now {cur_rv})"
                )
            if status_only:
                if kind == objects.PODS:
                    try:
                        _check_scheduling_gate(current, obj.get("status", {}))
                    except Invalid:
                        self.gate_rejections += 1
                        raise
                updated = copy.deepcopy(current)
                updated["status"] = copy.deepcopy(obj.get("status", {}))
            else:
                updated = copy.deepcopy(obj)
                # uid/creationTimestamp are immutable.
                objects.meta(updated)["uid"] = objects.meta(current)["uid"]
                objects.meta(updated)["creationTimestamp"] = objects.meta(current).get(
                    "creationTimestamp", ""
                )
            objects.meta(updated)["resourceVersion"] = self._next_rv()
            coll[name] = updated
            self._broadcast(kind, MODIFIED, updated)
            return copy.deepcopy(updated)

    def update(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        return self._update(kind, obj, status_only=False)

    def update_status(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        return self._update(kind, obj, status_only=True)

    def patch_merge(
        self, kind: str, namespace: str, name: str, patch: dict[str, Any]
    ) -> dict[str, Any]:
        API_REQUESTS_TOTAL.inc(verb="patch", kind=kind)
        with self._lock:
            coll = self._coll(kind, namespace)
            if name not in coll:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            merged = merge_patch(coll[name], copy.deepcopy(patch))
            objects.meta(merged)["resourceVersion"] = self._next_rv()
            coll[name] = merged
            self._broadcast(kind, MODIFIED, merged)
            return copy.deepcopy(merged)

    def ungate_pods(
        self, namespace: str, names: list[str], gate: str
    ) -> list[dict[str, Any]]:
        """Remove one scheduling gate from a set of pods in a SINGLE store
        transaction: every pod flips runnable under the same lock hold, so
        no observer (kubelet, informer, chaos probe) can see a gang whose
        members straddle the gate. This is the atomic gang release the
        scheduler uses on the in-memory backend; wire backends fall back to
        per-pod patches (see scheduler/core.py release_gang).
        """
        API_REQUESTS_TOTAL.inc(verb="patch", kind=objects.PODS)
        updated: list[dict[str, Any]] = []
        with self._lock:
            coll = self._coll(objects.PODS, namespace)
            for name in names:
                pod = coll.get(name)
                if pod is None:
                    continue
                gates = _gates_of(pod)
                remaining = [g for g in gates if g.get("name") != gate]
                if len(remaining) == len(gates):
                    continue
                pod.setdefault("spec", {})["schedulingGates"] = remaining
                objects.meta(pod)["resourceVersion"] = self._next_rv()
                updated.append(copy.deepcopy(pod))
            for pod in updated:
                self._broadcast(objects.PODS, MODIFIED, pod)
        return updated

    def heartbeat_node(self, name: str, ready: bool = True) -> dict[str, Any]:
        """Kubelet-style node heartbeat: bump the Ready condition and
        lastHeartbeatTime in one store tick. The fleet-health monitor reads
        these node objects (Ready=False, or a heartbeat gone stale) as the
        NotReady signal source; the same surface exists over the wire stub
        as PUT /api/v1/nodes/{name}/status."""
        API_REQUESTS_TOTAL.inc(verb="update_status", kind=objects.NODES)
        with self._lock:
            node = self._coll(objects.NODES, "default").get(name)
            if node is None:
                raise NotFound(f"{objects.NODES} default/{name} not found")
            objects.set_node_ready(node, ready)
            objects.meta(node)["resourceVersion"] = self._next_rv()
            self._broadcast(objects.NODES, MODIFIED, node)
            return copy.deepcopy(node)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        API_REQUESTS_TOTAL.inc(verb="delete", kind=kind)
        with self._lock:
            coll = self._coll(kind, namespace)
            obj = coll.pop(name, None)
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            self._broadcast(kind, DELETED, obj)

    def watch(self, kind: str, namespace: str | None = None) -> Watch:
        API_REQUESTS_TOTAL.inc(verb="watch", kind=kind)
        with self._lock:
            w = Watch()
            self._watchers.append((kind, namespace, w))
            return w

    def stop_watch(self, watch: Watch) -> None:
        with self._lock:
            self._watchers = [(k, n, w) for (k, n, w) in self._watchers if w is not watch]
            watch.stop()
