"""In-process cluster: a faithful miniature API server.

Serves three roles the reference splits across machinery:
- the fake clientset + seeded informer indexers of tier-2 controller tests
  (tfcontroller_test.go:63-64, testutil/pod.go:57-92),
- the backing store for local end-to-end runs where pods are real OS
  processes (runtime/executor.py — the "kubelet"),
- a reference implementation of the semantics the real kubeclient relies on
  (uid assignment, monotonically increasing resourceVersions, optimistic
  concurrency, label-selector lists, watch streams).

Deliberately K8s-faithful details: UID changes on recreate (the reference
UID-checks its job cache, controller.go:271-290), updates conflict on stale
resourceVersion (the status-update race SURVEY.md §7 calls out), and watch
events deliver deep copies so controllers can't mutate the store in place.
"""

from __future__ import annotations

import copy
import threading
import uuid
from typing import Any

from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExists,
    ClusterClient,
    Conflict,
    NotFound,
    Watch,
    WatchEvent,
    merge_patch,
)


def _matches(selector: dict[str, str] | None, obj: dict[str, Any]) -> bool:
    if not selector:
        return True
    labels = objects.labels_of(obj)
    return all(labels.get(k) == v for k, v in selector.items())


class InMemoryCluster(ClusterClient):
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._rv = 0
        # kind -> namespace -> name -> object
        self._store: dict[str, dict[str, dict[str, dict[str, Any]]]] = {}
        # (kind, namespace|None) watchers
        self._watchers: list[tuple[str, str | None, Watch]] = []

    # -- internals -----------------------------------------------------------

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    @property
    def current_rv(self) -> str:
        """Latest resourceVersion — the list-level RV a real apiserver returns
        (used by the K8s wire stub to pin watch starts)."""
        with self._lock:
            return str(self._rv)

    def _coll(self, kind: str, namespace: str) -> dict[str, dict[str, Any]]:
        return self._store.setdefault(kind, {}).setdefault(namespace, {})

    def _broadcast(self, kind: str, etype: str, obj: dict[str, Any]) -> None:
        ns = objects.namespace_of(obj)
        for wkind, wns, watch in list(self._watchers):
            if wkind == kind and (wns is None or wns == ns):
                watch.push(WatchEvent(etype, copy.deepcopy(obj)))

    # -- ClusterClient -------------------------------------------------------

    def create(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        with self._lock:
            obj = copy.deepcopy(obj)
            m = objects.meta(obj)
            ns, name = m.get("namespace", "default"), m.get("name")
            if not name:
                raise ValueError("metadata.name is required")
            m.setdefault("namespace", ns)
            coll = self._coll(kind, ns)
            if name in coll:
                raise AlreadyExists(f"{kind} {ns}/{name} already exists")
            # Honor a pre-set uid (fake-clientset behavior, relied on by test
            # fixtures that pre-wire ownerReferences); generate one otherwise.
            if not m.get("uid"):
                m["uid"] = str(uuid.uuid4())
            m["resourceVersion"] = self._next_rv()
            m.setdefault("creationTimestamp", objects.now_iso())
            coll[name] = obj
            self._broadcast(kind, ADDED, obj)
            return copy.deepcopy(obj)

    def get(self, kind: str, namespace: str, name: str) -> dict[str, Any]:
        with self._lock:
            try:
                return copy.deepcopy(self._store[kind][namespace][name])
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name} not found") from None

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict[str, Any]]:
        with self._lock:
            out: list[dict[str, Any]] = []
            for ns, coll in self._store.get(kind, {}).items():
                if namespace is not None and ns != namespace:
                    continue
                for obj in coll.values():
                    if _matches(label_selector, obj):
                        out.append(copy.deepcopy(obj))
            out.sort(key=objects.key_of)
            return out

    def _update(self, kind: str, obj: dict[str, Any], status_only: bool) -> dict[str, Any]:
        with self._lock:
            ns, name = objects.namespace_of(obj), objects.name_of(obj)
            coll = self._coll(kind, ns)
            if name not in coll:
                raise NotFound(f"{kind} {ns}/{name} not found")
            current = coll[name]
            sent_rv = str(objects.meta(obj).get("resourceVersion", ""))
            cur_rv = str(objects.meta(current).get("resourceVersion", ""))
            if sent_rv and sent_rv != cur_rv:
                raise Conflict(
                    f"{kind} {ns}/{name}: resourceVersion {sent_rv} is stale (now {cur_rv})"
                )
            if status_only:
                updated = copy.deepcopy(current)
                updated["status"] = copy.deepcopy(obj.get("status", {}))
            else:
                updated = copy.deepcopy(obj)
                # uid/creationTimestamp are immutable.
                objects.meta(updated)["uid"] = objects.meta(current)["uid"]
                objects.meta(updated)["creationTimestamp"] = objects.meta(current).get(
                    "creationTimestamp", ""
                )
            objects.meta(updated)["resourceVersion"] = self._next_rv()
            coll[name] = updated
            self._broadcast(kind, MODIFIED, updated)
            return copy.deepcopy(updated)

    def update(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        return self._update(kind, obj, status_only=False)

    def update_status(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        return self._update(kind, obj, status_only=True)

    def patch_merge(
        self, kind: str, namespace: str, name: str, patch: dict[str, Any]
    ) -> dict[str, Any]:
        with self._lock:
            coll = self._coll(kind, namespace)
            if name not in coll:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            merged = merge_patch(coll[name], copy.deepcopy(patch))
            objects.meta(merged)["resourceVersion"] = self._next_rv()
            coll[name] = merged
            self._broadcast(kind, MODIFIED, merged)
            return copy.deepcopy(merged)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            coll = self._coll(kind, namespace)
            obj = coll.pop(name, None)
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            self._broadcast(kind, DELETED, obj)

    def watch(self, kind: str, namespace: str | None = None) -> Watch:
        with self._lock:
            w = Watch()
            self._watchers.append((kind, namespace, w))
            return w

    def stop_watch(self, watch: Watch) -> None:
        with self._lock:
            self._watchers = [(k, n, w) for (k, n, w) in self._watchers if w is not watch]
            watch.stop()
