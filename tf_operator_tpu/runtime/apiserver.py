"""HTTP API server: exposes a ClusterClient over REST.

The process boundary of the framework (the role the K8s apiserver plays in
every call stack of SURVEY.md §3): the operator CLI runs this in front of
its backing store so remote clients — the dashboard frontend, the Python
TPUJobClient via runtime/restclient.py, genjob, the E2E harness — speak one
wire protocol. Shapes follow K8s REST conventions:

  GET    /api/{kind}                         list (all namespaces)
  GET    /api/{kind}?namespace=ns&labelSelector=k%3Dv,...   filtered list
  GET    /api/{kind}?watch=1[&namespace=ns]  watch (streamed JSON lines)
  POST   /api/{kind}                         create
  GET    /api/{kind}/{ns}/{name}             get
  PUT    /api/{kind}/{ns}/{name}             update (resourceVersion CAS)
  PUT    /api/{kind}/{ns}/{name}/status      status-subresource update
  PATCH  /api/{kind}/{ns}/{name}             JSON merge patch
  DELETE /api/{kind}/{ns}/{name}             delete

Errors map to the ApiError hierarchy: 404 NotFound, 409 AlreadyExists/
Conflict, 422 Invalid — the same codes a real apiserver returns, so
restclient raises the identical exceptions either way.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, unquote, urlparse

from tf_operator_tpu.runtime.client import (
    ApiError,
    ClusterClient,
    Invalid,
    merge_patch,
)
from tf_operator_tpu.runtime.httputil import JsonHandlerMixin
from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="apiserver")

Validator = Callable[[dict[str, Any]], None]


def default_validators() -> dict[str, Validator]:
    """Per-kind admission validators — the server-side schema enforcement the
    reference gets from CRD OpenAPI validation (crd-v1alpha2.yaml:24-47).
    Raise client.Invalid so the wire response is 422."""
    from tf_operator_tpu.api.admission import validate_tpujob_object
    from tf_operator_tpu.api.validation import ValidationError
    from tf_operator_tpu.runtime import objects

    def _validate_tpujob(obj: dict[str, Any]) -> None:
        try:
            validate_tpujob_object(obj)
        except ValidationError as e:
            raise Invalid(str(e)) from e

    return {objects.TPUJOBS: _validate_tpujob}


def parse_label_selector(raw: str) -> dict[str, str]:
    """Parse "k=v,k2=v2" (the equality subset the framework uses)."""
    out: dict[str, str] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad label selector term: {part!r}")
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip()
    return out


class _Handler(JsonHandlerMixin, BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: "ApiServer"

    # -- plumbing (shared JSON helpers live in JsonHandlerMixin) ------------

    _send_json = JsonHandlerMixin.send_json
    _read_body = JsonHandlerMixin.read_json_body
    _q = staticmethod(JsonHandlerMixin.first_query_value)

    def _send_error_obj(self, e: Exception) -> None:
        code = getattr(e, "code", 500)
        self._send_json({"error": type(e).__name__, "message": str(e)}, code=code)

    def _write_authorized(self) -> bool:
        """Bearer-token gate on every mutating method (API and dashboard
        routes alike). Reads stay open — the exposure that matters is an
        unauthenticated caller creating jobs that the operator materializes
        into pods with its own privileges."""
        token = self.server.write_token
        if not token:
            return True
        import hmac

        got = self.headers.get("Authorization", "")
        # bytes compare: str compare_digest raises TypeError on non-ASCII
        # input, which would turn a bad header into a 500 instead of a 401.
        if hmac.compare_digest(got.encode(), f"Bearer {token}".encode()):
            return True
        self._send_json(
            {"error": "Unauthorized",
             "message": "mutating requests require the bearer token"},
            401,
        )
        return False

    def _route(self) -> tuple[str | None, list[str], dict[str, list[str]]]:
        url = urlparse(self.path)
        parts = [unquote(p) for p in url.path.strip("/").split("/") if p]
        query = parse_qs(url.query)
        if not parts or parts[0] != "api":
            return None, [], query
        return "api", parts[1:], query

    # -- methods ------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802
        root, parts, query = self._route()
        if root is None:
            handled = self.server.handle_extra(self)
            if not handled:
                self._send_json({"error": "NotFound", "message": self.path}, 404)
            return
        try:
            if len(parts) == 1:
                kind = parts[0]
                if self._q(query, "watch"):
                    self._serve_watch(kind, self._q(query, "namespace"))
                    return
                selector = None
                raw_sel = self._q(query, "labelSelector")
                if raw_sel:
                    selector = parse_label_selector(raw_sel)
                items = self.server.backend.list(
                    kind, self._q(query, "namespace"), selector
                )
                self._send_json({"items": items})
            elif len(parts) == 3:
                self._send_json(self.server.backend.get(parts[0], parts[1], parts[2]))
            else:
                self._send_json({"error": "NotFound", "message": self.path}, 404)
        except ApiError as e:
            self._send_error_obj(e)
        except ValueError as e:
            self._send_json({"error": "BadRequest", "message": str(e)}, 400)

    def do_POST(self) -> None:  # noqa: N802
        if not self._write_authorized():
            return
        root, parts, _ = self._route()
        if root is None:
            if not self.server.handle_extra(self):
                self._send_json({"error": "NotFound", "message": self.path}, 404)
            return
        if len(parts) != 1:
            self._send_json({"error": "NotFound", "message": self.path}, 404)
            return
        try:
            body = self._read_body()
            self.server.validate(parts[0], body)
            self._send_json(self.server.backend.create(parts[0], body), 201)
        except ApiError as e:
            self._send_error_obj(e)
        except (ValueError, json.JSONDecodeError) as e:
            self._send_json({"error": "BadRequest", "message": str(e)}, 400)

    def do_PUT(self) -> None:  # noqa: N802
        if not self._write_authorized():
            return
        root, parts, _ = self._route()
        try:
            if root is not None and len(parts) == 3:
                body = self._read_body()
                with self.server.mutation_lock(parts[0]):
                    self.server.validate(parts[0], body)
                    self._send_json(self.server.backend.update(parts[0], body))
            elif root is not None and len(parts) == 4 and parts[3] == "status":
                self._send_json(
                    self.server.backend.update_status(parts[0], self._read_body())
                )
            else:
                self._send_json({"error": "NotFound", "message": self.path}, 404)
        except ApiError as e:
            self._send_error_obj(e)
        except (ValueError, json.JSONDecodeError) as e:
            self._send_json({"error": "BadRequest", "message": str(e)}, 400)

    def do_PATCH(self) -> None:  # noqa: N802
        if not self._write_authorized():
            return
        root, parts, _ = self._route()
        if root is None or len(parts) != 3:
            self._send_json({"error": "NotFound", "message": self.path}, 404)
            return
        try:
            kind, ns, name = parts[0], parts[1], parts[2]
            patch = self._read_body()
            with self.server.mutation_lock(kind):
                if self.server.validators.get(kind) is not None:
                    # Validate the post-merge result, as CRD admission does
                    # for patches. Read-merge-validate-write runs under the
                    # per-kind mutation lock: two concurrent, individually-
                    # valid patches must not interleave into an invalid
                    # stored object. NotFound propagates (missing object
                    # stays a 404).
                    current = self.server.backend.get(kind, ns, name)
                    self.server.validate(kind, merge_patch(current, patch))
                self._send_json(
                    self.server.backend.patch_merge(kind, ns, name, patch)
                )
        except ApiError as e:
            self._send_error_obj(e)
        except (ValueError, json.JSONDecodeError) as e:
            self._send_json({"error": "BadRequest", "message": str(e)}, 400)

    def do_DELETE(self) -> None:  # noqa: N802
        if not self._write_authorized():
            return
        root, parts, _ = self._route()
        if root is None:
            if not self.server.handle_extra(self):
                self._send_json({"error": "NotFound", "message": self.path}, 404)
            return
        if len(parts) != 3:
            self._send_json({"error": "NotFound", "message": self.path}, 404)
            return
        try:
            self.server.backend.delete(parts[0], parts[1], parts[2])
            self._send_json({"status": "Success"})
        except ApiError as e:
            self._send_error_obj(e)

    # -- watch streaming ----------------------------------------------------

    def _serve_watch(self, kind: str, namespace: str | None) -> None:
        """Stream watch events as newline-delimited JSON (chunked)."""
        watch = self.server.backend.watch(kind, namespace)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        write_chunk = self.write_chunk

        try:
            while not self.server.stopping.is_set():
                event = watch.next(timeout=1.0)
                if event is None:
                    write_chunk(b"\n")  # heartbeat keeps dead clients detectable
                    continue
                line = json.dumps({"type": event.type, "object": event.object})
                write_chunk(line.encode() + b"\n")
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            try:
                self.server.backend.stop_watch(watch)  # type: ignore[attr-defined]
            except Exception:
                pass

    def log_message(self, fmt: str, *args) -> None:  # route through our logger
        LOG.debug(fmt, *args)


class ApiServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(
        self,
        backend: ClusterClient,
        host: str = "127.0.0.1",
        port: int = 0,
        validators: dict[str, Validator] | None = None,
        write_token: str | None = None,
    ):
        super().__init__((host, port), _Handler)
        self.backend = backend
        # When set, every mutating request (any route) must carry
        # "Authorization: Bearer <token>"; reads stay open.
        self.write_token = write_token
        self.stopping = threading.Event()
        # Admission validation at the API boundary (422 Invalid before the
        # store is touched). Pass {} to disable.
        self.validators = default_validators() if validators is None else validators
        # Serializes spec mutations of validated kinds so PATCH's
        # read-merge-validate-write is atomic w.r.t. concurrent PUT/PATCH
        # (ThreadingHTTPServer handles requests concurrently).
        self._mutation_lock = threading.Lock()
        # Additional handlers (the dashboard mounts itself here).
        self._extra_handlers: list[Any] = []

    def validate(self, kind: str, obj: dict[str, Any]) -> None:
        validator = self.validators.get(kind)
        if validator is not None:
            validator(obj)

    def mutation_lock(self, kind: str):
        """The write-serialization lock for validated kinds; a no-op context
        for kinds with no validator (their writes need no merge admission)."""
        if self.validators.get(kind) is not None:
            return self._mutation_lock
        import contextlib

        return contextlib.nullcontext()

    @property
    def port(self) -> int:
        return self.server_address[1]

    def add_handler(self, handler: Any) -> None:
        """handler(request) -> bool; first one returning True wins. Used by
        the dashboard to mount /tpujobs/api/* and the static frontend."""
        self._extra_handlers.append(handler)

    def handle_extra(self, request: BaseHTTPRequestHandler) -> bool:
        for h in self._extra_handlers:
            if h(request):
                return True
        return False

    def start(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, name="apiserver", daemon=True)
        t.start()
        LOG.info("serving on %s:%d", *self.server_address)
        return t

    def stop(self) -> None:
        self.stopping.set()
        self.shutdown()
