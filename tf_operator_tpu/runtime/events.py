"""Event recorder: the audit-trail-as-API surface.

Parity: the record.EventRecorder wired into the reference controller
(tfcontroller.go:118-121) and the create/delete events emitted by
pod_control.go:138-147 / service_control.go:99-115. The E2E harness consumes
these events as observability data (test_runner.py:217-281), so the recorder
is a first-class part of the contract, not just logging.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any

from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ClusterClient

NORMAL = "Normal"
WARNING = "Warning"

# Canonical reasons (reference: SuccessfulCreatePodReason etc.)
SUCCESSFUL_CREATE_POD = "SuccessfulCreatePod"
FAILED_CREATE_POD = "FailedCreatePod"
SUCCESSFUL_DELETE_POD = "SuccessfulDeletePod"
FAILED_DELETE_POD = "FailedDeletePod"
SUCCESSFUL_CREATE_SERVICE = "SuccessfulCreateService"
FAILED_CREATE_SERVICE = "FailedCreateService"
SUCCESSFUL_DELETE_SERVICE = "SuccessfulDeleteService"
FAILED_DELETE_SERVICE = "FailedDeleteService"
FAILED_VALIDATION = "FailedValidation"


class EventRecorder:
    """Writes core/v1-style Event objects into the cluster."""

    _seq = itertools.count()

    def __init__(self, client: ClusterClient, component: str = "tpu-job-operator") -> None:
        self._client = client
        self._component = component
        self._lock = threading.Lock()

    def event(
        self,
        involved: dict[str, Any],
        event_type: str,
        reason: str,
        message: str,
    ) -> None:
        with self._lock:
            n = next(self._seq)
        name = f"{objects.name_of(involved)}.{n:x}"
        ev = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": name,
                "namespace": objects.namespace_of(involved) or "default",
            },
            "involvedObject": {
                "kind": involved.get("kind", ""),
                "namespace": objects.namespace_of(involved),
                "name": objects.name_of(involved),
                "uid": objects.uid_of(involved),
            },
            "reason": reason,
            "message": message,
            "type": event_type,
            "source": {"component": self._component},
            "firstTimestamp": objects.now_iso(),
            "lastTimestamp": objects.now_iso(),
            "count": 1,
        }
        try:
            self._client.create(objects.EVENTS, ev)
        except Exception:
            # Event emission must never break reconciliation.
            pass

    def normal(self, involved: dict[str, Any], reason: str, message: str) -> None:
        self.event(involved, NORMAL, reason, message)

    def warning(self, involved: dict[str, Any], reason: str, message: str) -> None:
        self.event(involved, WARNING, reason, message)


class FakeRecorder(EventRecorder):
    """record.FakeRecorder analog: captures events in memory for assertions."""

    def __init__(self) -> None:  # no client needed
        self.events: list[tuple[str, str, str, str]] = []  # (obj, type, reason, msg)
        self._lock = threading.Lock()

    def event(
        self, involved: dict[str, Any], event_type: str, reason: str, message: str
    ) -> None:
        with self._lock:
            self.events.append(
                (objects.key_of(involved), event_type, reason, message)
            )
