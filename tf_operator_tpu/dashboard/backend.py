"""Dashboard REST backend.

Parity: dashboard/backend/handler/api_handler.go:75-199 + main.go:23-27 —
list/get/create/delete TPUJobs, job detail with its pods (same label
selector the reference uses, api_handler.go:162-164), pod logs, namespace
list, namespace auto-create on deploy; plus the static frontend. Mounts
onto runtime/apiserver.py's extra-handler hook instead of running its own
listener, so one port serves both the raw resource API and the dashboard.

Routes (all under /tpujobs/api, mirroring the reference's URL space):
  GET    /tpujobs/api/tpujob                     all jobs
  GET    /tpujobs/api/tpujob/{ns}                jobs in namespace
  GET    /tpujobs/api/tpujob/{ns}/{name}         job detail (+pods,+events)
  POST   /tpujobs/api/tpujob                     deploy (creates ns if absent)
  DELETE /tpujobs/api/tpujob/{ns}/{name}         delete
  GET    /tpujobs/api/pod/{ns}/{name}/logs       container logs
  GET    /tpujobs/api/namespace                  namespaces
  GET    /                                       frontend (static files)
"""

from __future__ import annotations

import json
import os
from typing import Any
from urllib.parse import unquote, urlparse

from tf_operator_tpu.api import admission, helpers
from tf_operator_tpu.api.validation import ValidationError
from tf_operator_tpu.runtime import objects, podlogs
from tf_operator_tpu.runtime.client import AlreadyExists, ApiError, ClusterClient, Invalid
from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="dashboard")

FRONTEND_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "frontend")
_CONTENT_TYPES = {
    ".html": "text/html; charset=utf-8",
    ".js": "application/javascript",
    ".css": "text/css",
    ".svg": "image/svg+xml",
    ".ico": "image/x-icon",
}


class DashboardBackend:
    def __init__(self, client: ClusterClient, frontend_dir: str = FRONTEND_DIR):
        self._client = client
        self._frontend = frontend_dir

    # -- helpers ------------------------------------------------------------

    @staticmethod
    def _send_json(req: Any, payload: Any, code: int = 200) -> None:
        body = json.dumps(payload).encode()
        req.send_response(code)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    def _job_detail(self, ns: str, name: str) -> dict[str, Any]:
        job = self._client.get(objects.TPUJOBS, ns, name)
        selector = helpers.gen_labels(name)
        pods = self._client.list(objects.PODS, ns, label_selector=selector)
        services = self._client.list(objects.SERVICES, ns, label_selector=selector)
        events = [
            e
            for e in self._client.list(objects.EVENTS, ns)
            if e.get("involvedObject", {}).get("name", "").startswith(name)
        ]
        return {"tpujob": job, "pods": pods, "services": services, "events": events}

    def _ensure_namespace(self, ns: str) -> None:
        """api_handler.go:189-199: create the namespace when deploying into
        one that doesn't exist yet."""
        try:
            self._client.create(
                objects.NAMESPACES,
                {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": ns, "namespace": ns}},
            )
        except AlreadyExists:
            pass

    # -- request handling ----------------------------------------------------

    def __call__(self, req: Any) -> bool:
        """apiserver extra-handler: returns True when the request was ours."""
        url = urlparse(req.path)
        parts = [unquote(p) for p in url.path.strip("/").split("/") if p]
        try:
            if parts[:2] == ["tpujobs", "api"]:
                return self._handle_api(req, parts[2:])
            if req.command == "GET":
                return self._handle_static(req, parts)
        except ApiError as e:
            self._send_json(
                req, {"error": type(e).__name__, "message": str(e)}, getattr(e, "code", 500)
            )
            return True
        except (ValueError, json.JSONDecodeError) as e:
            self._send_json(req, {"error": "BadRequest", "message": str(e)}, 400)
            return True
        return False

    def _handle_api(self, req: Any, parts: list[str]) -> bool:
        method = req.command
        if not parts:
            return False
        head, rest = parts[0], parts[1:]

        if head == "tpujob":
            if method == "GET":
                if len(rest) == 0:
                    self._send_json(req, {"items": self._client.list(objects.TPUJOBS)})
                elif len(rest) == 1:
                    self._send_json(
                        req, {"items": self._client.list(objects.TPUJOBS, rest[0])}
                    )
                elif len(rest) == 2:
                    self._send_json(req, self._job_detail(rest[0], rest[1]))
                else:
                    return False
                return True
            if method == "POST" and len(rest) == 0:
                length = int(req.headers.get("Content-Length", 0))
                body = json.loads(req.rfile.read(length)) if length else {}
                # Admission at the deploy boundary: the UI gets the 422 +
                # message instead of a silently-stored, controller-rejected
                # job (the dashboard talks straight to the store, so the
                # apiserver's validators don't cover this path).
                try:
                    admission.validate_tpujob_object(body)
                except ValidationError as e:
                    raise Invalid(str(e)) from e
                ns = body.get("metadata", {}).get("namespace", "default")
                self._ensure_namespace(ns)
                created = self._client.create(objects.TPUJOBS, body)
                self._send_json(req, created, 201)
                return True
            if method == "DELETE" and len(rest) == 2:
                self._client.delete(objects.TPUJOBS, rest[0], rest[1])
                self._send_json(req, {"status": "Success"})
                return True
            return False

        if head == "pod" and method == "GET" and len(rest) == 3 and rest[2] == "logs":
            from urllib.parse import parse_qs

            query = parse_qs(urlparse(req.path).query)
            if "offset" in query:
                # Streaming contract (tpuctl logs -f): absolute offset +
                # spool id -> the appended chunk since then; byte-exact
                # across the 1 MiB tail cap and across pod incarnations.
                try:
                    offset = int(query.get("offset", ["0"])[0])
                except ValueError:
                    offset = 0
                spool = query.get("spool", [""])[0]
                got = podlogs.read_log_stream(rest[0], rest[1], offset, spool)
                if got is None:
                    self._send_json(
                        req, {"error": "NotFound",
                              "message": "no logs spooled"}, 404
                    )
                else:
                    chunk, next_offset, spool_id = got
                    self._send_json(req, {
                        "logs": chunk, "offset": next_offset,
                        "spool": spool_id,
                    })
                return True
            text = podlogs.read_log(rest[0], rest[1])
            if text is None:
                self._send_json(
                    req, {"error": "NotFound", "message": "no logs spooled"}, 404
                )
            else:
                self._send_json(req, {"logs": text})
            return True

        if head == "accelerators" and method == "GET":
            # The slice-picker catalog: offerable accelerator shapes with
            # default topology + host counts (topology/slices.catalog).
            from tf_operator_tpu.topology import slices as topo_slices

            self._send_json(req, {"items": topo_slices.catalog()})
            return True

        if head == "namespace" and method == "GET":
            names = sorted(
                {objects.name_of(n) for n in self._client.list(objects.NAMESPACES)}
                | {
                    objects.namespace_of(j)
                    for j in self._client.list(objects.TPUJOBS)
                }
                | {"default"}
            )
            self._send_json(req, {"items": names})
            return True

        return False

    def _handle_static(self, req: Any, parts: list[str]) -> bool:
        rel = "/".join(parts) or "index.html"
        path = os.path.normpath(os.path.join(self._frontend, rel))
        if not path.startswith(os.path.abspath(self._frontend)):
            return False
        if not os.path.isfile(path):
            # SPA fallback: unknown non-API paths render the app shell.
            path = os.path.join(self._frontend, "index.html")
            if not os.path.isfile(path):
                return False
        ext = os.path.splitext(path)[1]
        with open(path, "rb") as f:
            body = f.read()
        req.send_response(200)
        req.send_header("Content-Type", _CONTENT_TYPES.get(ext, "application/octet-stream"))
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)
        return True


def mount_dashboard(api_server: Any, client: ClusterClient) -> DashboardBackend:
    backend = DashboardBackend(client)
    api_server.add_handler(backend)
    LOG.info("dashboard mounted at / and /tpujobs/api")
    return backend
