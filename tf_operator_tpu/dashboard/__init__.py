"""REST dashboard: backend API + static SPA frontend (reference §2.6)."""

from tf_operator_tpu.dashboard.backend import DashboardBackend, mount_dashboard

__all__ = ["DashboardBackend", "mount_dashboard"]
