/* TPU Job Operator dashboard SPA.
 *
 * Hash-routed views over /tpujobs/api (the reference's services.js REST
 * surface): #/ job list, #/job/{ns}/{name} detail with pods + events +
 * volumes + log viewer, #/create deploy form, #/clone/{ns}/{name}
 * deep-linkable clone/resubmit (create form prefilled from the existing
 * job's spec). Polls the list/detail every 3 s.
 */
"use strict";

const app = document.getElementById("app");
const nsSelect = document.getElementById("ns-select");
let pollTimer = null;

// ---------- api ----------
async function api(path, opts) {
  // Optional write auth (operator --serve-token-file): stash the token with
  // localStorage.setItem("tpuOperatorToken", "<token>") in the console.
  const token = localStorage.getItem("tpuOperatorToken");
  if (token) {
    opts = opts || {};
    opts.headers = { ...(opts.headers || {}), Authorization: "Bearer " + token };
  }
  const resp = await fetch("/tpujobs/api" + path, opts);
  const body = await resp.json().catch(() => ({}));
  if (!resp.ok) throw new Error(body.message || resp.statusText);
  return body;
}

// ---------- helpers ----------
function h(tag, attrs, ...children) {
  const el = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs || {})) {
    if (k === "onclick") el.addEventListener("click", v);
    else if (k === "class") el.className = v;
    else el.setAttribute(k, v);
  }
  for (const c of children.flat()) {
    if (c == null) continue;
    el.append(c.nodeType ? c : document.createTextNode(String(c)));
  }
  return el;
}

function activeConditions(job) {
  return (job.status?.conditions || []).filter((c) => c.status === "True");
}

function phaseBadge(job) {
  const conds = activeConditions(job).map((c) => c.type);
  const order = ["Failed", "Succeeded", "Restarting", "Running", "Created"];
  const top = order.find((t) => conds.includes(t)) || "Created";
  return h("span", { class: "badge " + top }, top);
}

function replicaSummary(job) {
  const rs = job.status?.replicaStatuses || {};
  return Object.entries(rs)
    .map(([t, s]) => `${t} ${s.active || 0}/${s.succeeded || 0}/${s.failed || 0}`)
    .join(" · ");
}

function setPoll(fn) {
  if (pollTimer) clearInterval(pollTimer);
  pollTimer = setInterval(fn, 3000);
}

// ---------- views ----------
async function jobListView() {
  const ns = nsSelect.value;
  const data = await api(ns && ns !== "*" ? `/tpujob/${ns}` : "/tpujob");
  const rows = (data.items || []).map((job) => {
    const m = job.metadata;
    return h(
      "tr",
      {
        class: "clickable",
        onclick: () => (location.hash = `#/job/${m.namespace}/${m.name}`),
      },
      h("td", {}, m.namespace),
      h("td", {}, m.name),
      h("td", {}, phaseBadge(job)),
      h("td", {}, replicaSummary(job) || "—"),
      h("td", { class: "muted" }, m.creationTimestamp || "")
    );
  });
  app.replaceChildren(
    h("div", { class: "toolbar" }, h("h2", {}, "TPUJobs"), ""),
    h(
      "table",
      {},
      h(
        "thead",
        {},
        h("tr", {}, ...["Namespace", "Name", "State", "Active/Done/Failed", "Created"].map((t) => h("th", {}, t)))
      ),
      h("tbody", {}, rows.length ? rows : h("tr", {}, h("td", { class: "muted", colspan: 5 }, "No jobs")))
    )
  );
}

async function jobDetailView(ns, name) {
  const d = await api(`/tpujob/${ns}/${name}`);
  const job = d.tpujob;
  const conds = (job.status?.conditions || []).map((c) =>
    h(
      "tr",
      {},
      h("td", {}, c.type),
      h("td", {}, c.status),
      h("td", {}, c.reason || ""),
      h("td", { class: "muted" }, c.message || ""),
      h("td", { class: "muted" }, c.lastTransitionTime || "")
    )
  );
  const pods = (d.pods || []).map((p) =>
    h(
      "tr",
      {},
      h("td", {}, p.metadata.name),
      h("td", {}, h("span", { class: "badge " + (p.status?.phase || "") }, p.status?.phase || "?")),
      h("td", {}, (p.status?.containerStatuses || []).map((cs) => `restarts:${cs.restartCount ?? 0}`).join(" ")),
      h(
        "td",
        {},
        h("button", { class: "ghost", onclick: () => showLogs(ns, p.metadata.name) }, "logs")
      )
    )
  );
  const events = (d.events || []).slice(-20).map((e) =>
    h(
      "tr",
      {},
      h("td", {}, e.type || ""),
      h("td", {}, e.reason || ""),
      h("td", { class: "muted" }, e.message || ""),
      h("td", { class: "muted" }, e.involvedObject?.name || "")
    )
  );
  const replicas = Object.entries(job.status?.replicaStatuses || {}).map(
    ([type, s]) =>
      h(
        "tr",
        {},
        h("td", {}, type),
        h("td", {}, s.active || 0),
        h("td", {}, s.succeeded || 0),
        h("td", {}, s.failed || 0)
      )
  );
  const restarts = job.status?.restartCount
    ? h("span", { class: "muted" }, ` restarts: ${job.status.restartCount}`)
    : null;
  // Volumes across replica roles (parity: the reference detail view lists
  // volume mounts): one row per (role, volume) with its container mounts.
  const volRows = Object.entries(job.spec?.replicaSpecs || {}).flatMap(
    ([role, rs]) => {
      const tspec = rs.template?.spec || {};
      const mountsByVol = {};
      for (const c of tspec.containers || []) {
        for (const vm of c.volumeMounts || []) {
          (mountsByVol[vm.name] = mountsByVol[vm.name] || []).push(
            `${c.name}:${vm.mountPath}`
          );
        }
      }
      return (tspec.volumes || []).map((v) =>
        h(
          "tr",
          {},
          h("td", {}, role),
          h("td", {}, v.name),
          h("td", { class: "muted" }, v.hostPath?.path || JSON.stringify({ ...v, name: undefined })),
          h("td", { class: "muted" }, (mountsByVol[v.name] || []).join(" "))
        )
      );
    }
  );
  app.replaceChildren(
    h(
      "div",
      { class: "toolbar" },
      h("h2", {}, `${ns}/${name} `, phaseBadge(job), restarts),
      h(
        "span",
        {},
        h(
          "button",
          {
            class: "ghost",
            onclick: () => (location.hash = `#/clone/${ns}/${name}`),
          },
          "Clone"
        ),
        " ",
        h(
          "button",
          {
            class: "danger",
            onclick: async () => {
              if (confirm(`Delete TPUJob ${ns}/${name}?`)) {
                await api(`/tpujob/${ns}/${name}`, { method: "DELETE" });
                location.hash = "#/";
              }
            },
          },
          "Delete"
        )
      )
    ),
    h(
      "div",
      { class: "row" },
      h(
        "div",
        { class: "card" },
        h("h2", {}, "Conditions"),
        h("table", {}, h("tbody", {}, conds.length ? conds : h("tr", {}, h("td", { class: "muted" }, "none")))),
        h("h2", {}, "Replica sets"),
        h(
          "table",
          {},
          h("thead", {}, h("tr", {}, ...["Role", "Active", "Succeeded", "Failed"].map((t) => h("th", {}, t)))),
          h("tbody", {}, replicas.length ? replicas : h("tr", {}, h("td", { class: "muted", colspan: 4 }, "none")))
        )
      ),
      h(
        "div",
        { class: "card" },
        h("h2", {}, "Spec"),
        h("pre", {}, JSON.stringify(job.spec, null, 2))
      )
    ),
    h("div", { class: "card" }, h("h2", {}, "Pods"), h("table", {}, h("tbody", {}, pods.length ? pods : h("tr", {}, h("td", { class: "muted" }, "none"))))),
    h(
      "div",
      { class: "card" },
      h("h2", {}, "Volumes"),
      h(
        "table",
        {},
        h("thead", {}, h("tr", {}, ...["Role", "Volume", "Source", "Mounts"].map((t) => h("th", {}, t)))),
        h("tbody", {}, volRows.length ? volRows : h("tr", {}, h("td", { class: "muted", colspan: 4 }, "none")))
      )
    ),
    h("div", { class: "card" }, h("h2", {}, "Events"), h("table", {}, h("tbody", {}, events.length ? events : h("tr", {}, h("td", { class: "muted" }, "none"))))),
    h("div", { id: "log-panel" })
  );
}

async function showLogs(ns, podName) {
  const panel = document.getElementById("log-panel");
  try {
    const d = await api(`/pod/${ns}/${podName}/logs`);
    panel.replaceChildren(
      h("div", { class: "card" }, h("h2", {}, `Logs — ${podName}`), h("pre", { class: "logs" }, d.logs || "(empty)"))
    );
  } catch (e) {
    panel.replaceChildren(h("div", { class: "card" }, h("p", { class: "muted" }, `No logs: ${e.message}`)));
  }
}

// ---------- structured create form (parity: CreateJob.jsx /
// CreateReplicaSpec.jsx / EnvVarCreator.jsx / VolumeCreator.jsx — TPU-native
// twist: the accelerator picker is backed by the server's slice catalog) ----

const REPLICA_TYPES = ["Worker", "Chief", "PS", "Evaluator"];
const RESTART_POLICIES = ["Never", "OnFailure", "Always", "ExitCode"];
let acceleratorCatalog = []; // fetched once per create view

function kvRows(title, fields) {
  // Dynamic add/remove rows of small inputs (env vars, volumes).
  const body = h("div", { class: "kv-rows" });
  const addRow = (values = {}) => {
    const inputs = fields.map((f) =>
      h("input", {
        class: "kv",
        "data-field": f.name,
        placeholder: f.placeholder,
        value: values[f.name] || "",
      })
    );
    const row = h(
      "div",
      { class: "kv-row" },
      ...inputs,
      h("button", { type: "button", class: "ghost", onclick: () => row.remove() }, "×")
    );
    body.append(row);
  };
  const header = h(
    "div",
    { class: "kv-header" },
    h("span", {}, title),
    h("button", { type: "button", class: "ghost", onclick: () => addRow() }, "+ add")
  );
  const read = () =>
    [...body.querySelectorAll(".kv-row")]
      .map((row) => {
        const out = {};
        for (const inp of row.querySelectorAll("input.kv")) out[inp.dataset.field] = inp.value.trim();
        return out;
      })
      .filter((r) => Object.values(r).some((v) => v));
  return { el: h("div", { class: "kv-group" }, header, body), read, addRow };
}

function replicaSpecCard(onRemove, initType, initSpec) {
  // initType/initSpec: prefill from an existing job's replicaSpecs entry
  // (the clone/resubmit path); omitted = blank defaults.
  const init = initSpec || {};
  const c0 = init.template?.spec?.containers?.[0] || {};
  const typeSel = h("select", { "data-k": "type" }, ...REPLICA_TYPES.map((t) => h("option", { value: t }, t)));
  if (initType) typeSel.value = initType;
  const replicas = h("input", { "data-k": "replicas", type: "number", value: String(init.replicas || 2), min: "1" });
  const image = h("input", { "data-k": "image", value: c0.image || "tpu-operator/test-server" });
  const command = h("textarea", { "data-k": "command", placeholder: '["python", "train.py"] (JSON array, optional)' });
  if (c0.command) command.value = JSON.stringify(c0.command);
  const cmdArgs = h("textarea", { "data-k": "args", placeholder: '["--steps", "100"] (JSON array, optional)' });
  if (c0.args) cmdArgs.value = JSON.stringify(c0.args);
  // Per-replica compute resources (reference parity: CreateReplicaSpec's
  // gpuCount — generalized to the requests/limits the scheduler uses).
  const res = {};
  for (const key of ["reqCpu", "reqMem", "limCpu", "limMem"]) {
    res[key] = h("input", { class: "kv", "data-k": key, placeholder: {
      reqCpu: "cpu request (500m)", reqMem: "memory request (1Gi)",
      limCpu: "cpu limit", limMem: "memory limit",
    }[key] });
  }
  const initRes = c0.resources || {};
  res.reqCpu.value = initRes.requests?.cpu || "";
  res.reqMem.value = initRes.requests?.memory || "";
  res.limCpu.value = initRes.limits?.cpu || "";
  res.limMem.value = initRes.limits?.memory || "";
  const restart = h("select", { "data-k": "restart" }, ...RESTART_POLICIES.map((p) => h("option", { value: p }, p)));
  if (init.restartPolicy) restart.value = init.restartPolicy;

  // TPU slice picker: accelerator dropdown from the server catalog; the
  // topology/hosts readout updates live, numSlices enables DCN multislice.
  const accSel = h(
    "select",
    { "data-k": "accelerator" },
    h("option", { value: "" }, "none (CPU / plain replicas)"),
    ...acceleratorCatalog.map((a) =>
      h(
        "option",
        { value: a.acceleratorType, "data-topology": a.topology, "data-hosts": a.numHosts },
        `${a.acceleratorType} — ${a.topology}, ${a.numHosts} host${a.numHosts > 1 ? "s" : ""}`
      )
    )
  );
  const numSlices = h("input", { "data-k": "numSlices", type: "number", value: "1", min: "1" });
  const sliceInfo = h("span", { class: "muted" }, "");
  const syncSlice = () => {
    const opt = accSel.selectedOptions[0];
    const on = Boolean(accSel.value);
    replicas.disabled = on; // a slice binding determines the pod count
    numSlices.disabled = !on;
    sliceInfo.textContent = on
      ? `${opt.dataset.topology} topology · ${opt.dataset.hosts} pod(s)/slice × ${numSlices.value || 1} slice(s)`
      : "";
  };
  if (init.tpu?.acceleratorType) {
    accSel.value = init.tpu.acceleratorType;
    if (init.tpu.numSlices) numSlices.value = String(init.tpu.numSlices);
  }
  accSel.addEventListener("change", syncSlice);
  numSlices.addEventListener("input", syncSlice);
  syncSlice(); // initial state: numSlices disabled until a slice is chosen

  const envRows = kvRows("Environment variables", [
    { name: "name", placeholder: "NAME" },
    { name: "value", placeholder: "value" },
  ]);
  const volRows = kvRows("Volumes (hostPath)", [
    { name: "name", placeholder: "volume name" },
    { name: "hostPath", placeholder: "/host/path" },
    { name: "mountPath", placeholder: "/mount/path" },
  ]);
  for (const e of c0.env || []) envRows.addRow({ name: e.name, value: e.value });
  const mountByName = {};
  for (const vm of c0.volumeMounts || []) mountByName[vm.name] = vm.mountPath;
  for (const v of init.template?.spec?.volumes || []) {
    volRows.addRow({
      name: v.name,
      hostPath: v.hostPath?.path || "",
      mountPath: mountByName[v.name] || "",
    });
  }

  const card = h(
    "div",
    { class: "card replica-spec" },
    h(
      "div",
      { class: "toolbar" },
      h("h2", {}, "Replica set"),
      h("button", { type: "button", class: "ghost", onclick: () => onRemove(card) }, "remove")
    ),
    h("label", {}, "Role"), typeSel,
    h("label", {}, "Replicas (ignored when a TPU slice is bound)"), replicas,
    h("label", {}, "TPU slice"), accSel,
    h("label", {}, "Slices (numSlices > 1 = DCN multislice)"), numSlices, sliceInfo,
    h("label", {}, "Restart policy"), restart,
    h("label", {}, "Image"), image,
    h("label", {}, "Command"), command,
    h("label", {}, "Args"), cmdArgs,
    h("label", {}, "Resources"),
    h("div", { class: "kv-row" }, res.reqCpu, res.reqMem),
    h("div", { class: "kv-row" }, res.limCpu, res.limMem),
    envRows.el,
    volRows.el
  );

  card.readSpec = () => {
    const container = { name: "tensorflow", image: image.value.trim() };
    const cmd = command.value.trim();
    // Both must be JSON ARRAYS of strings: a bare JSON string would
    // pass JSON.parse and then explode into per-character argv elements
    // in the executor's list() — fail the form instead.
    const parseArgv = (text, label) => {
      const v = JSON.parse(text);
      if (!Array.isArray(v) || v.some((s) => typeof s !== "string")) {
        throw new Error(`${label} must be a JSON array of strings`);
      }
      return v;
    };
    if (cmd) container.command = parseArgv(cmd, "command");
    const argv = cmdArgs.value.trim();
    if (argv) container.args = parseArgv(argv, "args");
    const requests = {};
    if (res.reqCpu.value.trim()) requests.cpu = res.reqCpu.value.trim();
    if (res.reqMem.value.trim()) requests.memory = res.reqMem.value.trim();
    const limits = {};
    if (res.limCpu.value.trim()) limits.cpu = res.limCpu.value.trim();
    if (res.limMem.value.trim()) limits.memory = res.limMem.value.trim();
    if (Object.keys(requests).length || Object.keys(limits).length) {
      container.resources = {};
      if (Object.keys(requests).length) container.resources.requests = requests;
      if (Object.keys(limits).length) container.resources.limits = limits;
    }
    const env = envRows.read().map((r) => ({ name: r.name, value: r.value }));
    if (env.length) container.env = env;
    const vols = volRows.read();
    if (vols.length) {
      container.volumeMounts = vols.map((v) => ({ name: v.name, mountPath: v.mountPath }));
    }
    const template = { spec: { containers: [container] } };
    if (vols.length) {
      template.spec.volumes = vols.map((v) => ({ name: v.name, hostPath: { path: v.hostPath } }));
    }
    const spec = { template, restartPolicy: restart.value };
    if (accSel.value) {
      const opt = accSel.selectedOptions[0];
      spec.tpu = { acceleratorType: accSel.value, topology: opt.dataset.topology };
      const n = parseInt(numSlices.value, 10) || 1;
      if (n > 1) spec.tpu.numSlices = n;
    } else {
      spec.replicas = parseInt(replicas.value, 10) || 1;
    }
    return [typeSel.value, spec];
  };
  return card;
}

async function createView(prefill) {
  // prefill: an existing TPUJob object (clone/resubmit) — the form opens
  // populated with its spec, name suffixed "-copy" (parity: the reference
  // UI has no clone; kubectl users re-apply edited manifests).
  try {
    acceleratorCatalog = (await api("/accelerators")).items || [];
  } catch (e) {
    acceleratorCatalog = [];
  }
  const errBox = h("div", { id: "create-error", class: "error hidden" });
  const specsHost = h("div", { id: "replica-specs" });
  const removeCard = (card) => {
    if (specsHost.children.length > 1) card.remove();
  };
  const preSpecs = Object.entries(prefill?.spec?.replicaSpecs || {});
  if (preSpecs.length) {
    for (const [type, spec] of preSpecs) {
      specsHost.append(replicaSpecCard(removeCard, type, spec));
    }
  } else {
    specsHost.append(replicaSpecCard(removeCard));
  }

  const name = h("input", {
    name: "name", required: "", placeholder: "my-train-job",
    value: prefill ? `${prefill.metadata.name}-copy` : "",
  });
  const namespace = h("input", {
    name: "namespace", value: prefill?.metadata?.namespace || "default",
  });
  const cleanPolicy = h(
    "select",
    {},
    ...["Running", "All", "None"].map((p) => h("option", { value: p }, p))
  );
  if (prefill?.spec?.cleanPodPolicy) cleanPolicy.value = prefill.spec.cleanPodPolicy;
  const ttl = h("input", { type: "number", placeholder: "seconds (optional)", min: "0" });
  if (prefill?.spec?.ttlSecondsAfterFinished != null) ttl.value = String(prefill.spec.ttlSecondsAfterFinished);
  const gang = h("input", { type: "checkbox" });
  if (prefill?.spec?.scheduling?.gang) gang.checked = true;
  const scheduler = h("input", { placeholder: "scheduler name (optional)" });
  if (prefill?.spec?.scheduling?.schedulerName) scheduler.value = prefill.spec.scheduling.schedulerName;

  const form = h(
    "form",
    {},
    h("label", {}, "Name"), name,
    h("label", {}, "Namespace"), namespace,
    specsHost,
    h(
      "button",
      { type: "button", class: "ghost", onclick: () => specsHost.append(replicaSpecCard(removeCard)) },
      "+ add replica set"
    ),
    h("div", { class: "card" },
      h("h2", {}, "Job policies"),
      h("label", {}, "Clean pod policy"), cleanPolicy,
      h("label", {}, "TTL after finished"), ttl,
      h("label", {}, h("span", {}, "Gang scheduling "), gang),
      h("label", {}, "Scheduler"), scheduler
    ),
    errBox,
    h("pre", { id: "manifest-preview", class: "hidden" }),
    h("div", { style: "margin-top:1rem" },
      h("button", { type: "submit" }, "Deploy"),
      h("button", {
        type: "button", class: "ghost", style: "margin-left:.5rem",
        onclick: () => previewManifest(),
      }, "Preview manifest")
    )
  );

  // One builder for both Deploy and Preview: what you preview is
  // byte-for-byte what gets POSTed (kubectl users can paste it into a
  // manifest for `tpuctl apply -f`).
  const buildJob = () => {
    const replicaSpecs = {};
    for (const card of specsHost.querySelectorAll(".replica-spec")) {
      const [type, spec] = card.readSpec();
      if (replicaSpecs[type]) throw new Error(`duplicate replica role ${type}`);
      replicaSpecs[type] = spec;
    }
    const job = {
      apiVersion: "tpuflow.org/v1",
      kind: "TPUJob",
      metadata: { name: name.value.trim(), namespace: namespace.value.trim() || "default" },
      spec: { replicaSpecs, cleanPodPolicy: cleanPolicy.value },
    };
    if (ttl.value) job.spec.ttlSecondsAfterFinished = parseInt(ttl.value, 10);
    if (gang.checked || scheduler.value.trim()) {
      job.spec.scheduling = { gang: gang.checked };
      if (scheduler.value.trim()) job.spec.scheduling.schedulerName = scheduler.value.trim();
    }
    return job;
  };

  const previewManifest = () => {
    const pre = document.getElementById("manifest-preview");
    errBox.classList.add("hidden");
    try {
      pre.textContent = JSON.stringify(buildJob(), null, 2);
      pre.classList.remove("hidden");
    } catch (e) {
      pre.classList.add("hidden");
      errBox.textContent = "Invalid form: " + e.message;
      errBox.classList.remove("hidden");
    }
  };

  form.addEventListener("submit", async (ev) => {
    ev.preventDefault();
    errBox.classList.add("hidden");
    let job;
    try {
      job = buildJob();
    } catch (e) {
      errBox.textContent = "Invalid form: " + e.message;
      errBox.classList.remove("hidden");
      return;
    }
    try {
      await api("/tpujob", {
        method: "POST",
        headers: { "Content-Type": "application/json" },
        body: JSON.stringify(job),
      });
      location.hash = `#/job/${job.metadata.namespace}/${job.metadata.name}`;
    } catch (e) {
      // Server-side validation (422 Invalid) surfaces here verbatim.
      errBox.textContent = "Deploy rejected: " + e.message;
      errBox.classList.remove("hidden");
    }
  });
  app.replaceChildren(
    h(
      "div",
      { class: "card" },
      h("h2", {}, prefill ? `Clone TPUJob ${prefill.metadata.namespace}/${prefill.metadata.name}` : "Create TPUJob"),
      form
    )
  );
}

// ---------- router ----------
async function refreshNamespaces() {
  try {
    const d = await api("/namespace");
    const current = nsSelect.value || "*";
    nsSelect.replaceChildren(
      h("option", { value: "*" }, "all namespaces"),
      ...(d.items || []).map((n) => h("option", { value: n }, n))
    );
    nsSelect.value = current;
  } catch (e) {
    /* server restarting */
  }
}

async function route() {
  const parts = location.hash.replace(/^#\/?/, "").split("/").filter(Boolean);
  try {
    if (parts[0] === "create") {
      if (pollTimer) clearInterval(pollTimer);
      await createView();
    } else if (parts[0] === "clone" && parts.length === 3) {
      // Deep-linkable clone/resubmit: fetch the source job, open the
      // create form prefilled with its spec.
      if (pollTimer) clearInterval(pollTimer);
      const d = await api(`/tpujob/${parts[1]}/${parts[2]}`);
      await createView(d.tpujob);
    } else if (parts[0] === "job" && parts.length === 3) {
      await jobDetailView(parts[1], parts[2]);
      setPoll(() => jobDetailView(parts[1], parts[2]).catch(() => {}));
    } else {
      await jobListView();
      setPoll(() => jobListView().catch(() => {}));
    }
  } catch (e) {
    app.replaceChildren(h("div", { class: "card" }, h("p", { class: "muted" }, "Error: " + e.message)));
  }
}

window.addEventListener("hashchange", route);
nsSelect.addEventListener("change", route);
refreshNamespaces();
setInterval(refreshNamespaces, 10000);
route();
