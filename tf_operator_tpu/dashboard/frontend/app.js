/* TPU Job Operator dashboard SPA.
 *
 * Hash-routed views over /tpujobs/api (the reference's services.js REST
 * surface): #/ job list, #/job/{ns}/{name} detail with pods + events +
 * log viewer, #/create deploy form. Polls the list/detail every 3 s.
 */
"use strict";

const app = document.getElementById("app");
const nsSelect = document.getElementById("ns-select");
let pollTimer = null;

// ---------- api ----------
async function api(path, opts) {
  const resp = await fetch("/tpujobs/api" + path, opts);
  const body = await resp.json().catch(() => ({}));
  if (!resp.ok) throw new Error(body.message || resp.statusText);
  return body;
}

// ---------- helpers ----------
function h(tag, attrs, ...children) {
  const el = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs || {})) {
    if (k === "onclick") el.addEventListener("click", v);
    else if (k === "class") el.className = v;
    else el.setAttribute(k, v);
  }
  for (const c of children.flat()) {
    if (c == null) continue;
    el.append(c.nodeType ? c : document.createTextNode(String(c)));
  }
  return el;
}

function activeConditions(job) {
  return (job.status?.conditions || []).filter((c) => c.status === "True");
}

function phaseBadge(job) {
  const conds = activeConditions(job).map((c) => c.type);
  const order = ["Failed", "Succeeded", "Restarting", "Running", "Created"];
  const top = order.find((t) => conds.includes(t)) || "Created";
  return h("span", { class: "badge " + top }, top);
}

function replicaSummary(job) {
  const rs = job.status?.replicaStatuses || {};
  return Object.entries(rs)
    .map(([t, s]) => `${t} ${s.active || 0}/${s.succeeded || 0}/${s.failed || 0}`)
    .join(" · ");
}

function setPoll(fn) {
  if (pollTimer) clearInterval(pollTimer);
  pollTimer = setInterval(fn, 3000);
}

// ---------- views ----------
async function jobListView() {
  const ns = nsSelect.value;
  const data = await api(ns && ns !== "*" ? `/tpujob/${ns}` : "/tpujob");
  const rows = (data.items || []).map((job) => {
    const m = job.metadata;
    return h(
      "tr",
      {
        class: "clickable",
        onclick: () => (location.hash = `#/job/${m.namespace}/${m.name}`),
      },
      h("td", {}, m.namespace),
      h("td", {}, m.name),
      h("td", {}, phaseBadge(job)),
      h("td", {}, replicaSummary(job) || "—"),
      h("td", { class: "muted" }, m.creationTimestamp || "")
    );
  });
  app.replaceChildren(
    h("div", { class: "toolbar" }, h("h2", {}, "TPUJobs"), ""),
    h(
      "table",
      {},
      h(
        "thead",
        {},
        h("tr", {}, ...["Namespace", "Name", "State", "Active/Done/Failed", "Created"].map((t) => h("th", {}, t)))
      ),
      h("tbody", {}, rows.length ? rows : h("tr", {}, h("td", { class: "muted", colspan: 5 }, "No jobs")))
    )
  );
}

async function jobDetailView(ns, name) {
  const d = await api(`/tpujob/${ns}/${name}`);
  const job = d.tpujob;
  const conds = (job.status?.conditions || []).map((c) =>
    h(
      "tr",
      {},
      h("td", {}, c.type),
      h("td", {}, c.status),
      h("td", {}, c.reason || ""),
      h("td", { class: "muted" }, c.message || ""),
      h("td", { class: "muted" }, c.lastTransitionTime || "")
    )
  );
  const pods = (d.pods || []).map((p) =>
    h(
      "tr",
      {},
      h("td", {}, p.metadata.name),
      h("td", {}, h("span", { class: "badge " + (p.status?.phase || "") }, p.status?.phase || "?")),
      h("td", {}, (p.status?.containerStatuses || []).map((cs) => `restarts:${cs.restartCount ?? 0}`).join(" ")),
      h(
        "td",
        {},
        h("button", { class: "ghost", onclick: () => showLogs(ns, p.metadata.name) }, "logs")
      )
    )
  );
  const events = (d.events || []).slice(-20).map((e) =>
    h(
      "tr",
      {},
      h("td", {}, e.type || ""),
      h("td", {}, e.reason || ""),
      h("td", { class: "muted" }, e.message || ""),
      h("td", { class: "muted" }, e.involvedObject?.name || "")
    )
  );
  app.replaceChildren(
    h(
      "div",
      { class: "toolbar" },
      h("h2", {}, `${ns}/${name} `, phaseBadge(job)),
      h(
        "button",
        {
          class: "danger",
          onclick: async () => {
            if (confirm(`Delete TPUJob ${ns}/${name}?`)) {
              await api(`/tpujob/${ns}/${name}`, { method: "DELETE" });
              location.hash = "#/";
            }
          },
        },
        "Delete"
      )
    ),
    h(
      "div",
      { class: "row" },
      h(
        "div",
        { class: "card" },
        h("h2", {}, "Conditions"),
        h("table", {}, h("tbody", {}, conds.length ? conds : h("tr", {}, h("td", { class: "muted" }, "none"))))
      ),
      h(
        "div",
        { class: "card" },
        h("h2", {}, "Spec"),
        h("pre", {}, JSON.stringify(job.spec, null, 2))
      )
    ),
    h("div", { class: "card" }, h("h2", {}, "Pods"), h("table", {}, h("tbody", {}, pods.length ? pods : h("tr", {}, h("td", { class: "muted" }, "none"))))),
    h("div", { class: "card" }, h("h2", {}, "Events"), h("table", {}, h("tbody", {}, events.length ? events : h("tr", {}, h("td", { class: "muted" }, "none"))))),
    h("div", { id: "log-panel" })
  );
}

async function showLogs(ns, podName) {
  const panel = document.getElementById("log-panel");
  try {
    const d = await api(`/pod/${ns}/${podName}/logs`);
    panel.replaceChildren(
      h("div", { class: "card" }, h("h2", {}, `Logs — ${podName}`), h("pre", { class: "logs" }, d.logs || "(empty)"))
    );
  } catch (e) {
    panel.replaceChildren(h("div", { class: "card" }, h("p", { class: "muted" }, `No logs: ${e.message}`)));
  }
}

function createView() {
  const form = h(
    "form",
    {},
    h("label", {}, "Name"),
    h("input", { name: "name", required: "", placeholder: "my-train-job" }),
    h("label", {}, "Namespace"),
    h("input", { name: "namespace", value: "default" }),
    h("label", {}, "Worker replicas"),
    h("input", { name: "workers", type: "number", value: "2", min: "1" }),
    h("label", {}, "PS replicas (0 for none)"),
    h("input", { name: "ps", type: "number", value: "0", min: "0" }),
    h("label", {}, "TPU accelerator (optional, e.g. v5e-16 — overrides worker count)"),
    h("input", { name: "accelerator", placeholder: "" }),
    h("label", {}, "Image"),
    h("input", { name: "image", value: "tpu-operator/test-server" }),
    h("label", {}, "Command (JSON array, optional)"),
    h("textarea", { name: "command", placeholder: '["python", "train.py"]' }),
    h("div", { style: "margin-top:1rem" }, h("button", { type: "submit" }, "Deploy"))
  );
  form.addEventListener("submit", async (ev) => {
    ev.preventDefault();
    const f = new FormData(form);
    const container = { name: "tensorflow", image: f.get("image") };
    const cmd = (f.get("command") || "").trim();
    if (cmd) container.command = JSON.parse(cmd);
    const worker = { template: { spec: { containers: [container] } } };
    if (f.get("accelerator")) worker.tpu = { acceleratorType: f.get("accelerator") };
    else worker.replicas = parseInt(f.get("workers"), 10);
    const replicaSpecs = { Worker: worker };
    const ps = parseInt(f.get("ps"), 10);
    if (ps > 0)
      replicaSpecs.PS = {
        replicas: ps,
        template: { spec: { containers: [{ ...container }] } },
      };
    const job = {
      apiVersion: "tpuflow.org/v1",
      kind: "TPUJob",
      metadata: { name: f.get("name"), namespace: f.get("namespace") || "default" },
      spec: { replicaSpecs },
    };
    try {
      await api("/tpujob", {
        method: "POST",
        headers: { "Content-Type": "application/json" },
        body: JSON.stringify(job),
      });
      location.hash = `#/job/${job.metadata.namespace}/${job.metadata.name}`;
    } catch (e) {
      alert("Deploy failed: " + e.message);
    }
  });
  app.replaceChildren(h("div", { class: "card" }, h("h2", {}, "Create TPUJob"), form));
}

// ---------- router ----------
async function refreshNamespaces() {
  try {
    const d = await api("/namespace");
    const current = nsSelect.value || "*";
    nsSelect.replaceChildren(
      h("option", { value: "*" }, "all namespaces"),
      ...(d.items || []).map((n) => h("option", { value: n }, n))
    );
    nsSelect.value = current;
  } catch (e) {
    /* server restarting */
  }
}

async function route() {
  const parts = location.hash.replace(/^#\/?/, "").split("/").filter(Boolean);
  try {
    if (parts[0] === "create") {
      if (pollTimer) clearInterval(pollTimer);
      createView();
    } else if (parts[0] === "job" && parts.length === 3) {
      await jobDetailView(parts[1], parts[2]);
      setPoll(() => jobDetailView(parts[1], parts[2]).catch(() => {}));
    } else {
      await jobListView();
      setPoll(() => jobListView().catch(() => {}));
    }
  } catch (e) {
    app.replaceChildren(h("div", { class: "card" }, h("p", { class: "muted" }, "Error: " + e.message)));
  }
}

window.addEventListener("hashchange", route);
nsSelect.addEventListener("change", route);
refreshNamespaces();
setInterval(refreshNamespaces, 10000);
route();
