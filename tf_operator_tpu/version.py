"""Version info for the tpu-job-operator framework.

TPU-native analog of the reference's pkg/version/version.go:21-43.
"""

from __future__ import annotations

import platform

VERSION = "0.1.0"
GIT_SHA = "dev"


def version_string() -> str:
    return (
        f"tpu-job-operator {VERSION} (git {GIT_SHA}) "
        f"python {platform.python_version()} on {platform.system().lower()}"
    )
