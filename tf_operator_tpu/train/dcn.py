"""Cross-slice (DCN) collective channel for multislice training.

The transport analog of the MEGASCALE coordinator the operator wires up for
``num_slices > 1`` jobs (controller/cluster_spec.py gen_tpu_env): each
slice is its own jax.distributed process group running ICI collectives
internally; gradients/params are synchronized ACROSS slices over the data
center network. On real TPU multislice, libtpu's MEGASCALE transport does
this under one global jit; this module is the framework-level fallback and
the CPU-testable contract proof — slice leaders (in-slice process 0) meet
at MEGASCALE_COORDINATOR_ADDRESS and run allreduce over TCP, then
broadcast the result to their in-slice peers through the existing
jax.distributed group (multihost_utils.broadcast_one_to_all, which rides
the ICI mesh on hardware).

SURVEY.md §2.9: "keep DNS rendezvous for inter-slice DCN" — the address IS
a pod DNS name + port, so the same code runs under the local executor
(rewritten to 127.0.0.1) and on a real cluster.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import time
from typing import Any

import numpy as np

from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="dcn")

_HDR = struct.Struct("!I")  # 4-byte big-endian frame length


def _send_msg(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj)
    sock.sendall(_HDR.pack(len(data)) + data)


def _recv_msg(sock: socket.socket) -> Any:
    buf = b""
    while len(buf) < _HDR.size:
        chunk = sock.recv(_HDR.size - len(buf))
        if not chunk:
            raise ConnectionError("DCN peer closed mid-header")
        buf += chunk
    (n,) = _HDR.unpack(buf)
    parts: list[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(min(1 << 20, n - got))
        if not chunk:
            raise ConnectionError("DCN peer closed mid-frame")
        parts.append(chunk)
        got += len(chunk)
    return pickle.loads(b"".join(parts))


class CrossSliceChannel:
    """Slice-leader rendezvous at the MEGASCALE coordinator address.

    Only in-slice process 0 of each slice participates in the TCP leg;
    every process constructs the channel (non-leaders get a no-op handle
    whose :meth:`allreduce` raises — callers pair it with an in-slice
    broadcast, see :func:`cross_slice_mean`).
    """

    def __init__(
        self,
        slice_id: int,
        num_slices: int,
        coordinator_address: str,
        *,
        is_slice_leader: bool,
        timeout: float = 120.0,
    ) -> None:
        self.slice_id = slice_id
        self.num_slices = num_slices
        self.is_slice_leader = is_slice_leader
        self._timeout = timeout
        self._listener: socket.socket | None = None
        self._peers: dict[int, socket.socket] = {}  # slice_id -> conn (on slice 0)
        self._sock: socket.socket | None = None  # on slices > 0
        if not is_slice_leader or num_slices < 2:
            return
        host, port_s = coordinator_address.rsplit(":", 1)
        port = int(port_s)
        if slice_id == 0:
            self._bind_and_accept(host, port)
        else:
            self._connect(host, port)

    # -- rendezvous ---------------------------------------------------------

    def _bind_and_accept(self, host: str, port: int) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # The contract address names THIS pod; bind all interfaces so DNS
        # resolution differences (pod IP vs localhost rewrite) don't matter.
        srv.bind(("", port))
        srv.listen(self.num_slices)
        srv.settimeout(self._timeout)
        self._listener = srv
        deadline = time.monotonic() + self._timeout
        while len(self._peers) < self.num_slices - 1:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"DCN rendezvous: {len(self._peers) + 1}/{self.num_slices}"
                    " slices present at timeout"
                )
            conn, _ = srv.accept()
            # accept() does not inherit the listener's timeout: without this
            # a peer that connects then stalls would block recv() forever.
            conn.settimeout(self._timeout)
            hello = _recv_msg(conn)
            self._peers[int(hello["slice_id"])] = conn
        LOG.info("DCN rendezvous complete: %d slices", self.num_slices)

    def _connect(self, host: str, port: int) -> None:
        deadline = time.monotonic() + self._timeout
        last: Exception | None = None
        while time.monotonic() < deadline:
            try:
                sock = socket.create_connection((host, port), timeout=5.0)
                sock.settimeout(self._timeout)
                _send_msg(sock, {"slice_id": self.slice_id})
                self._sock = sock
                return
            except OSError as e:  # coordinator not up yet
                last = e
                time.sleep(0.2)
        raise TimeoutError(
            f"DCN connect to {host}:{port} failed within budget: {last}"
        )

    # -- collectives --------------------------------------------------------

    def allreduce(self, arrays: list[np.ndarray], op: str = "mean") -> list[np.ndarray]:
        """Leader-side allreduce: slice 0 gathers, reduces, fans back out."""
        if not self.is_slice_leader:
            raise RuntimeError("allreduce is leader-only; use cross_slice_mean")
        if self.num_slices < 2:
            return arrays
        if self.slice_id == 0:
            acc = [np.asarray(a, dtype=np.float32).copy() for a in arrays]
            for sid in sorted(self._peers):
                theirs = _recv_msg(self._peers[sid])
                for mine, other in zip(acc, theirs):
                    mine += other
            if op == "mean":
                for a in acc:
                    a /= self.num_slices
            for sid in sorted(self._peers):
                _send_msg(self._peers[sid], acc)
            return acc
        assert self._sock is not None
        _send_msg(self._sock, [np.asarray(a, dtype=np.float32) for a in arrays])
        return _recv_msg(self._sock)

    def close(self) -> None:
        for sock in (*self._peers.values(), self._sock, self._listener):
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        self._peers.clear()
        self._sock = self._listener = None


def channel_from_env(
    env: dict[str, str] | None = None, *, in_slice_process_id: int, timeout: float = 120.0
) -> CrossSliceChannel | None:
    """Build the channel from the operator-injected MEGASCALE env (None for
    single-slice jobs — no DCN leg to run)."""
    env = dict(os.environ if env is None else env)
    num_slices = int(env.get("MEGASCALE_NUM_SLICES", "1"))
    if num_slices < 2:
        return None
    return CrossSliceChannel(
        int(env.get("MEGASCALE_SLICE_ID", "0")),
        num_slices,
        env["MEGASCALE_COORDINATOR_ADDRESS"],
        is_slice_leader=in_slice_process_id == 0,
        timeout=timeout,
    )


def cross_slice_mean(channel: CrossSliceChannel | None, tree: Any) -> Any:
    """Mean a pytree of arrays across slices: DCN allreduce between slice
    leaders, then in-slice broadcast from the leader over the existing
    jax.distributed group. No-op for single-slice jobs (channel None).

    This is the framework's param/grad sync for CPU-tested multislice and
    the documented fallback where MEGASCALE-in-jit is unavailable."""
    import jax
    from jax.experimental import multihost_utils

    if channel is None:
        return tree
    leaves, treedef = jax.tree.flatten(tree)
    if channel.is_slice_leader:
        reduced = channel.allreduce([np.asarray(leaf) for leaf in leaves])
    else:
        reduced = [np.zeros_like(np.asarray(leaf)) for leaf in leaves]
    # In-slice broadcast rides the slice's own process group (ICI on
    # hardware): process 0 is the DCN participant, everyone else receives.
    reduced = multihost_utils.broadcast_one_to_all(
        tuple(reduced), is_source=channel.is_slice_leader
    )
    return jax.tree.unflatten(treedef, list(reduced))
