"""Consume the operator-injected topology contract inside a training process.

The analog of the reference sample's TF_CONFIG parsing + tf.train.Server
bring-up (examples/tf_sample/tf_smoke.py:86-113), TPU-first: the operator
injects TPU_COORDINATOR_ADDRESS / TPU_WORKER_ID / TPU_NUM_PROCESSES (see
controller/cluster_spec.py) and this module turns them into a
``jax.distributed.initialize`` call, after which ``jax.devices()`` spans the
whole slice and jitted SPMD code runs multi-host.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

from tf_operator_tpu.api import constants


@dataclass(frozen=True)
class ProcessTopology:
    coordinator_address: str | None
    process_id: int
    num_processes: int
    accelerator_type: str | None
    topology: str | None
    worker_hostnames: list[str]

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1 and self.coordinator_address is not None


def from_env(env: dict[str, str] | None = None) -> ProcessTopology:
    """Parse the injected contract; fall back to TF_CONFIG task info so plain
    TF-style pods (no TPU slice) also resolve their identity."""
    env = dict(os.environ if env is None else env)
    coord = env.get(constants.ENV_COORDINATOR_ADDRESS)
    worker_id = env.get(constants.ENV_TPU_WORKER_ID)
    num = env.get(constants.ENV_NUM_PROCESSES)

    if worker_id is None and constants.ENV_TF_CONFIG in env:
        try:
            tf_config = json.loads(env[constants.ENV_TF_CONFIG])
            worker_id = str(tf_config.get("task", {}).get("index", 0))
            cluster = tf_config.get("cluster", {})
            workers = cluster.get("worker", [])
            num = num or str(len(workers) or 1)
            if coord is None and workers:
                coord = workers[0]
        except (ValueError, KeyError):
            pass

    hostnames = [
        h for h in env.get(constants.ENV_TPU_WORKER_HOSTNAMES, "").split(",") if h
    ]
    return ProcessTopology(
        coordinator_address=coord,
        process_id=int(worker_id or 0),
        num_processes=int(num or 1),
        accelerator_type=env.get(constants.ENV_TPU_ACCELERATOR_TYPE),
        topology=env.get(constants.ENV_TPU_TOPOLOGY),
        worker_hostnames=hostnames,
    )


def initialize(topology: ProcessTopology | None = None) -> ProcessTopology:
    """jax.distributed.initialize from the injected env (no-op single-process)."""
    topo = topology or from_env()
    if topo.is_distributed:
        import jax

        jax.distributed.initialize(
            coordinator_address=topo.coordinator_address,
            num_processes=topo.num_processes,
            process_id=topo.process_id,
        )
    return topo
