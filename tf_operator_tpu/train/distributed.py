"""Consume the operator-injected topology contract inside a training process.

The analog of the reference sample's TF_CONFIG parsing + tf.train.Server
bring-up (examples/tf_sample/tf_smoke.py:86-113), TPU-first: the operator
injects TPU_COORDINATOR_ADDRESS / TPU_WORKER_ID / TPU_NUM_PROCESSES (see
controller/cluster_spec.py) and this module turns them into a
``jax.distributed.initialize`` call, after which ``jax.devices()`` spans the
whole slice and jitted SPMD code runs multi-host.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

from tf_operator_tpu.api import constants


@dataclass(frozen=True)
class ProcessTopology:
    coordinator_address: str | None
    process_id: int
    num_processes: int
    accelerator_type: str | None
    topology: str | None
    worker_hostnames: list[str]
    # The replica role from TF_CONFIG task.type ("worker", "chief", "ps",
    # "evaluator", ...). Role-aware workloads branch on this — the
    # reference's chief/evaluator semantics (SURVEY §2.9).
    role: str = "worker"

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1 and self.coordinator_address is not None


def from_env(env: dict[str, str] | None = None) -> ProcessTopology:
    """Parse the injected contract; fall back to TF_CONFIG task info so plain
    TF-style pods (no TPU slice) also resolve their identity.

    Evaluators never join the TRAINING rendezvous: the operator excludes
    them from the cluster map (controller/cluster_spec.py:58-62, the
    reference's evaluator exclusion), so TF_CONFIG-derived identity is
    neutralized for them (standalone: num_processes=1, no coordinator) —
    without this, a multi-worker job's evaluator would wrongly claim
    worker 0's rendezvous slot. TPU slice env still wins: a multi-host
    evaluator slice has its OWN rendezvous and must initialize it."""
    env = dict(os.environ if env is None else env)
    coord = env.get(constants.ENV_COORDINATOR_ADDRESS)
    worker_id = env.get(constants.ENV_TPU_WORKER_ID)
    num = env.get(constants.ENV_NUM_PROCESSES)
    role = "worker"

    if constants.ENV_TF_CONFIG in env:
        try:
            tf_config = json.loads(env[constants.ENV_TF_CONFIG])
            task = tf_config.get("task", {})
            role = str(task.get("type", role)) or role
            if worker_id is None:
                if role == "evaluator":
                    # Only neutralize TF_CONFIG-DERIVED identity: an
                    # evaluator must not claim a worker's rendezvous slot
                    # from the cluster map. TPU slice env (above) still
                    # wins — a multi-host evaluator slice has its own
                    # rendezvous and must initialize it.
                    coord, worker_id, num = None, "0", "1"
                else:
                    worker_id = str(task.get("index", 0))
                    cluster = tf_config.get("cluster", {})
                    workers = cluster.get("worker", [])
                    num = num or str(len(workers) or 1)
                    if coord is None and workers:
                        coord = workers[0]
        except (ValueError, KeyError):
            pass

    hostnames = [
        h for h in env.get(constants.ENV_TPU_WORKER_HOSTNAMES, "").split(",") if h
    ]
    return ProcessTopology(
        coordinator_address=coord,
        process_id=int(worker_id or 0),
        num_processes=int(num or 1),
        accelerator_type=env.get(constants.ENV_TPU_ACCELERATOR_TYPE),
        topology=env.get(constants.ENV_TPU_TOPOLOGY),
        worker_hostnames=hostnames,
        role=role,
    )


def initialize(topology: ProcessTopology | None = None) -> ProcessTopology:
    """jax.distributed.initialize from the injected env (no-op single-process)."""
    topo = topology or from_env()
    if topo.is_distributed:
        import jax

        jax.distributed.initialize(
            coordinator_address=topo.coordinator_address,
            num_processes=topo.num_processes,
            process_id=topo.process_id,
        )
    return topo
