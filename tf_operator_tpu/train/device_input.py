"""Device-resident input: the TPU-idiomatic answer to an h2d-bound host.

The round-5 hardware window attributed the ResNet end-to-end gap
(181 img/s vs 2,533 device-resident) to h2d transfer through the
tunnel — the host pipeline itself sustains 14.4k img/s (docs/perf.md,
"ResNet attribution"). When the dataset (or a working shard of it) fits
in HBM, the classic TPU move is to put the RAW uint8 records on device
ONCE and run sampling + augmentation there too: per step the only
"input pipeline" is an HBM gather + crop + flip fused into the training
scan — zero per-step host work, zero per-step transfer.

This is a different contract from the streaming path (`native/pipeline`
+ `native/augment`): sampling is i.i.d. with replacement via the JAX
PRNG (stateless, replayable from a key) rather than epoch-shuffled, and
the crop/flip draws come from `jax.random` rather than the native
augmenter's counter-based RNG — statistically equivalent augmentation,
not bit-identical. Document the mode on any number measured with it.

No reference counterpart: the reference operator has no input pipeline
at all (it schedules pods; SURVEY.md §2.9 — zero sharded-execution
code). This module exists because the framework side of this repo
carries the full training stack.
"""

from __future__ import annotations

from typing import Callable

import numpy as np


def load_records_numpy(
    path: str, rec_bytes: int, record_size: int
) -> tuple[np.ndarray, np.ndarray]:
    """Read a record file (image bytes + 1 trailing label byte per
    record — the `bench.ensure_bench_records` / `native.pipeline`
    layout) into ([N, R, R, 3] uint8 images, [N] int32 labels), ready
    for a one-time `jax.device_put`."""
    raw = np.fromfile(path, dtype=np.uint8)
    if raw.size % rec_bytes:
        raise ValueError(
            f"{path}: size {raw.size} is not a multiple of rec_bytes "
            f"{rec_bytes}"
        )
    n = raw.size // rec_bytes
    img_bytes = record_size * record_size * 3
    if img_bytes + 1 != rec_bytes:
        raise ValueError(
            f"rec_bytes {rec_bytes} != {record_size}^2*3 + 1 label byte"
        )
    recs = raw.reshape(n, rec_bytes)
    images = recs[:, :img_bytes].reshape(n, record_size, record_size, 3)
    labels = recs[:, img_bytes].astype(np.int32)
    return images, labels


def make_resident_sampler(
    images, labels, batch: int, image_size: int, num_classes: int = 1000
) -> Callable:
    """sample_batch(key) -> {"image": bf16 normalized [B,S,S,3],
    "label": int32 [B]} — gather + random-crop + random-hflip +
    normalize, entirely on device from resident uint8 records.

    `images`: [N, R, R, 3] uint8 (device array or committed numpy),
    `labels`: [N] int32. R > image_size enables random cropping (margin
    R - image_size); R == image_size degenerates to flip-only. Traceable
    under jit/scan: all shapes static, per-sample crops via a vmapped
    dynamic_slice.
    """
    import jax
    import jax.numpy as jnp

    n, r = images.shape[0], images.shape[1]
    margin = r - image_size
    if margin < 0:
        raise ValueError(f"records {r}^2 smaller than crop {image_size}^2")

    def sample_batch(key):
        k_idx, k_oy, k_ox, k_flip = jax.random.split(key, 4)
        idx = jax.random.randint(k_idx, (batch,), 0, n)
        oy = jax.random.randint(k_oy, (batch,), 0, margin + 1)
        ox = jax.random.randint(k_ox, (batch,), 0, margin + 1)
        flip = jax.random.bernoulli(k_flip, 0.5, (batch,))

        gathered = jnp.take(images, idx, axis=0)  # [B, R, R, 3] u8 gather

        def crop_one(img, y0, x0):
            return jax.lax.dynamic_slice(
                img, (y0, x0, 0), (image_size, image_size, 3)
            )

        cropped = jax.vmap(crop_one)(gathered, oy, ox)
        flipped = jnp.where(
            flip[:, None, None, None], cropped[:, :, ::-1, :], cropped
        )
        img = (flipped.astype(jnp.bfloat16) - 127.5) / 127.5
        return {"image": img, "label": jnp.take(labels, idx) % num_classes}

    return sample_batch


def make_resident_train_loop(
    step: Callable, sample_batch: Callable, n_steps: int
) -> Callable:
    """Fuse `n_steps` of (sample on device → train step) into one jitted
    scan: fused(state, key) -> (state, last_metrics, next_key). The PRNG
    key rides the scan carry, so consecutive calls continue the stream
    — the whole training loop runs without touching the host."""
    import jax

    def fused(state, key):
        def body(carry, _):
            state, key = carry
            key, sub = jax.random.split(key)
            state, metrics = step(state, sample_batch(sub))
            return (state, key), metrics

        (state, key), ms = jax.lax.scan(
            body, (state, key), None, length=n_steps
        )
        return state, {k: v[-1] for k, v in ms.items()}, key

    return jax.jit(fused, donate_argnums=(0,))
