"""Device-resident input: the TPU-idiomatic answer to an h2d-bound host.

The round-5 hardware window attributed the ResNet end-to-end gap
(181 img/s vs 2,533 device-resident) to h2d transfer through the
tunnel — the host pipeline itself sustains 14.4k img/s (docs/perf.md,
"ResNet attribution"). When the dataset (or a working shard of it) fits
in HBM, the classic TPU move is to put the RAW uint8 records on device
ONCE and run sampling + augmentation there too: per step the only
"input pipeline" is an HBM gather + crop + flip fused into the training
scan — zero per-step host work, zero per-step transfer.

Two sampling contracts, both fully on device: i.i.d.-with-replacement
(`make_resident_sampler` — stateless, replayable from a key) and exact
per-epoch permutation coverage (`make_resident_epoch_sampler` — the
classic input-pipeline semantics; permutation + cursor ride the scan
carry). Either way the crop/flip draws come from `jax.random` rather
than the native augmenter's counter-based RNG — statistically
equivalent augmentation to the streaming path, not bit-identical.
Document the mode on any number measured with it.

No reference counterpart: the reference operator has no input pipeline
at all (it schedules pods; SURVEY.md §2.9 — zero sharded-execution
code). This module exists because the framework side of this repo
carries the full training stack.
"""

from __future__ import annotations

from typing import Callable

import numpy as np


def load_records_numpy(
    path: str, rec_bytes: int, record_size: int
) -> tuple[np.ndarray, np.ndarray]:
    """Read a record file (image bytes + 1 trailing label byte per
    record — the `bench.ensure_bench_records` / `native.pipeline`
    layout) into ([N, R, R, 3] uint8 images, [N] int32 labels), ready
    for a one-time `jax.device_put`."""
    raw = np.fromfile(path, dtype=np.uint8)
    if raw.size % rec_bytes:
        raise ValueError(
            f"{path}: size {raw.size} is not a multiple of rec_bytes "
            f"{rec_bytes}"
        )
    n = raw.size // rec_bytes
    img_bytes = record_size * record_size * 3
    if img_bytes + 1 != rec_bytes:
        raise ValueError(
            f"rec_bytes {rec_bytes} != {record_size}^2*3 + 1 label byte"
        )
    recs = raw.reshape(n, rec_bytes)
    images = recs[:, :img_bytes].reshape(n, record_size, record_size, 3)
    labels = recs[:, img_bytes].astype(np.int32)
    return images, labels


def _make_augment(images, labels, image_size: int, num_classes: int):
    """augment(idx, k_oy, k_ox, k_flip) -> batch dict: the ONE
    gather + random-crop + random-hflip + normalize block, shared by
    both samplers so the two modes can never preprocess differently.
    Traceable under jit/scan: all shapes static, per-sample crops via a
    vmapped dynamic_slice."""
    import jax
    import jax.numpy as jnp

    r = images.shape[1]
    margin = r - image_size
    if margin < 0:
        raise ValueError(f"records {r}^2 smaller than crop {image_size}^2")

    def augment(idx, k_oy, k_ox, k_flip):
        batch = idx.shape[0]
        oy = jax.random.randint(k_oy, (batch,), 0, margin + 1)
        ox = jax.random.randint(k_ox, (batch,), 0, margin + 1)
        flip = jax.random.bernoulli(k_flip, 0.5, (batch,))

        gathered = jnp.take(images, idx, axis=0)  # [B, R, R, 3] u8 gather

        def crop_one(img, y0, x0):
            return jax.lax.dynamic_slice(
                img, (y0, x0, 0), (image_size, image_size, 3)
            )

        cropped = jax.vmap(crop_one)(gathered, oy, ox)
        flipped = jnp.where(
            flip[:, None, None, None], cropped[:, :, ::-1, :], cropped
        )
        img = (flipped.astype(jnp.bfloat16) - 127.5) / 127.5
        return {"image": img, "label": jnp.take(labels, idx) % num_classes}

    return augment


def make_resident_sampler(
    images, labels, batch: int, image_size: int, num_classes: int = 1000
) -> Callable:
    """sample_batch(key) -> {"image": bf16 normalized [B,S,S,3],
    "label": int32 [B]} — i.i.d.-with-replacement draws through the
    shared on-device augment block (make_resident_epoch_sampler is the
    epoch-shuffled alternative).

    `images`: [N, R, R, 3] uint8 (device array or committed numpy),
    `labels`: [N] int32. R > image_size enables random cropping (margin
    R - image_size); R == image_size degenerates to flip-only.
    """
    import jax

    n = images.shape[0]
    augment = _make_augment(images, labels, image_size, num_classes)

    def sample_batch(key):
        k_idx, k_oy, k_ox, k_flip = jax.random.split(key, 4)
        idx = jax.random.randint(k_idx, (batch,), 0, n)
        return augment(idx, k_oy, k_ox, k_flip)

    return sample_batch


def make_resident_epoch_sampler(
    images, labels, batch: int, image_size: int, num_classes: int = 1000
):
    """Epoch-shuffled variant of make_resident_sampler: every record is
    visited exactly once per epoch, in a per-epoch device-computed
    permutation (classic input-pipeline semantics, vs the plain
    sampler's i.i.d.-with-replacement draws).

    Returns (sample_batch, state0): ``sample_batch(key, state) ->
    (batch_dict, state)`` where state = (perm [N] int32, cursor scalar)
    rides the caller's scan carry alongside the key. Requires
    N % batch == 0 (drop-remainder semantics would silently skip a tail
    each epoch; an explicit contract beats a hidden one). The crop/flip
    draws still come from ``key`` per call, so augmentation differs
    across epochs even though the visit order is the permutation's.
    """
    import jax
    import jax.numpy as jnp

    n = images.shape[0]
    if n % batch:
        raise ValueError(
            f"records ({n}) must be divisible by batch ({batch}) for "
            "exact epoch coverage"
        )
    augment = _make_augment(images, labels, image_size, num_classes)

    def sample_batch(key, state):
        perm, cursor = state
        k_perm, k_oy, k_ox, k_flip = jax.random.split(key, 4)
        # Epoch boundary: reshuffle and restart. cursor is always a
        # multiple of batch (the only mutation is += batch), so the
        # boundary test is exact equality with n.
        at_end = cursor >= n
        perm = jax.lax.cond(
            at_end,
            lambda: jax.random.permutation(k_perm, n).astype(jnp.int32),
            lambda: perm,
        )
        cursor = jnp.where(at_end, 0, cursor)
        idx = jax.lax.dynamic_slice(perm, (cursor,), (batch,))
        return augment(idx, k_oy, k_ox, k_flip), (perm, cursor + batch)

    # cursor starts AT n so the first call draws the first permutation
    # from the caller's key — no host-side shuffle needed.
    state0 = (jnp.arange(n, dtype=jnp.int32), jnp.asarray(n, jnp.int32))
    return sample_batch, state0


def make_resident_epoch_train_loop(
    step: Callable, sample_batch: Callable, n_steps: int
) -> Callable:
    """THE fused (sample on device → train step) scan, stateful-sampler
    form: fused(state, key, sampler_state) -> (state, last_metrics,
    key, sampler_state). The PRNG key and the sampler state (e.g. the
    epoch sampler's permutation + cursor) ride the scan carry, so
    consecutive calls continue both streams — the whole training loop
    runs without touching the host. make_resident_train_loop is the
    stateless degenerate case built on this scaffold."""
    import jax

    def fused(state, key, sstate):
        def body(carry, _):
            state, key, sstate = carry
            key, sub = jax.random.split(key)
            batch, sstate = sample_batch(sub, sstate)
            state, metrics = step(state, batch)
            return (state, key, sstate), metrics

        (state, key, sstate), ms = jax.lax.scan(
            body, (state, key, sstate), None, length=n_steps
        )
        return state, {k: v[-1] for k, v in ms.items()}, key, sstate

    return jax.jit(fused, donate_argnums=(0,))


def make_resident_train_loop(
    step: Callable, sample_batch: Callable, n_steps: int
) -> Callable:
    """Stateless-sampler form: fused(state, key) -> (state,
    last_metrics, next_key), for make_resident_sampler's
    sample_batch(key). A thin wrapper over the stateful scaffold with
    unit sampler state — one loop implementation, two signatures."""

    def stateful_sample(key, sstate):
        return sample_batch(key), sstate

    inner = make_resident_epoch_train_loop(step, stateful_sample, n_steps)

    def fused(state, key):
        state, metrics, key, _ = inner(state, key, ())
        return state, metrics, key

    return fused
