"""Checkpoint / resume for train state (orbax-backed).

The reference delegates checkpointing entirely to user code — the operator's
contribution is stable pod identity + restart semantics so resume can work
(SURVEY.md §5, `pkg/trainer` keeps names/indices stable across restarts).
This framework keeps that contract AND owns the training stack, so it ships
the checkpoint layer too: orbax writes sharded TrainState pytrees (each host
persists its shards; restore honors the target's NamedShardings, so a
restored state lands pre-sharded on the mesh), and the restart policies of
the operator (ExitCode/OnFailure) compose with ``restore_or_init`` to give
kill-and-resume training out of the box — exercised end-to-end by the
preemption-recovery example tests.
"""

from __future__ import annotations

import os
from typing import Any

import jax

from tf_operator_tpu.ckpt import protocol as ckpt_protocol


def resume_min_step() -> int | None:
    """The operator-injected resume contract (TPU_RESUME_STEP): the last
    checkpoint step the operator saw acked before this pod was (re)placed.
    Pass it to restore_or_init(min_step=...) so a follower-cached step
    list can never resume below what is known durable."""
    raw = os.environ.get(ckpt_protocol.ENV_RESUME_STEP)
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def injected_dir() -> str | None:
    """The operator-injected checkpoint directory (TPU_CKPT_DIR), if any."""
    return os.environ.get(ckpt_protocol.ENV_CKPT_DIR) or None


class CheckpointManager:
    """Thin orbax CheckpointManager wrapper bound to one train state shape.

    save() is async (orbax background thread); close() drains pending
    writes. Directory layout is orbax-standard: {dir}/{step}/...

    Checkpoint coordination: when ``ack_path`` is set (defaulting to the
    operator-injected $TPU_CKPT_ACK_FILE), ``ack()``/``maybe_ack()`` write
    the durable-save report the local executor lifts into pod annotations
    (ckpt/protocol.py) — the worker's leg of the operator's checkpoint
    registry and graceful-eviction barrier.
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        ack_path: str | None = None,
    ) -> None:
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self.ack_path = (
            ack_path
            if ack_path is not None
            else os.environ.get(ckpt_protocol.ENV_ACK_FILE)
        )
        self._last_acked: int | None = None
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
            ),
        )

    @property
    def directory(self) -> str:
        return self._dir

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def reload(self) -> None:
        """Re-read the directory: orbax caches the step list, so a FOLLOWER
        process (e.g. an evaluator polling a trainer's checkpoints) must
        reload before latest_step/restore sees externally-written steps."""
        self._mgr.reload()

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Queue an async save of the state pytree at ``step``."""
        import orbax.checkpoint as ocp

        try:
            return self._mgr.save(
                step, args=ocp.args.StandardSave(state), force=force
            )
        except ocp.checkpoint_manager.StepAlreadyExistsError:
            # A force=True save of a step that is already saved (or still
            # committing): the checkpoint the caller wants IS there —
            # orbax just refuses to overwrite. The eviction-signal path
            # (periodic save then forced save of the same step) hits this
            # whenever the signal lands inside a save interval.
            return False

    def restore(self, step: int | None, target: Any) -> Any:
        """Restore ``step`` (or the latest) into the target's structure.

        ``target`` supplies the pytree structure, dtypes and shardings —
        pass the freshly-initialized (and device_put) TrainState so the
        restored arrays land with the same mesh placement.
        """
        import orbax.checkpoint as ocp

        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array)
            else x,
            target,
        )
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )

    def restore_or_init(
        self, state: Any, min_step: int | None = None
    ) -> tuple[Any, int]:
        """Resume from the latest checkpoint if one exists.

        Returns (state, next_step): the restored state and the step to
        continue from (0 when starting fresh). The kill-and-resume entry
        point used by example workloads under the operator's restart
        policies.

        ``min_step`` is the operator's resume contract (TPU_RESUME_STEP):
        the step it knows was acked durable. If the manager's cached step
        list shows less — the FOLLOWER caveat: orbax caches the step list,
        and a directory another process (the evicted predecessor) wrote
        into is invisible until reload() — the directory is re-read before
        giving up, so a replacement pod never resumes below the acked step
        that is actually on disk.
        """
        step = self.latest_step()
        if min_step is not None and (step is None or step < min_step):
            self.reload()
            step = self.latest_step()
        if step is None:
            return state, 0
        return self.restore(step, state), int(step) + 1

    def wait(self) -> None:
        """Block until queued async saves are durable."""
        self._mgr.wait_until_finished()

    def ack(self) -> int | None:
        """Durably ack the newest checkpoint: drain pending async saves,
        then write the ack file (no-op without one configured). Returns
        the acked step. This is what an eviction-signal handler calls
        after its forced save — the operator's barrier releases on it.

        Always REWRITES the file, even when the step is unchanged: the
        executor's relay treats "the ack file changed after the signal
        was delivered" as the ack, and a just-drained writer proving an
        existing step durable is exactly that."""
        self._mgr.wait_until_finished()
        step = self._mgr.latest_step()
        if step is None or not self.ack_path:
            return None
        try:
            ckpt_protocol.write_ack(self.ack_path, int(step), self._dir)
        except OSError:
            return None
        self._last_acked = int(step)
        return int(step)

    def maybe_ack(self) -> int | None:
        """Opportunistic ack of the latest COMMITTED step, without
        draining in-flight saves (orbax finalizes a step atomically, so
        latest_step never names a half-written checkpoint). Call after
        periodic save()s: keeps the operator's progress/staleness view
        fresh at zero synchronization cost."""
        return self._write_ack(self._mgr.latest_step())

    def _write_ack(self, step: int | None) -> int | None:
        if step is None or not self.ack_path or step == self._last_acked:
            return None
        try:
            ckpt_protocol.write_ack(self.ack_path, int(step), self._dir)
        except OSError:
            return None  # ack is observability; never fail the save path
        self._last_acked = int(step)
        return int(step)

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
