"""Checkpoint / resume for train state (orbax-backed).

The reference delegates checkpointing entirely to user code — the operator's
contribution is stable pod identity + restart semantics so resume can work
(SURVEY.md §5, `pkg/trainer` keeps names/indices stable across restarts).
This framework keeps that contract AND owns the training stack, so it ships
the checkpoint layer too: orbax writes sharded TrainState pytrees (each host
persists its shards; restore honors the target's NamedShardings, so a
restored state lands pre-sharded on the mesh), and the restart policies of
the operator (ExitCode/OnFailure) compose with ``restore_or_init`` to give
kill-and-resume training out of the box — exercised end-to-end by the
preemption-recovery example tests.
"""

from __future__ import annotations

import os
from typing import Any

import jax


class CheckpointManager:
    """Thin orbax CheckpointManager wrapper bound to one train state shape.

    save() is async (orbax background thread); close() drains pending
    writes. Directory layout is orbax-standard: {dir}/{step}/...
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
    ) -> None:
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
            ),
        )

    @property
    def directory(self) -> str:
        return self._dir

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def reload(self) -> None:
        """Re-read the directory: orbax caches the step list, so a FOLLOWER
        process (e.g. an evaluator polling a trainer's checkpoints) must
        reload before latest_step/restore sees externally-written steps."""
        self._mgr.reload()

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Queue an async save of the state pytree at ``step``."""
        import orbax.checkpoint as ocp

        return self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )

    def restore(self, step: int | None, target: Any) -> Any:
        """Restore ``step`` (or the latest) into the target's structure.

        ``target`` supplies the pytree structure, dtypes and shardings —
        pass the freshly-initialized (and device_put) TrainState so the
        restored arrays land with the same mesh placement.
        """
        import orbax.checkpoint as ocp

        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array)
            else x,
            target,
        )
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )

    def restore_or_init(self, state: Any) -> tuple[Any, int]:
        """Resume from the latest checkpoint if one exists.

        Returns (state, next_step): the restored state and the step to
        continue from (0 when starting fresh). The kill-and-resume entry
        point used by example workloads under the operator's restart
        policies.
        """
        step = self.latest_step()
        if step is None:
            return state, 0
        return self.restore(step, state), int(step) + 1

    def wait(self) -> None:
        """Block until queued async saves are durable."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
