"""Synthetic data pipelines (deterministic, learnable).

The zero-egress analog of the reference's sample datasets: labels derive from
a fixed random projection of the inputs, so models measurably learn (loss
decreases, accuracy rises) without downloading anything. Batches are yielded
host-side as numpy and device_put with batch sharding by the caller.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def synthetic_mnist(
    batch_size: int, seed: int = 0, flat: bool = False, noise: float = 1.0
) -> Iterator[dict[str, np.ndarray]]:
    """28x28x1 images drawn as class-template + gaussian noise: a learnable
    10-way classification task (digit-like class-conditional structure)."""
    rng = np.random.default_rng(seed)
    templates = (
        np.random.default_rng(1234).normal(size=(10, 28, 28, 1)).astype(np.float32)
    )
    while True:
        y = rng.integers(0, 10, size=(batch_size,)).astype(np.int32)
        x = templates[y] + noise * rng.normal(size=(batch_size, 28, 28, 1)).astype(
            np.float32
        )
        yield {"image": x.reshape(batch_size, -1) if flat else x, "label": y}


def synthetic_imagenet(
    batch_size: int, image_size: int = 224, num_classes: int = 1000, seed: int = 0
) -> Iterator[dict[str, np.ndarray]]:
    """ImageNet-shaped batches for the ResNet-50 benchmark path."""
    rng = np.random.default_rng(seed)
    while True:
        x = rng.normal(size=(batch_size, image_size, image_size, 3)).astype(np.float32)
        y = rng.integers(0, num_classes, size=(batch_size,)).astype(np.int32)
        yield {"image": x, "label": y}


def synthetic_tokens(
    batch_size: int, seq_len: int, vocab_size: int = 32000, seed: int = 0
) -> Iterator[dict[str, np.ndarray]]:
    """Token streams with next-token structure (shifted-window markov-ish)."""
    rng = np.random.default_rng(seed)
    while True:
        base = rng.integers(0, vocab_size, size=(batch_size, seq_len + 1))
        yield {
            "tokens": base[:, :-1].astype(np.int32),
            "targets": base[:, 1:].astype(np.int32),
        }
