"""Synthetic data pipelines (deterministic, learnable).

The zero-egress analog of the reference's sample datasets: labels derive from
a fixed random projection of the inputs, so models measurably learn (loss
decreases, accuracy rises) without downloading anything. Batches are yielded
host-side as numpy and device_put with batch sharding by the caller.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def synthetic_mnist(
    batch_size: int, seed: int = 0, flat: bool = False, noise: float = 1.0
) -> Iterator[dict[str, np.ndarray]]:
    """28x28x1 images drawn as class-template + gaussian noise: a learnable
    10-way classification task (digit-like class-conditional structure)."""
    rng = np.random.default_rng(seed)
    templates = (
        np.random.default_rng(1234).normal(size=(10, 28, 28, 1)).astype(np.float32)
    )
    while True:
        y = rng.integers(0, 10, size=(batch_size,)).astype(np.int32)
        x = templates[y] + noise * rng.normal(size=(batch_size, 28, 28, 1)).astype(
            np.float32
        )
        yield {"image": x.reshape(batch_size, -1) if flat else x, "label": y}


def synthetic_imagenet(
    batch_size: int, image_size: int = 224, num_classes: int = 1000, seed: int = 0
) -> Iterator[dict[str, np.ndarray]]:
    """ImageNet-shaped batches for the ResNet-50 benchmark path."""
    rng = np.random.default_rng(seed)
    while True:
        x = rng.normal(size=(batch_size, image_size, image_size, 3)).astype(np.float32)
        y = rng.integers(0, num_classes, size=(batch_size,)).astype(np.int32)
        yield {"image": x, "label": y}


def synthetic_tokens(
    batch_size: int, seq_len: int, vocab_size: int = 32000, seed: int = 0
) -> Iterator[dict[str, np.ndarray]]:
    """Token streams with next-token structure (shifted-window markov-ish)."""
    rng = np.random.default_rng(seed)
    while True:
        base = rng.integers(0, vocab_size, size=(batch_size, seq_len + 1))
        yield {
            "tokens": base[:, :-1].astype(np.int32),
            "targets": base[:, 1:].astype(np.int32),
        }


def record_dataset(
    path: str,
    example_shape: tuple[int, ...],
    dtype: np.dtype,
    batch_size: int,
    *,
    label_dtype: np.dtype | None = np.dtype(np.int32),
    seed: int = 0,
    shuffle: bool = True,
    loop: bool = True,
    prefetch: int = 4,
    threads: int = 2,
    engine: str = "auto",
    crop_hw: tuple[int, int] | None = None,
    augment_train: bool = True,
    shard_id: int = 0,
    num_shards: int = 1,
) -> Iterator[dict[str, np.ndarray]]:
    """Stream {image, label} batches from a binary record file.

    The file layout is one fixed-size record per example: the feature bytes
    (example_shape x dtype) immediately followed by the label
    (label_dtype; omit by passing label_dtype=None). IO, shuffling and
    prefetch run in the native C++ pipeline when available
    (native/record_pipeline.cc) — off the GIL, so the accelerator never
    waits on Python — with a semantics-identical Python fallback.

    crop_hw: for uint8 [H, W, C] examples, crop each image to this size via
    the augment stage (random crop + hflip while augment_train, else center
    crop) — ImageNet-style host preprocessing; ``engine`` selects the
    native/python implementation for the augment stage and the record
    pipeline alike. ``engine="mmap"`` selects the zero-copy tier for
    page-cache-resident files: the file is mmap'd and images are gathered
    (and cropped) straight out of the mapping — ~5x the pread pipeline on
    a single-core host at ImageNet shapes (docs/perf.md) — with the
    IDENTICAL sample stream (same epoch order, same augment decisions).

    shard_id/num_shards: multi-host input sharding (one disjoint slice of
    every epoch per host — see RecordPipeline).
    """
    dtype = np.dtype(dtype)
    if crop_hw is not None and (dtype != np.uint8 or len(example_shape) != 3):
        # Validate at the call site, not on first next(): the misconfigured
        # call is where the fix belongs.
        raise ValueError(
            f"crop_hw needs uint8 [H,W,C] examples, got {dtype} {example_shape}"
        )
    if engine == "mmap":
        return _mmap_batches(
            path, example_shape, dtype, batch_size, label_dtype, seed,
            shuffle, loop, crop_hw, augment_train, threads,
            shard_id, num_shards,
        )
    return _record_batches(
        path, example_shape, dtype, batch_size, label_dtype, seed, shuffle,
        loop, prefetch, threads, engine, crop_hw, augment_train,
        shard_id, num_shards,
    )


def _mmap_batches(
    path, example_shape, dtype, batch_size, label_dtype, seed, shuffle,
    loop, crop_hw, augment_train, threads, shard_id, num_shards,
) -> Iterator[dict[str, np.ndarray]]:
    from tf_operator_tpu.native.augment import augment_gather
    from tf_operator_tpu.native.pipeline import MMapRecordPipeline

    feat_bytes = int(np.prod(example_shape)) * dtype.itemsize
    rec_bytes = feat_bytes + (
        np.dtype(label_dtype).itemsize if label_dtype is not None else 0
    )
    pipe = MMapRecordPipeline(
        path, rec_bytes, batch_size, seed=seed, shuffle=shuffle, loop=loop,
        shard_id=shard_id, num_shards=num_shards,
    )
    table = np.asarray(pipe.data).reshape(pipe.num_records, rec_bytes)
    sample_index = 0
    try:
        while True:
            idx = pipe.next_indices()
            if idx is None:
                return
            if crop_hw is not None:
                feats = augment_gather(
                    pipe.data, idx, rec_bytes, example_shape, crop_hw,
                    seed=seed, index0=sample_index, train=augment_train,
                    threads=threads,
                )
                sample_index += len(idx)
            else:
                feats = (
                    table[idx, :feat_bytes]
                    .view(dtype)
                    .reshape(len(idx), *example_shape)
                )
            out = {"image": feats}
            if label_dtype is not None:
                out["label"] = (
                    table[idx, feat_bytes:]
                    .view(np.dtype(label_dtype))
                    .reshape(len(idx))
                )
            yield out
    finally:
        pipe.close()


def _record_batches(
    path, example_shape, dtype, batch_size, label_dtype, seed, shuffle,
    loop, prefetch, threads, engine, crop_hw, augment_train,
    shard_id, num_shards,
) -> Iterator[dict[str, np.ndarray]]:
    from tf_operator_tpu.native.pipeline import RecordPipeline

    if label_dtype is not None:
        label_dtype = np.dtype(label_dtype)
    feat_bytes = int(np.prod(example_shape)) * dtype.itemsize
    rec_bytes = feat_bytes + (
        label_dtype.itemsize if label_dtype is not None else 0
    )
    if crop_hw is not None:
        from tf_operator_tpu.native.augment import augment_records

    pipe = RecordPipeline(
        path, rec_bytes, batch_size, prefetch=prefetch, threads=threads,
        seed=seed, shuffle=shuffle, loop=loop, engine=engine,
        shard_id=shard_id, num_shards=num_shards,
    )
    sample_index = 0
    try:
        for raw in pipe:
            if crop_hw is not None:
                # Strided path: the crop reads image bytes straight out of
                # the raw record rows — no whole-batch slice-and-copy
                # between the loader and the augmenter (record_dataset
                # guarantees uint8 [H,W,C] when crop_hw is set).
                feats = augment_records(
                    raw, example_shape, crop_hw, seed=seed,
                    index0=sample_index, train=augment_train,
                    threads=threads, engine=engine,
                )
                sample_index += len(feats)
            else:
                feats = (
                    raw[:, :feat_bytes]
                    .copy()
                    .view(dtype)
                    .reshape(len(raw), *example_shape)
                )
            out = {"image": feats}
            if label_dtype is not None:
                out["label"] = (
                    raw[:, feat_bytes:].copy().view(label_dtype).reshape(len(raw))
                )
            yield out
    finally:
        pipe.close()


def token_dataset(
    path: str,
    seq_len: int,
    batch_size: int,
    *,
    seed: int = 0,
    shuffle: bool = True,
    loop: bool = True,
    prefetch: int = 4,
    threads: int = 2,
    engine: str = "auto",
    shard_id: int = 0,
    num_shards: int = 1,
) -> Iterator[dict[str, np.ndarray]]:
    """Stream {tokens, targets} LM batches from a binary token-record file.

    Layout: one fixed-size record per training sequence — (seq_len + 1)
    int32 token ids; tokens = rec[:-1], targets = rec[1:] (next-token
    objective). IO, shuffling and prefetch ride the same native C++
    pipeline as the image path (native/record_pipeline.cc), so the LM
    input side is also off the GIL. Multi-host: pass each process its
    topology slot (shard_id=process_id, num_shards=num_processes) and
    every epoch is dealt disjointly across hosts from ONE shared file.
    """
    base = record_dataset(
        path, (seq_len + 1,), np.int32, batch_size, label_dtype=None,
        seed=seed, shuffle=shuffle, loop=loop, prefetch=prefetch,
        threads=threads, engine=engine, shard_id=shard_id,
        num_shards=num_shards,
    )

    def gen() -> Iterator[dict[str, np.ndarray]]:
        for batch in base:  # record_dataset owns the pipeline lifecycle
            seqs = batch["image"]
            yield {"tokens": seqs[:, :-1], "targets": seqs[:, 1:]}

    return gen()


def write_token_records(path: str, seqs: np.ndarray) -> int:
    """Write [N, seq_len+1] int32 token sequences as the records
    token_dataset reads. Returns the record size in bytes."""
    seqs = np.ascontiguousarray(seqs, dtype=np.int32)
    if seqs.ndim != 2:
        raise ValueError(f"expected [N, seq_len+1] tokens, got {seqs.shape}")
    return write_example_records(path, seqs)


def write_example_records(
    path: str, features: np.ndarray, labels: np.ndarray | None = None
) -> int:
    """Write features (+ labels) as the fixed-size records record_dataset
    reads. Returns the record size in bytes."""
    from tf_operator_tpu.native.pipeline import write_records

    n = len(features)
    feats = np.ascontiguousarray(features).reshape(n, -1)
    rows = feats.view(np.uint8).reshape(n, -1)
    if labels is not None:
        lab = np.ascontiguousarray(labels).reshape(n, -1)
        rows = np.concatenate([rows, lab.view(np.uint8).reshape(n, -1)], axis=1)
    write_records(path, rows)
    return rows.shape[1]
