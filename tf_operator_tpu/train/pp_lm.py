"""Pipeline-parallel transformer LM training over the block stack
(GPipe and 1F1B schedules).

Completes the parallelism matrix at the model level: dp/sp/tp/ep run
through the Transformer directly (models/transformer.py), and pipeline
parallelism previously existed only for generic homogeneous stages
(parallel/pipeline.py). Here the transformer's own block stack becomes
the pipeline:

- embed + positions run OUTSIDE the pipeline (cheap, O(B*T*d), GSPMD
  dp-sharded), as does the final norm + chunked-xent head — so the
  pipelined stages are perfectly homogeneous (pp stages x k blocks each),
  which is what `stack_stage_params` / `pipeline_apply` require.
- each stage applies its k blocks with a `lax.scan` over stacked block
  params; activations hop stages via ppermute inside shard_map
  (pipeline.py's schedule), composing with dp on the microbatch dim.
- the backward is autodiff through scan + ppermute — the reverse
  pipeline schedule for free, grads summed over dp by shard_map
  (schedule="gpipe") — or the explicit interleaved 1F1B engine
  (schedule="1f1b", parallel/pipeline.py:pipeline_value_and_grad) whose
  activation stash is O(pp) instead of O(num_micro), so the bubble
  (pp-1)/num_micro can be shrunk by raising num_micro without raising
  memory.

The reference has no model parallelism at all (SURVEY.md §2.9); this is
TPU-native capability on top of parity. Exercised multi-process by
`__graft_entry__.dryrun_multichip` (pp path) and pinned against the
plain Transformer forward in tests/test_moe_pipeline.py.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from tf_operator_tpu.models.transformer import Block, TransformerConfig
from tf_operator_tpu.parallel.pipeline import (
    microbatch,
    pipeline_apply,
    pipeline_value_and_grad,
    stack_stage_params,
    unmicrobatch,
)
from tf_operator_tpu.train.steps import chunked_lm_xent

OUTER_KEYS = ("embed", "pos", "RMSNorm_0", "lm_head")


def split_pp_params(params: Any, n_layers: int, pp: int) -> tuple[Any, Any]:
    """Standard Transformer param tree -> (outer, stages).

    outer: embed/pos/final-norm/head subtrees, unchanged.
    stages: block params stacked to leaves [pp, k, ...] (stage-major,
    layer order preserved: stage s holds blocks s*k .. s*k+k-1).
    """
    if n_layers % pp:
        raise ValueError(f"n_layers={n_layers} not divisible by pp={pp}")
    k = n_layers // pp
    missing = [f"block_{i}" for i in range(n_layers) if f"block_{i}" not in params]
    if missing:
        raise ValueError(f"params missing {missing}")
    outer = {key: params[key] for key in OUTER_KEYS}
    stage_trees = []
    for s in range(pp):
        blocks = [params[f"block_{s * k + j}"] for j in range(k)]
        stage_trees.append(jax.tree.map(lambda *xs: jnp.stack(xs), *blocks))
    return outer, stack_stage_params(stage_trees)


def merge_pp_params(outer: Any, stages: Any, n_layers: int) -> Any:
    """(outer, stages) -> the standard Transformer tree (for checkpoints
    / serving / decode interop)."""
    leaves = jax.tree.leaves(stages)
    pp = leaves[0].shape[0] if leaves else 1
    k = n_layers // pp
    params = dict(outer)
    for s in range(pp):
        stage = jax.tree.map(lambda a, s=s: a[s], stages)
        for j in range(k):
            params[f"block_{s * k + j}"] = jax.tree.map(
                lambda a, j=j: a[j], stage
            )
    return params


def _stage_cfg(cfg: TransformerConfig) -> TransformerConfig:
    # Inside shard_map each stage is single-device code: the Block must
    # take the plain attention path (no nested mesh logic). remat is
    # applied by make_pp_lm_forward around each block apply (the
    # Transformer-level nn.remat wrapper never runs on this path).
    return replace(cfg, mesh=None, remat=False)


def _make_stage_fn(cfg: TransformerConfig):
    """One pipeline stage: this stage's k blocks applied in order (leaves
    [k, ...]); remat per block when the model asks for it."""
    block = Block(_stage_cfg(cfg))

    def apply_block(block_p, x):
        return block.apply({"params": block_p}, x)

    if cfg.remat:
        # Honor the model's remat request on the pipelined path too: each
        # block's activations are recomputed in the backward instead of
        # stored through the scan (cfg.remat would otherwise be silently
        # dropped — the stage cfg disables the Transformer-level wrapper).
        apply_block = jax.checkpoint(apply_block)

    def stage_fn(p_stage, x):
        def body(x, block_p):
            return apply_block(block_p, x), None

        out, _ = jax.lax.scan(body, x, p_stage)
        return out

    return stage_fn


def make_pp_lm_forward(
    cfg: TransformerConfig,
    mesh: Mesh,
    *,
    num_micro: int,
    pp_axis: str = "pp",
    batch_axis: str | None = "dp",
    xent_chunk: int | None = None,
):
    """Returns loss_fn((outer, stages), tokens, targets) -> scalar loss.

    The full pipelined forward + chunked-xent loss, differentiable in
    both param trees (GPipe: autodiff through the schedule).
    """
    data_axis = (
        batch_axis if batch_axis and mesh.shape.get(batch_axis, 1) > 1
        else None
    )
    stage_fn = _make_stage_fn(cfg)

    def loss_fn(pp_params, tokens, targets):
        outer, stages = pp_params["outer"], pp_params["stages"]
        B, T = tokens.shape
        x = jnp.take(
            outer["embed"]["embedding"], tokens, axis=0
        ).astype(cfg.dtype)
        pos = outer["pos"]["embedding"][jnp.arange(T)][None, :, :]
        x = x + pos.astype(cfg.dtype)
        out = pipeline_apply(
            stage_fn, stages, microbatch(x, num_micro), mesh,
            axis=pp_axis, batch_axis=data_axis,
        )
        y = unmicrobatch(out)
        y = nn.RMSNorm(dtype=cfg.dtype).apply(
            {"params": outer["RMSNorm_0"]}, y
        )
        head = outer["lm_head"]
        return chunked_lm_xent(
            y, head["kernel"], head["bias"], targets,
            chunk=xent_chunk or min(512, T),
        )

    return loss_fn


def pp_param_shardings(mesh: Mesh, pp_params: Any,
                       pp_axis: str = "pp") -> Any:
    """Placement tree: stage params sharded over ``pp_axis`` on the stage
    dim, outer params replicated."""
    return {
        "outer": jax.tree.map(
            lambda _: NamedSharding(mesh, P()), pp_params["outer"]
        ),
        "stages": jax.tree.map(
            lambda _: NamedSharding(mesh, P(pp_axis)), pp_params["stages"]
        ),
    }


def place_pp_state(mesh: Mesh, state: Any) -> Any:
    """Pin every leaf of a TrainState to the mesh: leaves already carried
    by a NamedSharding (params placed by ``pp_param_shardings``, optimizer
    moments inheriting them via ``tx.init``) keep their placement; the
    rest (step counter, optax count scalars — uncommitted by default) are
    replicated. Without this, a checkpoint restore commits the scalars to
    one device while the params live on the mesh, and the next jitted
    step rejects the mixed device sets."""
    repl = NamedSharding(mesh, P())
    return jax.tree.map(
        lambda x: x
        if isinstance(getattr(x, "sharding", None), NamedSharding)
        else jax.device_put(x, repl),
        state,
    )


def make_pp_lm_train_step(
    cfg: TransformerConfig,
    mesh: Mesh,
    tx,
    *,
    num_micro: int,
    pp_axis: str = "pp",
    batch_axis: str | None = "dp",
    xent_chunk: int | None = None,
    schedule: str = "gpipe",
):
    """Jitted (state, batch) -> (state, metrics) for the pipelined LM.

    ``state.params`` is {"outer": ..., "stages": ...} (build with
    ``split_pp_params``; place with ``pp_param_shardings``).

    schedule:
      "gpipe" — autodiff through ``pipeline_apply``: all forwards, then
        all backwards; the scan stores O(num_micro) activations/stage.
      "1f1b"  — ``pipeline_value_and_grad``: interleaved schedule with an
        O(pp) activation stash, so num_micro can grow (shrinking the
        (pp-1)/num_micro bubble) without growing memory. Bit-identical
        losses and numerically identical grads (pinned in
        tests/test_moe_pipeline.py).
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"schedule {schedule!r}: want 'gpipe' or '1f1b'")

    import optax

    if schedule == "gpipe":
        loss_fn = make_pp_lm_forward(
            cfg, mesh, num_micro=num_micro, pp_axis=pp_axis,
            batch_axis=batch_axis, xent_chunk=xent_chunk,
        )

        def step(state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                state.params, batch["tokens"], batch["targets"]
            )
            updates, opt_state = tx.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            new_state = state.replace(
                step=state.step + 1, params=params, opt_state=opt_state
            )
            return new_state, {"loss": loss}

        return jax.jit(step)

    # --- 1f1b: explicit fwd/bwd interleave; embed vjp'd outside, the
    # norm+head+xent ("last_fn") inside the schedule on the last stage ---
    data_axis = (
        batch_axis if batch_axis and mesh.shape.get(batch_axis, 1) > 1
        else None
    )
    stage_fn = _make_stage_fn(cfg)
    norm = nn.RMSNorm(dtype=cfg.dtype)

    def last_fn(last_p, y, tgt):
        y = norm.apply({"params": last_p["norm"]}, y)
        head = last_p["head"]
        return chunked_lm_xent(
            y, head["kernel"], head["bias"], tgt,
            chunk=xent_chunk or min(512, y.shape[-2]),
        )

    engine = pipeline_value_and_grad(
        stage_fn, last_fn, mesh, axis=pp_axis, batch_axis=data_axis,
    )

    def step(state, batch):
        outer, stages = state.params["outer"], state.params["stages"]
        tokens, targets = batch["tokens"], batch["targets"]
        T = tokens.shape[1]

        def embed_fn(emb_p):
            x = jnp.take(
                emb_p["embed"]["embedding"], tokens, axis=0
            ).astype(cfg.dtype)
            pos = emb_p["pos"]["embedding"][jnp.arange(T)][None, :, :]
            return microbatch(x + pos.astype(cfg.dtype), num_micro)

        emb_p = {"embed": outer["embed"], "pos": outer["pos"]}
        x_mb, embed_vjp = jax.vjp(embed_fn, emb_p)
        last_p = {"norm": outer["RMSNorm_0"], "head": outer["lm_head"]}
        loss, d_stages, d_last, dx = engine(
            stages, last_p, x_mb, microbatch(targets, num_micro)
        )
        (d_emb,) = embed_vjp(dx.astype(x_mb.dtype))
        grads = {
            "outer": {
                "embed": d_emb["embed"],
                "pos": d_emb["pos"],
                "RMSNorm_0": d_last["norm"],
                "lm_head": d_last["head"],
            },
            "stages": d_stages,
        }
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1, params=params, opt_state=opt_state
        )
        return new_state, {"loss": loss}

    return jax.jit(step)
