"""Pipeline-parallel transformer LM training (GPipe over the block stack).

Completes the parallelism matrix at the model level: dp/sp/tp/ep run
through the Transformer directly (models/transformer.py), and pipeline
parallelism previously existed only for generic homogeneous stages
(parallel/pipeline.py). Here the transformer's own block stack becomes
the pipeline:

- embed + positions run OUTSIDE the pipeline (cheap, O(B*T*d), GSPMD
  dp-sharded), as does the final norm + chunked-xent head — so the
  pipelined stages are perfectly homogeneous (pp stages x k blocks each),
  which is what `stack_stage_params` / `pipeline_apply` require.
- each stage applies its k blocks with a `lax.scan` over stacked block
  params; activations hop stages via ppermute inside shard_map
  (pipeline.py's schedule), composing with dp on the microbatch dim.
- the backward is autodiff through scan + ppermute — the reverse
  pipeline schedule for free, grads summed over dp by shard_map.

The reference has no model parallelism at all (SURVEY.md §2.9); this is
TPU-native capability on top of parity. Exercised multi-process by
`__graft_entry__.dryrun_multichip` (pp path) and pinned against the
plain Transformer forward in tests/test_moe_pipeline.py.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from tf_operator_tpu.models.transformer import Block, TransformerConfig
from tf_operator_tpu.parallel.pipeline import (
    microbatch,
    pipeline_apply,
    stack_stage_params,
    unmicrobatch,
)
from tf_operator_tpu.train.steps import chunked_lm_xent

OUTER_KEYS = ("embed", "pos", "RMSNorm_0", "lm_head")


def split_pp_params(params: Any, n_layers: int, pp: int) -> tuple[Any, Any]:
    """Standard Transformer param tree -> (outer, stages).

    outer: embed/pos/final-norm/head subtrees, unchanged.
    stages: block params stacked to leaves [pp, k, ...] (stage-major,
    layer order preserved: stage s holds blocks s*k .. s*k+k-1).
    """
    if n_layers % pp:
        raise ValueError(f"n_layers={n_layers} not divisible by pp={pp}")
    k = n_layers // pp
    missing = [f"block_{i}" for i in range(n_layers) if f"block_{i}" not in params]
    if missing:
        raise ValueError(f"params missing {missing}")
    outer = {key: params[key] for key in OUTER_KEYS}
    stage_trees = []
    for s in range(pp):
        blocks = [params[f"block_{s * k + j}"] for j in range(k)]
        stage_trees.append(jax.tree.map(lambda *xs: jnp.stack(xs), *blocks))
    return outer, stack_stage_params(stage_trees)


def merge_pp_params(outer: Any, stages: Any, n_layers: int) -> Any:
    """(outer, stages) -> the standard Transformer tree (for checkpoints
    / serving / decode interop)."""
    leaves = jax.tree.leaves(stages)
    pp = leaves[0].shape[0] if leaves else 1
    k = n_layers // pp
    params = dict(outer)
    for s in range(pp):
        stage = jax.tree.map(lambda a, s=s: a[s], stages)
        for j in range(k):
            params[f"block_{s * k + j}"] = jax.tree.map(
                lambda a, j=j: a[j], stage
            )
    return params


def _stage_cfg(cfg: TransformerConfig) -> TransformerConfig:
    # Inside shard_map each stage is single-device code: the Block must
    # take the plain attention path (no nested mesh logic). remat is
    # applied by make_pp_lm_forward around each block apply (the
    # Transformer-level nn.remat wrapper never runs on this path).
    return replace(cfg, mesh=None, remat=False)


def make_pp_lm_forward(
    cfg: TransformerConfig,
    mesh: Mesh,
    *,
    num_micro: int,
    pp_axis: str = "pp",
    batch_axis: str | None = "dp",
    xent_chunk: int | None = None,
):
    """Returns loss_fn((outer, stages), tokens, targets) -> scalar loss.

    The full pipelined forward + chunked-xent loss, differentiable in
    both param trees.
    """
    scfg = _stage_cfg(cfg)
    block = Block(scfg)
    data_axis = (
        batch_axis if batch_axis and mesh.shape.get(batch_axis, 1) > 1
        else None
    )

    def apply_block(block_p, x):
        return block.apply({"params": block_p}, x)

    if cfg.remat:
        # Honor the model's remat request on the pipelined path too: each
        # block's activations are recomputed in the backward instead of
        # stored through the scan (cfg.remat would otherwise be silently
        # dropped — the stage cfg disables the Transformer-level wrapper).
        apply_block = jax.checkpoint(apply_block)

    def stage_fn(p_stage, x):
        # p_stage leaves: [k, ...] — this stage's blocks, applied in order.
        def body(x, block_p):
            return apply_block(block_p, x), None

        out, _ = jax.lax.scan(body, x, p_stage)
        return out

    def loss_fn(pp_params, tokens, targets):
        outer, stages = pp_params["outer"], pp_params["stages"]
        B, T = tokens.shape
        x = jnp.take(
            outer["embed"]["embedding"], tokens, axis=0
        ).astype(cfg.dtype)
        pos = outer["pos"]["embedding"][jnp.arange(T)][None, :, :]
        x = x + pos.astype(cfg.dtype)
        out = pipeline_apply(
            stage_fn, stages, microbatch(x, num_micro), mesh,
            axis=pp_axis, batch_axis=data_axis,
        )
        y = unmicrobatch(out)
        y = nn.RMSNorm(dtype=cfg.dtype).apply(
            {"params": outer["RMSNorm_0"]}, y
        )
        head = outer["lm_head"]
        return chunked_lm_xent(
            y, head["kernel"], head["bias"], targets,
            chunk=xent_chunk or min(512, T),
        )

    return loss_fn


def pp_param_shardings(mesh: Mesh, pp_params: Any,
                       pp_axis: str = "pp") -> Any:
    """Placement tree: stage params sharded over ``pp_axis`` on the stage
    dim, outer params replicated."""
    return {
        "outer": jax.tree.map(
            lambda _: NamedSharding(mesh, P()), pp_params["outer"]
        ),
        "stages": jax.tree.map(
            lambda _: NamedSharding(mesh, P(pp_axis)), pp_params["stages"]
        ),
    }


def place_pp_state(mesh: Mesh, state: Any) -> Any:
    """Pin every leaf of a TrainState to the mesh: leaves already carried
    by a NamedSharding (params placed by ``pp_param_shardings``, optimizer
    moments inheriting them via ``tx.init``) keep their placement; the
    rest (step counter, optax count scalars — uncommitted by default) are
    replicated. Without this, a checkpoint restore commits the scalars to
    one device while the params live on the mesh, and the next jitted
    step rejects the mixed device sets."""
    repl = NamedSharding(mesh, P())
    return jax.tree.map(
        lambda x: x
        if isinstance(getattr(x, "sharding", None), NamedSharding)
        else jax.device_put(x, repl),
        state,
    )


def make_pp_lm_train_step(
    cfg: TransformerConfig,
    mesh: Mesh,
    tx,
    *,
    num_micro: int,
    pp_axis: str = "pp",
    batch_axis: str | None = "dp",
    xent_chunk: int | None = None,
):
    """Jitted (state, batch) -> (state, metrics) for the pipelined LM.

    ``state.params`` is {"outer": ..., "stages": ...} (build with
    ``split_pp_params``; place with ``pp_param_shardings``).
    """
    loss_fn = make_pp_lm_forward(
        cfg, mesh, num_micro=num_micro, pp_axis=pp_axis,
        batch_axis=batch_axis, xent_chunk=xent_chunk,
    )

    import optax

    def step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, batch["tokens"], batch["targets"]
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1, params=params, opt_state=opt_state
        )
        return new_state, {"loss": loss}

    return jax.jit(step)
