"""Jitted SPMD training steps.

The TPU-native replacement for the reference's PS/Worker execution model:
one jitted train step over a Mesh, parameters replicated (dp) or sharded
(fsdp/tp), batch sharded over dp — XLA inserts the gradient all-reduces that
a PS round-trip performed in the reference's world. Everything is a pure
function of (state, batch): no Python control flow under jit, static shapes.
"""

from __future__ import annotations

import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from tf_operator_tpu import parallel as parallel_compat


def _data_axis_sharding(mesh: Mesh, data_axis: Any) -> tuple[NamedSharding, int]:
    """(batch NamedSharding, shard count) for a str-or-tuple data axis,
    with axes absent from the mesh treated as unsharded — the shared
    absent-axis contract of the train/eval step builders (NamedSharding
    rejects unknown axis names)."""
    axes = tuple(
        a
        for a in ((data_axis,) if isinstance(data_axis, str) else tuple(data_axis))
        if a in mesh.axis_names
    )
    spec_axes = axes if len(axes) != 1 else axes[0]
    sharding = NamedSharding(mesh, P(spec_axes) if axes else P())
    return sharding, math.prod(mesh.shape[a] for a in axes) if axes else 1


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    batch_stats: Any = None  # BatchNorm models only

    @classmethod
    def create(cls, params, tx, batch_stats=None):
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            batch_stats=batch_stats,
        )


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def _head_logits(h, kernel, bias, dot_dtype):
    """One chunk's f32 logits; dot_dtype (e.g. bf16) runs the matmul at that
    dtype's MXU rate with f32 accumulation. Shared by both chunked losses so
    their exactness-critical numerics cannot drift apart."""
    if dot_dtype is not None:
        logits = jnp.dot(
            h.astype(dot_dtype), kernel.astype(dot_dtype),
            preferred_element_type=jnp.float32,
        )
    else:
        logits = h.astype(jnp.float32) @ kernel.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    return logits


def chunked_lm_xent(
    hidden: jax.Array,
    kernel: jax.Array,
    bias: jax.Array | None,
    labels: jax.Array,
    *,
    chunk: int = 512,
    dot_dtype: Any = None,
) -> jax.Array:
    """Exact mean softmax cross-entropy WITHOUT materializing [B,S,V] logits.

    The LM head's f32 logits are the memory peak of long-context training:
    at B=2, S=8k, V=32k they are 2.1 GB (and their cotangent doubles it) —
    pure HBM traffic, since the loss only needs logsumexp and one gathered
    logit per position. This computes the loss chunk-by-chunk over the
    sequence inside a rematerialized lax.scan: peak logits memory drops to
    O(B*chunk*V) and the backward pass recomputes each chunk's logits
    (one extra [B*chunk,D]x[D,V] matmul — FLOPs the MXU has to spare when
    the bottleneck is HBM). Numerics match the naive loss to f32 tolerance
    (tests/test_training.py::test_chunked_xent_matches_naive, incl. grads).

    ``dot_dtype=jnp.bfloat16`` runs the head matmul at the MXU's bf16 rate
    with f32 accumulation (preferred_element_type) — logsumexp/gather stay
    f32. A dense f32 head matmul runs at a fraction of bf16 peak, so on a
    32k vocab this is the difference between the head being free and the
    head dominating the step.
    """
    b, s, d = hidden.shape
    if s % chunk:
        raise ValueError(f"seq {s} not divisible by xent chunk {chunk}")
    n = s // chunk
    h = hidden.reshape(b, n, chunk, d).swapaxes(0, 1)  # [n, B, chunk, D]
    lab = labels.reshape(b, n, chunk).swapaxes(0, 1)

    def body(acc, xs):
        hc, lc = xs
        logits = _head_logits(hc, kernel, bias, dot_dtype)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, lc[..., None], axis=-1)[..., 0]
        return acc + (lse - picked).sum(), None

    total, _ = jax.lax.scan(
        jax.checkpoint(body), jnp.zeros((), jnp.float32), (h, lab)
    )
    return total / (b * s)


def sharded_lm_xent(
    mesh: Mesh,
    hidden: jax.Array,
    kernel: jax.Array,
    bias: jax.Array | None,
    labels: jax.Array,
    *,
    chunk: int = 512,
    data_axis: str = "dp",
    seq_axis: str | None = "sp",
    tp_axis: str = "tp",
    dot_dtype: Any = None,
) -> jax.Array:
    """chunked_lm_xent under SPMD sharding: batch over dp, sequence over sp,
    vocab over tp (the lm_head kernel's tp split in param_sharding_rules).

    The distributed form of the chunked loss — each device computes partial
    sums over its local (batch x sequence) tokens and its local vocab shard
    inside a shard_map; the vocab direction uses the Megatron-style
    vocab-parallel reduction (global max via pmax, then log of a psum'd
    sumexp, and the label logit recovered by masking each shard's local
    vocab range and psum'ing). Exact — same value and gradients as the
    naive full-logits loss (tests/test_training.py::test_sharded_xent_*).

    ``chunk`` must divide the PER-DEVICE sequence length (seq / sp).
    Axes absent from the mesh (or passed as None) are treated as unsharded.
    ``data_axis`` may be a tuple (e.g. ("dp", "fsdp")) when the batch is
    sharded over several axes.
    """
    b, s, _ = hidden.shape
    names = mesh.axis_names
    dp_axes = tuple(
        a for a in (
            data_axis if isinstance(data_axis, (tuple, list)) else (data_axis,)
        ) if a in names
    )
    dp = dp_axes if dp_axes else None
    sp = seq_axis if seq_axis in names else None
    tp = tp_axis if tp_axis in names else None
    token_axes = dp_axes + ((sp,) if sp else ())

    def local(h, k, bia, lab):
        lb, ls, d = h.shape
        if ls % chunk:
            raise ValueError(
                f"per-device seq {ls} not divisible by xent chunk {chunk}"
            )
        n = ls // chunk
        hc = h.reshape(lb, n, chunk, d).swapaxes(0, 1)
        lc = lab.reshape(lb, n, chunk).swapaxes(0, 1)
        v_local = k.shape[1]
        v_start = jax.lax.axis_index(tp) * v_local if tp else 0

        def body(acc, xs):
            hx, lx = xs
            logits = _head_logits(hx, k, bia, dot_dtype)
            # Vocab-parallel logsumexp: max must be global before exp. The
            # shift is purely for stability (lse is invariant to it), so a
            # stop_gradient is exact — and it must wrap pmax's INPUT, since
            # pmax has no differentiation rule (a zero-tangent operand keeps
            # AD from ever visiting it).
            lmax = jax.lax.stop_gradient(logits.max(axis=-1))
            gmax = jax.lax.pmax(lmax, tp) if tp else lmax
            sumexp = jnp.exp(logits - gmax[..., None]).sum(axis=-1)
            if tp:
                sumexp = jax.lax.psum(sumexp, tp)
            lse = jnp.log(sumexp) + gmax
            # The label's logit lives on exactly one vocab shard.
            idx = lx - v_start
            in_range = (idx >= 0) & (idx < v_local)
            safe = jnp.clip(idx, 0, v_local - 1)
            val = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
            picked = jnp.where(in_range, val, 0.0)
            if tp:
                picked = jax.lax.psum(picked, tp)
            return acc + (lse - picked).sum(), None

        total, _ = jax.lax.scan(
            jax.checkpoint(body), jnp.zeros((), jnp.float32), (hc, lc)
        )
        return jax.lax.psum(total, token_axes) if token_axes else total

    if bias is None:
        fn, in_specs = (
            lambda h, k, lab: local(h, k, None, lab),
            (P(dp, sp, None), P(None, tp), P(dp, sp)),
        )
        args = (hidden, kernel, labels)
    else:
        fn, in_specs = (
            local,
            (P(dp, sp, None), P(None, tp), P(tp), P(dp, sp)),
        )
        args = (hidden, kernel, bias, labels)
    total = parallel_compat.shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=P(), check_vma=False
    )(*args)
    return total / (b * s)


def chunked_lm_xent_sums(
    hidden: jax.Array,
    kernel: jax.Array,
    bias: jax.Array | None,
    labels: jax.Array,
    mask: jax.Array,
    *,
    chunk: int = 512,
    dot_dtype: Any = None,
) -> tuple[jax.Array, jax.Array]:
    """Masked (loss_sum, token_count) via the chunked scan — the eval-side
    form of chunked_lm_xent: padding rows carry mask 0, counts are exact
    int32, and the [B,S,V] logits never materialize."""
    b, s, d = hidden.shape
    if s % chunk:
        raise ValueError(f"seq {s} not divisible by xent chunk {chunk}")
    n = s // chunk
    h = hidden.reshape(b, n, chunk, d).swapaxes(0, 1)
    lab = labels.reshape(b, n, chunk).swapaxes(0, 1)
    msk = mask.reshape(b, n, chunk).swapaxes(0, 1)

    def body(carry, xs):
        loss_sum, count = carry
        hc, lc, mc = xs
        logits = _head_logits(hc, kernel, bias, dot_dtype)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, lc[..., None], axis=-1)[..., 0]
        loss_sum = loss_sum + ((lse - picked) * mc.astype(jnp.float32)).sum()
        count = count + (mc > 0).astype(jnp.int32).sum()
        return (loss_sum, count), None

    (loss_sum, count), _ = jax.lax.scan(
        body,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        (h, lab, msk),
    )
    return loss_sum, count


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return (logits.argmax(-1) == labels).mean()


def make_classifier_train_step(
    model: Any,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    has_batch_stats: bool = True,
    data_axis: Any = "dp",
    donate: bool = True,
    param_shardings: Any = None,
) -> Callable[[TrainState, dict[str, jax.Array]], tuple[TrainState, dict[str, jax.Array]]]:
    """Train step for image classifiers (ResNet/MNIST): batch sharded over
    the data axis (a mesh axis name or tuple of names, e.g. ("dp", "fsdp")),
    params replicated — or, with ``param_shardings`` (e.g. from
    fsdp_sharding_tree), fully sharded: the caller device_puts params per the
    tree before TrainState.create so optimizer moments inherit the placement,
    the step pins updated params to it, and XLA inserts the fsdp
    all-gather/reduce-scatter collectives."""

    def loss_fn(params, batch_stats, batch):
        variables = {"params": params}
        if has_batch_stats:
            variables["batch_stats"] = batch_stats
            logits, updates = model.apply(
                variables, batch["image"], train=True, mutable=["batch_stats"]
            )
            new_stats = updates["batch_stats"]
        else:
            logits = model.apply(variables, batch["image"], train=True)
            new_stats = batch_stats
        loss = cross_entropy(logits, batch["label"])
        return loss, (new_stats, logits)

    def step(state: TrainState, batch):
        (loss, (new_stats, logits)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, state.batch_stats, batch)
        if param_shardings is not None:
            # Pin grads to the param placement so the gradient collective is
            # a reduce-scatter (grad shards) rather than a full all-reduce.
            grads = jax.lax.with_sharding_constraint(grads, param_shardings)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if param_shardings is not None:
            new_params = jax.lax.with_sharding_constraint(
                new_params, param_shardings
            )
        metrics = {"loss": loss, "accuracy": accuracy(logits, batch["label"])}
        return (
            state.replace(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt,
                batch_stats=new_stats,
            ),
            metrics,
        )

    batch_sharding = {
        "image": NamedSharding(mesh, P(data_axis)),
        "label": NamedSharding(mesh, P(data_axis)),
    }
    if param_shardings is not None:
        # Sharded-state path: placement is inferred from the (already
        # fsdp-placed) state argument; metrics stay replicated by default.
        return jax.jit(
            step,
            in_shardings=(None, batch_sharding),
            donate_argnums=(0,) if donate else (),
        )
    replicated = NamedSharding(mesh, P())
    return jax.jit(
        step,
        in_shardings=(replicated, batch_sharding),
        out_shardings=(replicated, replicated),
        donate_argnums=(0,) if donate else (),
    )


def make_lm_train_step(
    model: Any,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    param_shardings: Any = None,
    opt_shardings: Any = None,
    data_axis: Any = "dp",
    seq_axis: str | None = "sp",
    tp_axis: str = "tp",
    donate: bool = True,
    xent_chunk: int | None = None,
    xent_dot_dtype: Any = None,
    aux_loss_weight: float = 0.0,
    grad_accum: int = 1,
):
    """Train step for the transformer: batch over dp, sequence over sp (ring
    attention inside the model). Params are placed by the caller
    (shard_params_by_rules); optionally pass ``param_shardings`` (a
    NamedSharding pytree matching params, e.g. from sharding_tree_by_rules)
    to pin the tp placement inside the step — updated params are constrained
    to it so drift toward replication is impossible even if the optimizer
    update would otherwise change placement.

    ``xent_chunk`` switches the loss to the chunked cross-entropy (exact,
    but never materializes the [B,S,V] logits — the long-context memory
    peak): chunked_lm_xent on an unsharded mesh, sharded_lm_xent (vocab-
    parallel, sequence-parallel) when the mesh shards sp or tp. The chunk
    must divide the per-device sequence length.

    ``aux_loss_weight`` > 0 collects sown auxiliary losses (the MoE
    load-balancing loss) via mutable=["losses"] and adds them weighted;
    metrics then carry "aux_loss".

    ``opt_shardings`` (weight-update sharding, ZeRO-1 over plain dp)
    constrains the updated optimizer state; when it is set and
    ``param_shardings`` is not, params are pinned REPLICATED — without
    that pin GSPMD would propagate the sharded update into new_params
    (silent FSDP), exactly the drift the technique's contract forbids.

    ``grad_accum`` > 1 splits the batch's leading dim into that many
    microbatches and averages their gradients inside ONE jitted step (a
    lax.scan; one optimizer update) — the peak-activation memory of a
    microbatch buys the global batch the optimizer sees. Exact for the
    per-token-mean LM loss when microbatches are equal-sized (the batch
    dim must divide by grad_accum); the reported loss is the mean over
    microbatches."""

    # seq_axis=None means the caller opted out of sequence sharding: only
    # a tp-split head then forces the sharded (vocab-parallel) loss, and
    # the sequence stays unsharded inside it (sharded_lm_xent treats a
    # missing axis name as unsharded).
    sharded_loss = xent_chunk is not None and any(
        mesh.shape.get(a, 1) > 1
        for a in ((seq_axis, tp_axis) if seq_axis else (tp_axis,))
    )

    def apply_model(params, tokens, **kw):
        if aux_loss_weight:
            from tf_operator_tpu.models.moe import aux_loss_from

            out, col = model.apply(
                {"params": params}, tokens, mutable=["losses"], **kw
            )
            return out, aux_loss_from(col)
        return model.apply({"params": params}, tokens, **kw), jnp.zeros(())

    def loss_fn(params, batch):
        if xent_chunk is not None:
            hidden, aux = apply_model(
                params, batch["tokens"], return_hidden=True
            )
            head = params["lm_head"]
            if sharded_loss:
                xent = sharded_lm_xent(
                    mesh, hidden, head["kernel"], head.get("bias"),
                    batch["targets"], chunk=xent_chunk,
                    data_axis=data_axis, seq_axis=seq_axis,
                    tp_axis=tp_axis, dot_dtype=xent_dot_dtype,
                )
            else:
                xent = chunked_lm_xent(
                    hidden, head["kernel"], head.get("bias"),
                    batch["targets"], chunk=xent_chunk,
                    dot_dtype=xent_dot_dtype,
                )
        else:
            logits, aux = apply_model(params, batch["tokens"])
            xent = cross_entropy(logits, batch["targets"])
        return xent + aux_loss_weight * aux, aux

    if grad_accum < 1:
        raise ValueError(f"grad_accum={grad_accum} must be >= 1")
    # Computed here (also used below for the batch shardings) so the
    # microbatch split can validate against the PER-SHARD batch: a
    # microbatch that cannot tile the data axis would silently reshard at
    # partial utilization, defeating the feature's memory/throughput trade.
    row_sharding, data_size = _data_axis_sharding(mesh, data_axis)

    def grads_of(params, batch):
        if grad_accum == 1:
            return jax.value_and_grad(loss_fn, has_aux=True)(params, batch)

        from tf_operator_tpu.parallel.pipeline import microbatch

        def accum_step(carry, micro):
            loss_sum, aux_sum, grad_sum = carry
            (loss, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(
                params, micro
            )
            return (
                loss_sum + loss,
                aux_sum + aux,
                jax.tree.map(jnp.add, grad_sum, g),
            ), None

        b = batch["tokens"].shape[0]
        if b % grad_accum or (b // grad_accum) % data_size:
            raise ValueError(
                f"batch dim {b} not divisible into grad_accum="
                f"{grad_accum} microbatches that tile the data axis "
                f"(size {data_size})"
            )
        micros = jax.tree.map(lambda x: microbatch(x, grad_accum), batch)
        zero_grads = jax.tree.map(jnp.zeros_like, params)
        (loss_sum, aux_sum, grad_sum), _ = jax.lax.scan(
            accum_step, (jnp.zeros(()), jnp.zeros(()), zero_grads), micros
        )
        inv = 1.0 / grad_accum
        return (
            (loss_sum * inv, aux_sum * inv),
            jax.tree.map(lambda g: g * inv, grad_sum),
        )

    def step(state: TrainState, batch):
        (loss, aux), grads = grads_of(state.params, batch)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if param_shardings is not None:
            new_params = jax.lax.with_sharding_constraint(
                new_params, param_shardings
            )
        elif opt_shardings is not None:
            # Default half of the two-constraint contract (docstring):
            # sharded moments with unpinned params would silently FSDP
            # the params via GSPMD propagation of the sharded update.
            new_params = jax.lax.with_sharding_constraint(
                new_params,
                jax.tree.map(
                    lambda _: jax.sharding.NamedSharding(
                        mesh, jax.sharding.PartitionSpec()
                    ),
                    new_params,
                ),
            )
        if opt_shardings is not None:
            # Weight-update sharding (ZeRO-1 over plain dp): moments live
            # sharded over the data axis while params stay replicated —
            # the constraint stops GSPMD from drifting the moments back
            # to the (dominant) replicated layout of grads/params. See
            # parallel/sharding.py:weight_update_shardings.
            new_opt = jax.lax.with_sharding_constraint(
                new_opt, opt_shardings
            )
        metrics = {"loss": loss}
        if aux_loss_weight:
            metrics["aux_loss"] = aux
        return (
            state.replace(step=state.step + 1, params=new_params, opt_state=new_opt),
            metrics,
        )

    seq = seq_axis if (seq_axis and mesh.shape.get(seq_axis, 1) > 1) else None
    # row_sharding/data_size computed above (shared with the microbatch
    # validation); axes absent from the mesh are treated as unsharded
    # (same contract as sharded_lm_xent) — _data_axis_sharding filters.
    batch_axes = row_sharding.spec[0] if data_size > 1 else None
    tok_spec = P(batch_axes, seq)
    batch_sharding = {
        "tokens": NamedSharding(mesh, tok_spec),
        "targets": NamedSharding(mesh, tok_spec),
    }
    # State shardings are inferred from the placed arguments: the caller
    # device_puts params per the tp rules (shard_params_by_rules) before
    # TrainState.create, and optimizer moments inherit those placements
    # because tx.init builds them from the (already-sharded) params.
    return jax.jit(
        step,
        in_shardings=(None, batch_sharding),
        donate_argnums=(0,) if donate else (),
    )


def make_classifier_eval_step(
    model: Any,
    mesh: Mesh,
    *,
    has_batch_stats: bool = True,
    data_axis: Any = "dp",
):
    """Jitted eval step (what an Evaluator replica runs against checkpoints
    the trainer writes): batch sharded over the data axis, params
    replicated, BatchNorm in inference mode (running stats). The batch
    carries a 0/1 ``mask`` (padding rows are 0) and the step returns MASKED
    sums (correct, loss_sum, count), so ``evaluate`` below can pad every
    batch to one fixed shape — exact metrics, one XLA compilation."""

    def step(state: TrainState, batch):
        variables = {"params": state.params}
        if has_batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, batch["image"], train=False)
        labels = batch["label"]
        mask = batch["mask"]
        per_example = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        )
        # Integer counts: f32 accumulation would silently lose exactness
        # past 2^24 examples; int32 is exact to 2^31.
        return {
            "correct": ((logits.argmax(-1) == labels) & (mask > 0))
            .astype(jnp.int32).sum(),
            "loss_sum": (per_example * mask.astype(jnp.float32)).sum(),
            "count": (mask > 0).astype(jnp.int32).sum(),
        }

    sharded, shard_count = _data_axis_sharding(mesh, data_axis)
    batch_sharding = {"image": sharded, "label": sharded, "mask": sharded}
    replicated = NamedSharding(mesh, P())
    fn = jax.jit(
        step,
        in_shardings=(replicated, batch_sharding),
        out_shardings=replicated,
    )
    return _EvalStep(fn, sharded, shard_count)


class _EvalStep:
    """A jitted eval step plus the sharding facts evaluate() needs — bound
    at build time so the caller can't pass a mismatched mesh/axis later."""

    def __init__(self, fn, sharding: NamedSharding, shard_count: int) -> None:
        self._fn = fn
        self.sharding = sharding
        self.shard_count = shard_count

    def __call__(self, state: TrainState, batch):
        return self._fn(state, batch)

    def compilation_count(self) -> int:
        """Best-effort (private JAX API): -1 when unavailable."""
        probe = getattr(self._fn, "_cache_size", None)
        return int(probe()) if callable(probe) else -1


def _iter_padded(batches, shard_count: int, pad_to: int | None,
                 fields: tuple[str, ...], mask_ndim: int):
    """Shared eval-driver padding: yield (arrays-with-mask, pad_to) for each
    non-empty host batch, every batch zero-padded to ONE fixed row count
    (``pad_to``; default = first non-empty batch rounded up to the data-axis
    size) so a single compiled executable serves the whole stream. The mask
    (ones over real rows, zeros over padding; shape = leading ``mask_ndim``
    dims, honoring a caller-provided per-element "mask" field) makes padded
    rows contribute nothing."""
    for batch in batches:
        arrs = {f: np.asarray(batch[f]) for f in fields}
        n = arrs[fields[0]].shape[0]
        if n == 0:
            continue  # an empty shard must not define (or fail) the shape
        if pad_to is None:
            pad_to = -(-n // shard_count) * shard_count
        if n > pad_to:
            raise ValueError(
                f"batch of {n} exceeds pad_to={pad_to}; the first batch "
                "sets the compiled shape — pass pad_to= explicitly when "
                "later batches can be larger"
            )
        mshape = arrs[fields[0]].shape[:mask_ndim]
        arrs["mask"] = (
            np.asarray(batch["mask"], np.float32)
            if "mask" in batch
            else np.ones(mshape, np.float32)
        )
        pad = pad_to - n
        if pad:
            arrs = {
                k: np.concatenate(
                    [v, np.zeros((pad, *v.shape[1:]), v.dtype)]
                )
                for k, v in arrs.items()
            }
        yield arrs, pad_to


def evaluate(
    eval_step: "_EvalStep",
    state: TrainState,
    batches,
    *,
    pad_to: int | None = None,
) -> dict[str, float]:
    """Drive an eval step over host batches of ANY sizes (tail batches
    included) — padding via _iter_padded, so every call hits the same
    compiled executable and counts/accuracy are exact (loss accumulates in
    f32). Accumulation stays on device; the host syncs once at the end."""
    sharding, shard_count = eval_step.sharding, eval_step.shard_count
    correct = loss_sum = count = None
    for arrs, pad_to in _iter_padded(
        batches, shard_count, pad_to, ("image", "label"), mask_ndim=1
    ):
        dev = {k: jax.device_put(v, sharding) for k, v in arrs.items()}
        m = eval_step(state, dev)  # async: dispatch overlaps host prep
        if correct is None:
            correct, loss_sum, count = m["correct"], m["loss_sum"], m["count"]
        else:
            correct = correct + m["correct"]
            loss_sum = loss_sum + m["loss_sum"]
            count = count + m["count"]
    if correct is None or int(count) == 0:
        raise ValueError("evaluate() got no non-empty batches")
    total = int(count)  # single host sync
    return {
        "accuracy": int(correct) / total,
        "loss": float(loss_sum) / total,
        "count": total,
    }


def make_lm_eval_step(
    model: Any,
    mesh: Mesh,
    *,
    data_axis: Any = "dp",
    xent_chunk: int = 512,
):
    """Jitted LM eval step (the Evaluator-role flow for the transformer):
    batch {tokens, targets, mask} sharded over the data axis, returns
    MASKED sums (loss_sum f32, count int32) so ``evaluate_lm`` can pad
    every batch to one fixed shape — exact perplexity, one compilation,
    and the [B,S,V] logits never materialize (chunked scan)."""

    def step(state: TrainState, batch):
        hidden = model.apply(
            {"params": state.params}, batch["tokens"], return_hidden=True
        )
        head = state.params["lm_head"]
        seq = batch["tokens"].shape[1]
        # Largest divisor of the (static) sequence length <= xent_chunk, so
        # any sequence length works without caller-side chunk math.
        # xent_chunk is a MEMORY BOUND and is never exceeded; a prime/odd
        # length whose best divisor is tiny still evaluates correctly,
        # just slowly — warn (at trace time) so the caller can pick a
        # friendlier length.
        chunk = next(
            c for c in range(min(xent_chunk, seq), 0, -1) if seq % c == 0
        )
        if chunk < min(8, xent_chunk, seq):
            from tf_operator_tpu.utils import logger

            logger.with_fields(component="lm-eval").warning(
                "seq %d has no divisor <= xent_chunk %d above %d; eval "
                "will scan %d tiny chunks — consider a seq length with a "
                "divisor near the chunk size",
                seq, xent_chunk, chunk, seq // chunk,
            )
        # The device count is unused here — evaluate_lm counts tokens
        # host-side (a device int32 would wrap past 2^31 tokens).
        loss_sum, _ = chunked_lm_xent_sums(
            hidden, head["kernel"], head.get("bias"),
            batch["targets"], batch["mask"], chunk=chunk,
        )
        return {"loss_sum": loss_sum}

    sharded, shard_count = _data_axis_sharding(mesh, data_axis)
    batch_sharding = {"tokens": sharded, "targets": sharded, "mask": sharded}
    replicated = NamedSharding(mesh, P())
    fn = jax.jit(
        step, in_shardings=(None, batch_sharding), out_shardings=replicated
    )
    return _EvalStep(fn, sharded, shard_count)


def evaluate_lm(
    eval_step: "_EvalStep",
    state: TrainState,
    batches,
    *,
    pad_to: int | None = None,
) -> dict[str, float]:
    """Drive an LM eval step over host batches of any row counts — padding
    via _iter_padded; returns mean token loss, perplexity, and the total
    token weight (a float: the mask-value sum — exactly the token count
    for 0/1 masks). The f32 loss accumulates on device (one sync at the end);
    the TOKEN weight accumulates host-side in float64 as the SUM of mask
    values (matching the device numerator's mask weighting, so fractional
    masks stay consistent; exact for 0/1 masks) — a device int32 would
    silently wrap past 2^31 tokens, routine corpus scale for perplexity
    eval."""
    sharding, shard_count = eval_step.sharding, eval_step.shard_count
    loss_sum = None
    tokens = 0
    for arrs, pad_to in _iter_padded(
        batches, shard_count, pad_to, ("tokens", "targets"), mask_ndim=2
    ):
        # Sum mask VALUES (not count of nonzeros) so a fractional
        # per-token mask weights the denominator the same way the device
        # loss_sum weights the numerator. For 0/1 masks this is identical
        # to counting; float64 host accumulation holds exact integer
        # counts far past 2^31.
        tokens += float(arrs["mask"].sum(dtype=np.float64))
        dev = {k: jax.device_put(v, sharding) for k, v in arrs.items()}
        m = eval_step(state, dev)  # async: dispatch overlaps host prep
        loss_sum = m["loss_sum"] if loss_sum is None else loss_sum + m["loss_sum"]
    if loss_sum is None or tokens == 0:
        raise ValueError("evaluate_lm() got no non-empty batches")
    mean = float(loss_sum) / tokens
    return {"loss": mean, "perplexity": math.exp(mean), "tokens": tokens}


def fuse_steps(step_fn, num_steps: int, *, scan_batches: bool = False,
               donate: bool = True):
    """Fuse ``num_steps`` train steps into ONE jitted call via lax.scan.

    Per-step host dispatch is pure overhead on TPU (and dominates entirely
    through a remote-chip tunnel): scanning the step inside a single
    executable keeps the chip busy with zero host round-trips between
    steps — measured 12x throughput on single-chip ResNet-50 here. The
    carry (train state) is donated; metrics returned are the last step's.
    Build the inner step with donate=False (the outer jit owns donation).

    By default every iteration re-trains on the SAME batch argument —
    right for benchmarking and synthetic data, wrong for a real data
    pipeline. For real training pass scan_batches=True and feed a batch
    pytree whose leaves are stacked with leading dim num_steps (e.g.
    [num_steps, per_step_batch, ...]); each iteration then consumes its
    own slice.
    """

    def multi(state, batch):
        if scan_batches:
            for leaf in jax.tree.leaves(batch):
                if leaf.shape[0] != num_steps:
                    raise ValueError(
                        f"scan_batches=True needs leading dim {num_steps}, "
                        f"got {leaf.shape}"
                    )
            state, metrics = jax.lax.scan(step_fn, state, batch)
        else:
            state, metrics = jax.lax.scan(
                lambda s, _: step_fn(s, batch), state, None, length=num_steps
            )
        return state, jax.tree.map(lambda x: x[-1], metrics)

    return jax.jit(multi, donate_argnums=(0,) if donate else ())


def sgd_momentum(lr: float = 0.1, momentum: float = 0.9, nesterov: bool = True):
    return optax.sgd(lr, momentum=momentum, nesterov=nesterov)


def adamw(lr: Any = 3e-4, weight_decay: float = 0.01):
    """AdamW; ``lr`` may be a float or an optax schedule (warmup_cosine)."""
    return optax.adamw(lr, weight_decay=weight_decay)


def adafactor(lr: Any = 1e-3):
    """Adafactor (factored second moments): optimizer state for a [d_in,
    d_out] kernel is O(d_in + d_out) instead of AdamW's 2x O(d_in *
    d_out) — the memory-efficient choice for large LMs, where AdamW
    moments alone can exceed the params. Composes with FSDP sharding
    (the factored vectors shard like their params' leading dims); ``lr``
    may be a float or an optax schedule."""
    return optax.adafactor(lr)


def _no_norm_or_bias(params: Any) -> Any:
    """Mask tree: True for >=2-D kernels, False for biases and norm
    scales (1-D / scalar leaves) — the canonical LARS/MLPerf exclusion
    set for weight decay and trust-ratio adaptation."""
    return jax.tree.map(lambda p: jnp.ndim(p) >= 2, params)


def lars(lr: Any = 1.0, weight_decay: float = 1e-4,
         momentum: float = 0.9, mask_norm_and_bias: bool = True):
    """LARS — layerwise-adaptive SGD for LARGE-BATCH vision training
    (the optimizer behind the MLPerf ResNet TPU-pod entries: per-layer
    trust ratio ||w||/||g|| keeps early layers stable when the global
    batch reaches tens of thousands, where plain momentum diverges).
    Use with warmup_cosine and batch-scaled lr; ``lr`` may be a float
    or schedule. The canonical recipe EXCLUDES BatchNorm scales/biases
    and bias vectors from both decay and the trust ratio (a known
    large-batch convergence degrader otherwise) — on by default via the
    dimensionality mask; pass mask_norm_and_bias=False for raw LARS."""
    mask = _no_norm_or_bias if mask_norm_and_bias else True
    return optax.lars(
        lr, weight_decay=weight_decay, momentum=momentum,
        weight_decay_mask=mask, trust_ratio_mask=mask,
    )


def lamb(lr: Any = 1e-3, weight_decay: float = 0.01,
         mask_norm_and_bias: bool = True):
    """LAMB — the adam-based layerwise-adaptive counterpart for
    large-batch transformer training (BERT-in-76-minutes recipe).
    Same trust-ratio idea as LARS on top of adam updates; ``lr`` may be
    a float or schedule. Like lars(), norm scales and biases are
    excluded from weight decay by default (the canonical recipe).
    Moments shard under FSDP / weight-update sharding like adamw's."""
    mask = _no_norm_or_bias if mask_norm_and_bias else None
    return optax.lamb(lr, weight_decay=weight_decay, mask=mask)


def warmup_cosine(
    peak_lr: float,
    total_steps: int,
    *,
    warmup_steps: int | None = None,
    end_lr_fraction: float = 0.1,
):
    """Linear warmup -> cosine decay, the standard large-batch TPU recipe
    (jit-compatible: a pure function of the step counter, so the schedule
    lives INSIDE the compiled update — no host-side LR bookkeeping, and it
    survives checkpoint/resume for free because optax keeps the step in the
    optimizer state)."""
    if warmup_steps is None:
        warmup_steps = max(1, total_steps // 20)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=peak_lr,
        warmup_steps=warmup_steps,
        decay_steps=total_steps,
        end_value=peak_lr * end_lr_fraction,
    )
