"""Process entry points: operator main + genjob load generator (§2.5)."""
