"""The operator process entry point.

Parity: cmd/tf-operator.v2/{main.go,app/server.go,app/options/options.go} —
flags, signal handling, leader election, controller startup. Re-designed as
a self-hosting runtime: `--serve` exposes the backing store over HTTP
(runtime/apiserver.py) so remote clients/dashboard/harness connect to this
process the way the reference's clients connect to the K8s apiserver, and
`--local-executor` turns pods into real OS processes (the single-node mode).

  # all-in-one local runtime with REST API on :8080 and real processes:
  python -m tf_operator_tpu.cli.operator --serve 8080 --local-executor

  # controller-only against a remote runtime:
  python -m tf_operator_tpu.cli.operator --master http://host:8080
"""

from __future__ import annotations

import argparse
import os
import signal as signal_mod
import socket
import sys
import threading

from tf_operator_tpu.api import constants
from tf_operator_tpu.controller.jobcontroller import JobControllerConfig
from tf_operator_tpu.controller.tpujob_controller import TPUJobController
from tf_operator_tpu.runtime.leader_election import LeaderElectionConfig, LeaderElector
from tf_operator_tpu.utils import logger, signals
from tf_operator_tpu.version import version_string


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-operator",
        description="TPU-native training-job operator (tf-operator rebuilt TPU-first)",
    )
    # Parity: options.go:22-51 (threadiness, gang, json-log, namespace).
    p.add_argument("--namespace", default=None,
                   help="restrict reconciliation to one namespace (default: all)")
    p.add_argument("--threadiness", type=int, default=2,
                   help="concurrent sync workers")
    p.add_argument("--reconcile-period", type=float, default=15.0,
                   help="periodic resync seconds (reference: 15s)")
    p.add_argument("--informer-resync", type=float, default=30.0,
                   help="informer relist seconds (reference: 30s)")
    p.add_argument("--enable-gang-scheduling", dest="gang", action="store_true",
                   default=True)
    p.add_argument("--disable-gang-scheduling", dest="gang", action="store_false")
    # Gang-admission fleet declaration (scheduler/placement.py). Without it
    # the admission pipeline still runs (gate → admit → release, so no
    # partial slice can run) but every gang admits immediately; with it the
    # scheduler arbitrates topology-contiguous placement on the declared
    # meshes and queues what does not fit.
    p.add_argument("--tpu-capacity", default=None, metavar="SPEC",
                   help='installed fleet per generation, e.g. '
                        '"v5e=16x16,v4=4x4x8" (default: unbounded)')
    p.add_argument("--quota", action="append", default=[], metavar="NS=CHIPS[:SLICES]",
                   help="per-namespace admission budget, repeatable, e.g. "
                        "--quota team-a=64 --quota team-b=32:2")
    p.add_argument("--scheduler-aging-rate", type=float, default=1.0,
                   help="priority points gained per second queued "
                        "(starvation valve; 0 disables aging)")
    p.add_argument("--disable-preemption", dest="preemption",
                   action="store_false", default=True,
                   help="never evict lower-priority gangs to admit a "
                        "higher-priority one")
    # Fleet health & auto-repair (tf_operator_tpu/health/): node heartbeats,
    # exit-138 attribution and restart churn feed per-cell health states;
    # cordoned cells are excluded from placement and gangs on them are
    # checkpoint-signaled and migrated whole.
    p.add_argument("--disable-fleet-health", dest="fleet_health",
                   action="store_false", default=True,
                   help="run without the fleet-health monitor (no cordons, "
                        "no maintenance-aware migration)")
    p.add_argument("--health-poll-interval", type=float, default=2.0,
                   help="seconds between health monitor sweeps "
                        "(heartbeats, repair clocks, deferred migrations)")
    p.add_argument("--health-suspect-threshold", type=float, default=3.0,
                   help="suspect score at which a cell auto-cordons")
    p.add_argument("--health-repair-after", type=float, default=30.0,
                   help="seconds a cordon holds before the repair probe")
    p.add_argument("--health-probe-window", type=float, default=30.0,
                   help="quiet seconds in the repair probe before a cell "
                        "auto-uncordons")
    # Fleet serving (tf_operator_tpu/fleet/): TPUServe resources become
    # long-running replica fleets — child jobs per replica, /healthz
    # probed membership, queue-depth/TTFT autoscaling, drain-before-
    # delete scale-down and surge-then-drain rolling updates.
    p.add_argument("--disable-fleet-serving", dest="fleet_serving",
                   action="store_false", default=True,
                   help="run without the TPUServe fleet controller "
                        "(TPUServe objects are stored but not reconciled)")
    p.add_argument("--fleet-sync-interval", type=float, default=1.0,
                   help="seconds between TPUServe reconcile sweeps "
                        "(each sweep probes every replica's /healthz)")
    p.add_argument("--fleet-probe-timeout", type=float, default=2.0,
                   help="per-replica /healthz probe timeout")
    p.add_argument("--fleet-fail-threshold", type=int, default=3,
                   help="consecutive unanswered probes before a replica "
                        "is declared dead and replaced")
    # Checkpoint coordination (tf_operator_tpu/ckpt/): per-job checkpoint
    # registry, ack'd graceful eviction, resume injection, checkpoint GC.
    p.add_argument("--checkpoint-grace", type=float, default=30.0,
                   metavar="SECS",
                   help="graceful-eviction barrier: seconds a preemption/"
                        "migration waits for a checkpoint ack before "
                        "deleting pods (released early on ack; 0 = evict "
                        "immediately, the fire-and-forget behavior)")
    p.add_argument("--checkpoint-stale-after", type=float, default=600.0,
                   metavar="SECS",
                   help="flag a Running job CheckpointStale when its "
                        "checkpoint roll-up is quiet this long (0 = off)")
    p.add_argument("--ckpt-gc-keep", type=int, default=1,
                   help="checkpoint steps retained per Succeeded job by "
                        "the retention sweeper (local-executor mode)")
    p.add_argument("--ckpt-gc-ttl", type=float, default=0.0, metavar="SECS",
                   help="additionally expire retained checkpoint steps of "
                        "Succeeded jobs older than this (0 = never)")
    p.add_argument("--ckpt-gc-interval", type=float, default=60.0,
                   metavar="SECS",
                   help="seconds between checkpoint retention sweeps")
    p.add_argument("--json-log", action="store_true", help="structured JSON logs")
    p.add_argument("--version", action="store_true", help="print version and exit")
    # Runtime wiring: the backing store is the in-process store (default),
    # a remote runtime's REST API (--master), or a real Kubernetes apiserver
    # (--backend kube, the reference's native habitat).
    p.add_argument("--backend", choices=("mem", "kube"), default="mem",
                   help="'mem': in-process store (or --master); "
                        "'kube': real Kubernetes via kubeconfig/in-cluster")
    p.add_argument("--kubeconfig", default=None,
                   help="kubeconfig path for --backend kube "
                        "(default: in-cluster, then $KUBECONFIG, then ~/.kube/config)")
    p.add_argument("--kube-context", default=None,
                   help="kubeconfig context to use (default: current-context)")
    p.add_argument("--master", default=None,
                   help="URL of a remote runtime API server; default: in-process store")
    p.add_argument("--serve", type=int, default=None, metavar="PORT",
                   help="serve the HTTP API on PORT: the in-process store "
                        "(default backend), or an aggregating proxy + "
                        "dashboard + /metrics over --backend kube")
    p.add_argument("--serve-host", default="127.0.0.1")
    p.add_argument("--serve-token-file", default=None, metavar="PATH",
                   help="bearer token required on every mutating HTTP "
                        "request (reads stay open); strongly recommended "
                        "with --backend kube + --serve")
    p.add_argument("--local-executor", action="store_true",
                   help="run pods as local OS processes (single-node mode)")
    # Leader election (server.go:140-152).
    p.add_argument("--leader-elect", action="store_true", default=False)
    p.add_argument("--lease-namespace",
                   default=os.environ.get(constants.ENV_OPERATOR_NAMESPACE,
                                          constants.DEFAULT_OPERATOR_NAMESPACE))
    p.add_argument("--lease-duration", type=float, default=15.0)
    p.add_argument("--renew-deadline", type=float, default=5.0)
    p.add_argument("--retry-period", type=float, default=3.0)
    p.add_argument("--dashboard", action="store_true",
                   help="mount the dashboard UI/API on the --serve server")
    p.add_argument("--exit-with-parent", action="store_true",
                   help="die when the parent process dies (Linux PDEATHSIG; "
                        "harness mode — a SIGKILLed test run must not leak "
                        "operator processes that churn CPU forever)")
    return p


def _arm_parent_death_signal(log) -> None:
    """Exit when the parent PROCESS dies, by polling getppid() for the
    re-parenting to init. Deliberately NOT prctl(PR_SET_PDEATHSIG): that is
    keyed to the parent *thread* that forked us, so a harness that spawns
    the operator from a short-lived worker thread (the CI workflow's deploy
    step) would kill the operator the moment the thread exits — observed as
    ECONNRESET in the very next workflow step. Polling is process-level and
    immune; a few seconds of latency is irrelevant for leak prevention
    (leaked operators previously churned CPU for hours)."""
    if os.name != "posix":
        # No orphan re-parenting semantics to observe (getppid keeps
        # returning the dead parent's pid on Windows): the flag cannot
        # work, say so instead of silently no-opping.
        log.warning("--exit-with-parent unavailable on this platform")
        return
    original_ppid = os.getppid()
    if original_ppid == 1:
        log.info("parent already exited; honoring --exit-with-parent")
        raise SystemExit(0)

    poll = threading.Event()

    def watch() -> None:
        while not poll.wait(2.0):
            # Any CHANGE of ppid means the original parent died — the
            # orphan may be re-parented to init (1) or to a subreaper
            # (systemd user manager, tini), so comparing against the
            # original pid is the robust check, not == 1.
            if os.getppid() != original_ppid:
                # Mirror a SIGTERM exit; os._exit because the interpreter
                # may be blocked in non-interruptible native calls.
                os._exit(128 + int(signal_mod.SIGTERM))

    threading.Thread(
        target=watch, name="parent-watch", daemon=True
    ).start()


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.version:
        print(version_string())
        return 0
    logger.configure(json_format=args.json_log)
    log = logger.with_fields(component="operator-main")
    log.info("%s", version_string())

    stop = signals.setup_signal_handler()
    if args.exit_with_parent:
        _arm_parent_death_signal(log)

    # --- backing store ------------------------------------------------------
    if args.backend == "kube":
        if args.master:
            log.error("--backend kube and --master are mutually exclusive")
            return 2
        if args.local_executor:
            # Real kubelets run the pods on a real cluster; a local executor
            # would double-execute every replica.
            log.error("--local-executor is incompatible with --backend kube")
            return 2
        from tf_operator_tpu.runtime.kubeclient import (
            KubeClusterClient,
            KubeConfigError,
            resolve_config,
        )

        try:
            kube_cfg = resolve_config(args.kubeconfig, args.kube_context)
        except KubeConfigError as e:
            log.error("kube config resolution failed: %s", e)
            return 2
        client = KubeClusterClient(kube_cfg)
        log.info("using Kubernetes apiserver at %s", kube_cfg.server)
    elif args.master:
        from tf_operator_tpu.runtime.restclient import RestClusterClient

        client = RestClusterClient(args.master)
        log.info("using remote runtime at %s", args.master)
    else:
        from tf_operator_tpu.runtime.memcluster import InMemoryCluster

        client = InMemoryCluster()

    # --- gang admission scheduler ------------------------------------------
    from tf_operator_tpu.scheduler import GangScheduler, Quota, SchedulerConfig
    from tf_operator_tpu.scheduler.placement import CapacityError, parse_capacity

    try:
        capacity = parse_capacity(args.tpu_capacity) if args.tpu_capacity else None
        quotas = {}
        for spec in args.quota:
            ns, _, budget = spec.partition("=")
            if not ns or not budget:
                raise CapacityError(
                    f"--quota must be NS=CHIPS[:SLICES], got {spec!r}"
                )
            chips_s, _, slices_s = budget.partition(":")
            quotas[ns.strip()] = Quota(
                chips=int(chips_s),
                slices=int(slices_s) if slices_s else None,
            )
    except (CapacityError, ValueError) as e:
        log.error("bad scheduler flag: %s", e)
        return 2
    scheduler = GangScheduler(config=SchedulerConfig(
        capacity=capacity,
        quotas=quotas,
        aging_rate=args.scheduler_aging_rate,
        preemption=args.preemption,
        gate_pods=args.gang,
        checkpoint_grace=args.checkpoint_grace,
    ))

    # --- checkpoint coordination -------------------------------------------
    from tf_operator_tpu.ckpt import CheckpointRegistry, CkptConfig

    ckpt_registry = CheckpointRegistry(
        scheduler,
        config=CkptConfig(stale_after=args.checkpoint_stale_after),
    )

    # --- fleet health monitor ----------------------------------------------
    health = None
    if args.fleet_health:
        from tf_operator_tpu.health import FleetHealthMonitor, HealthConfig

        health = FleetHealthMonitor(
            scheduler,
            config=HealthConfig(
                suspect_threshold=args.health_suspect_threshold,
                repair_after=args.health_repair_after,
                probe_window=args.health_probe_window,
            ),
        )

    # --- fleet serving (TPUServe) ------------------------------------------
    serve_ctrl = None
    if args.fleet_serving:
        from tf_operator_tpu.fleet import FleetConfig, TPUServeController

        serve_ctrl = TPUServeController(
            client,
            scheduler=scheduler,
            config=FleetConfig(
                sync_interval_s=args.fleet_sync_interval,
                probe_timeout_s=args.fleet_probe_timeout,
                fail_threshold=args.fleet_fail_threshold,
                namespace=args.namespace,
            ),
        )

    api_server = None
    if args.serve is not None:
        if args.master:
            log.error("--serve requires the in-process store (drop --master)")
            return 2
        from tf_operator_tpu.runtime.apiserver import ApiServer

        write_token = None
        if args.serve_token_file:
            with open(args.serve_token_file) as f:
                write_token = f.read().strip()
            if not write_token:
                log.error("--serve-token-file %s is empty", args.serve_token_file)
                return 2
        elif args.backend == "kube":
            log.warning(
                "serving an UNAUTHENTICATED write API over the kube backend:"
                " anyone reaching %s:%s can create jobs the operator runs"
                " with its own privileges — set --serve-token-file (or a"
                " NetworkPolicy)", args.serve_host, args.serve,
            )
        # Over the in-memory store this IS the cluster API; over the kube
        # backend it is an aggregating proxy (REST + dashboard + /metrics
        # riding KubeClusterClient) — the in-cluster observability surface.
        api_server = ApiServer(
            client, host=args.serve_host, port=args.serve,
            write_token=write_token,
        )
        # Observability mounts BEFORE the dashboard: handlers run in
        # registration order and the dashboard's SPA fallback swallows any
        # unmatched GET, which would shadow /metrics with index.html.
        from tf_operator_tpu.runtime.observability import mount_observability

        mount_observability(
            api_server, scheduler=scheduler, health=health,
            ckpt=ckpt_registry, fleet=serve_ctrl,
        )
        if args.dashboard:
            from tf_operator_tpu.dashboard.backend import mount_dashboard

            mount_dashboard(api_server, client)
        api_server.start()

    # --- controller stack ---------------------------------------------------
    cfg = JobControllerConfig(
        reconcile_period=args.reconcile_period,
        informer_resync=args.informer_resync,
        enable_gang_scheduling=args.gang,
        namespace=args.namespace,
        threadiness=args.threadiness,
    )

    extras: list[object] = []

    def run_controller(leading_stop: threading.Event) -> None:
        controller = TPUJobController(client, cfg, scheduler=scheduler)
        if serve_ctrl is not None:
            # Reconciles TPUServe fleets only while leading — a standby
            # creating or draining replicas would fight the leader.
            serve_ctrl.start(leading_stop)
        if health is not None:
            # Attached by the controller (client + recorder, cordon
            # recovery); the poll loop runs only while leading — a
            # standby must not cordon or migrate anything.
            health.start(leading_stop, interval=args.health_poll_interval)
        if args.local_executor:
            from tf_operator_tpu.ckpt import CheckpointSweeper, SweepConfig
            from tf_operator_tpu.runtime.executor import LocalProcessExecutor
            from tf_operator_tpu.runtime.gc import OwnerGarbageCollector

            executor = LocalProcessExecutor(client, args.namespace)
            collector = OwnerGarbageCollector(client, args.namespace)
            # Checkpoint retention GC runs where the checkpoint storage is
            # reachable — which is exactly the local-executor runtime (on
            # a real cluster the sweeper belongs wherever the shared
            # filesystem mounts).
            sweeper = CheckpointSweeper(
                client,
                SweepConfig(
                    keep=args.ckpt_gc_keep,
                    ttl=args.ckpt_gc_ttl,
                    interval=args.ckpt_gc_interval,
                ),
                args.namespace,
            )
            executor.start(leading_stop)
            collector.start(leading_stop)
            sweeper.start(leading_stop)
            extras.append(executor)
        controller.run(leading_stop)

    if args.leader_elect:
        identity = f"{socket.gethostname()}-{os.getpid()}"
        elector = LeaderElector(
            client,
            identity,
            on_started_leading=run_controller,
            config=LeaderElectionConfig(
                namespace=args.lease_namespace,
                lease_duration=args.lease_duration,
                renew_deadline=args.renew_deadline,
                retry_period=args.retry_period,
            ),
        )
        elector.run(stop)  # blocks until signal
    else:
        t = threading.Thread(target=run_controller, args=(stop,), daemon=True)
        t.start()
        stop.wait()

    log.info("shutting down")
    if api_server is not None:
        api_server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
