"""Load generator: create N synthetic TPUJobs for scale / gang-scheduling
experiments.

Parity: hack/genjob/genjob.go:30-92 (creates N TFJobs, optionally GPU,
custom schedulerName). TPU-native twist: `--accelerator` attaches a TPU
slice spec instead of a GPU resource limit, so the generated fleet
exercises slice-granular gang scheduling.

  python -m tf_operator_tpu.cli.genjob --master http://127.0.0.1:8080 -n 50
"""

from __future__ import annotations

import argparse
import sys
import uuid

from tf_operator_tpu.api import constants
from tf_operator_tpu.client import TPUJobClient
from tf_operator_tpu.utils import logger


def synthetic_job(
    name: str,
    namespace: str,
    workers: int,
    accelerator: str | None,
    scheduler: str | None,
    command: list[str] | None = None,
) -> dict:
    worker_spec: dict = {
        "template": {
            "spec": {
                "containers": [
                    {
                        "name": constants.DEFAULT_CONTAINER_NAME,
                        "image": "tpu-operator/test-server",
                        "command": command
                        or [sys.executable, "-m", "tf_operator_tpu.harness.test_server"],
                    }
                ]
            }
        },
    }
    if accelerator:
        worker_spec["tpu"] = {"acceleratorType": accelerator}
    else:
        worker_spec["replicas"] = workers
    spec: dict = {"replicaSpecs": {"Worker": worker_spec}}
    if scheduler:
        spec["scheduling"] = {"schedulerName": scheduler, "gang": True}
    return {
        "apiVersion": constants.API_VERSION,
        "kind": constants.KIND,
        "metadata": {"name": name, "namespace": namespace},
        "spec": spec,
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpu-genjob", description=__doc__)
    p.add_argument("--master", default="http://127.0.0.1:8080")
    p.add_argument("-n", "--num", type=int, default=10, help="jobs to create")
    p.add_argument("--namespace", default="default")
    p.add_argument("--workers", type=int, default=2, help="workers per job")
    p.add_argument("--accelerator", default=None,
                   help="TPU slice per job, e.g. v5e-16 (overrides --workers)")
    p.add_argument("--scheduler", default=None, help="schedulerName for gang pods")
    p.add_argument("--prefix", default=None, help="job name prefix")
    args = p.parse_args(argv)

    logger.configure()
    log = logger.with_fields(component="genjob")
    from tf_operator_tpu.runtime.restclient import RestClusterClient

    cli = TPUJobClient(RestClusterClient(args.master))
    prefix = args.prefix or f"genjob-{uuid.uuid4().hex[:5]}"
    for i in range(args.num):
        job = synthetic_job(
            f"{prefix}-{i}", args.namespace, args.workers, args.accelerator,
            args.scheduler,
        )
        cli.create(job)
    log.info("created %d TPUJobs with prefix %s", args.num, prefix)
    print(prefix)
    return 0


if __name__ == "__main__":
    sys.exit(main())
