"""tpuctl — the kubectl-shaped CLI for the TPU job operator.

The reference assumes kubectl for every user interaction (README.md:16-18:
`kubectl apply` a TFJob, `kubectl get tfjobs`); this framework's apiserver
speaks its own REST dialect, so a standalone deployment needs its own
ctl. Commands mirror the kubectl verbs users already know:

    tpuctl get jobs [-n NS] [-w]            # table of TPUJobs (stream w/ -w)
    tpuctl get job NS/NAME [-o json|yaml]   # one job (table row or doc)
    tpuctl describe NS/NAME                 # conditions/replicas/pods/events
    tpuctl apply -f job.json|yaml           # create (json or yaml, - = stdin)
    tpuctl delete NS/NAME
    tpuctl logs NS/POD [-f]                 # pod logs (stream with -f)
    tpuctl wait NS/NAME [--for Succeeded] [--timeout 300]
    tpuctl queue [-o json]                  # gang-admission queue/capacity
    tpuctl health [-o json]                 # fleet health: cell states
    tpuctl ckpt [-o json]                   # checkpoint registry: acked steps
    tpuctl trace NS/FLEET [--router H:P]    # merged fleet Chrome trace → stdout
    tpuctl cordon v4 0,0,0 0,0,1            # pin cells out of placement
    tpuctl uncordon v4 0,0,0 0,0,1          # return cells to service
    tpuctl drain v4 0,0,0 --at 3600         # maintenance notice + migrate

The server is ``--master`` / $TPU_OPERATOR_MASTER (default
http://127.0.0.1:8080 — the operator's --serve address). Write auth rides
$TPU_OPERATOR_API_TOKEN exactly as the client library does.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from typing import Any

from tf_operator_tpu.client.tpujob_client import TimeoutError_, TPUJobClient
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.restclient import RestClusterClient

DEFAULT_MASTER = os.environ.get(
    "TPU_OPERATOR_MASTER", "http://127.0.0.1:8080"
)


def _age(ts: str | None) -> str:
    import calendar

    if not ts:
        return "?"
    try:
        then = calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
    except ValueError:
        return "?"
    s = max(0, int(time.time() - then))
    for unit, div in (("d", 86400), ("h", 3600), ("m", 60)):
        if s >= div:
            return f"{s // div}{unit}"
    return f"{s}s"


def _state(job: dict[str, Any]) -> str:
    conds = [
        c["type"] for c in job.get("status", {}).get("conditions", [])
        if c.get("status") == "True"
    ]
    for top in ("Failed", "Succeeded", "Restarting", "Running", "Created"):
        if top in conds:
            return top
    return "Pending"


def _replicas(job: dict[str, Any]) -> str:
    rs = job.get("status", {}).get("replicaStatuses", {})
    return ",".join(
        f"{t}:{s.get('active', 0)}/{s.get('succeeded', 0)}/{s.get('failed', 0)}"
        for t, s in sorted(rs.items())
    ) or "-"


def _table(rows: list[list[str]], header: list[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows)
        for i in range(len(header))
    ]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*header)]
    lines += [fmt.format(*(str(c) for c in r)) for r in rows]
    return "\n".join(lines)


def _split_ref(ref: str, what: str = "job") -> tuple[str, str]:
    if "/" not in ref:
        raise SystemExit(
            f"tpuctl: {what} reference must be NAMESPACE/NAME, got {ref!r}"
        )
    ns, name = ref.split("/", 1)
    return ns, name


def _job_row(j: dict[str, Any]) -> list[str]:
    return [
        j["metadata"].get("namespace", ""),
        j["metadata"].get("name", ""),
        _state(j),
        _replicas(j),
        _age(j["metadata"].get("creationTimestamp")),
    ]


def _dump(obj, fmt: str) -> str:
    if fmt == "yaml":
        import yaml

        return yaml.safe_dump(obj, sort_keys=False, default_flow_style=False)
    return json.dumps(obj, indent=2)


def cmd_get(args, client: TPUJobClient) -> int:
    if args.kind in ("jobs", "tpujobs"):
        jobs = client.list(args.namespace)
        if args.output in ("json", "yaml"):
            print(_dump({"items": jobs}, args.output))
            return 0
        rows = [_job_row(j) for j in jobs]
        print(_table(rows, ["NAMESPACE", "NAME", "STATE", "REPLICAS", "AGE"]))
        if args.watch:
            # kubectl -w semantics: stream one row per update event until
            # interrupted (or --watch-events N for scripts/tests).
            # namespace None = all-namespace watch, matching the listing.
            w = client._client.watch(  # noqa: SLF001 — raw watch surface
                objects.TPUJOBS, args.namespace
            )
            seen = 0
            try:
                while args.watch_events is None or seen < args.watch_events:
                    ev = w.next(timeout=1.0)
                    if ev is None:
                        continue
                    print(_table([_job_row(ev.object)],
                                 ["", "", "", "", ""]).splitlines()[1])
                    seen += 1
            except KeyboardInterrupt:
                pass
            finally:
                client._client.stop_watch(w)  # noqa: SLF001
        return 0
    if args.kind in ("job", "tpujob"):
        ns, name = _split_ref(args.name or "", "job")
        job = client.get(ns, name)
        if args.output in ("json", "yaml"):
            print(_dump(job, args.output))
        else:
            print(_table(
                [[ns, name, _state(job), _replicas(job),
                  _age(job["metadata"].get("creationTimestamp"))]],
                ["NAMESPACE", "NAME", "STATE", "REPLICAS", "AGE"],
            ))
        return 0
    if args.kind == "pods":
        if args.name:  # pods of one job
            ns, jname = _split_ref(args.name, "job")
            pods = client.get_pods(ns, jname)
        else:
            pods = client._client.list(objects.PODS, args.namespace)  # noqa: SLF001
        rows = [
            [
                p["metadata"].get("namespace", ""),
                p["metadata"].get("name", ""),
                p.get("status", {}).get("phase", "?"),
                _age(p["metadata"].get("creationTimestamp")),
            ]
            for p in pods
        ]
        if args.output in ("json", "yaml"):
            print(_dump({"items": pods}, args.output))
        else:
            print(_table(rows, ["NAMESPACE", "NAME", "PHASE", "AGE"]))
        return 0
    raise SystemExit(f"tpuctl: unknown kind {args.kind!r} "
                     "(expected jobs|job|pods)")


def cmd_describe(args, client: TPUJobClient) -> int:
    ns, name = _split_ref(args.ref)
    job = client.get(ns, name)
    print(f"Name:       {name}")
    print(f"Namespace:  {ns}")
    print(f"State:      {_state(job)}")
    st = job.get("status", {})
    if st.get("restartCount"):
        print(f"Restarts:   {st['restartCount']}")
    for label, key in (("Started", "startTime"),
                       ("Completed", "completionTime")):
        if st.get(key):
            print(f"{label}:    {st[key]}")
    print("\nConditions:")
    conds = st.get("conditions", [])
    if conds:
        print(_table(
            [[c.get("type", ""), c.get("status", ""), c.get("reason", ""),
              c.get("message", "")[:60]] for c in conds],
            ["TYPE", "STATUS", "REASON", "MESSAGE"],
        ))
    else:
        print("  none")
    print("\nReplica statuses:")
    rs = st.get("replicaStatuses", {})
    if rs:
        print(_table(
            [[t, s.get("active", 0), s.get("succeeded", 0),
              s.get("failed", 0)] for t, s in sorted(rs.items())],
            ["ROLE", "ACTIVE", "SUCCEEDED", "FAILED"],
        ))
    else:
        print("  none")
    pods = client.get_pods(ns, name)
    print("\nPods:")
    if pods:
        print(_table(
            [[p["metadata"]["name"], p.get("status", {}).get("phase", "?")]
             for p in pods],
            ["NAME", "PHASE"],
        ))
    else:
        print("  none")
    events = client.get_events(ns, name)
    print("\nEvents (last 15):")
    if events:
        print(_table(
            [[e.get("type", ""), e.get("reason", ""),
              e.get("message", "")[:70]] for e in events[-15:]],
            ["TYPE", "REASON", "MESSAGE"],
        ))
    else:
        print("  none")
    return 0


def _load_manifest(path: str) -> dict[str, Any]:
    raw = sys.stdin.read() if path == "-" else open(path).read()
    stripped = raw.lstrip()
    if stripped.startswith("{"):
        return json.loads(raw)
    import yaml

    docs = [d for d in yaml.safe_load_all(raw) if d]
    if len(docs) != 1:
        raise SystemExit(
            f"tpuctl: expected exactly one TPUJob document, got {len(docs)}"
        )
    return docs[0]


def cmd_apply(args, client: TPUJobClient) -> int:
    job = _load_manifest(args.filename)
    if job.get("kind") != "TPUJob":
        raise SystemExit(
            f"tpuctl: manifest kind {job.get('kind')!r} is not TPUJob"
        )
    created = client.create(job)
    m = created["metadata"]
    print(f"tpujob {m['namespace']}/{m['name']} created")
    return 0


def cmd_delete(args, client: TPUJobClient) -> int:
    ns, name = _split_ref(args.ref)
    client.delete(ns, name)
    print(f"tpujob {ns}/{name} deleted")
    return 0


def _logs_request(master: str, ns: str, pod: str, params: str = ""):
    url = f"{master.rstrip('/')}/tpujobs/api/pod/{ns}/{pod}/logs{params}"
    req = urllib.request.Request(url)
    token = os.environ.get("TPU_OPERATOR_API_TOKEN")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def cmd_logs(args, master: str) -> int:
    ns, pod = _split_ref(args.ref, "pod")
    if not args.follow:
        try:
            body = _logs_request(master, ns, pod)
        except urllib.error.HTTPError as e:  # type: ignore[attr-defined]
            raise SystemExit(
                f"tpuctl: logs unavailable ({e.code}) — is the operator "
                "running with --dashboard?"
            ) from None
        sys.stdout.write(body.get("logs") or "(no logs)\n")
        return 0
    # kubectl logs -f: the server's streaming contract (?offset=&spool=)
    # returns the appended chunk since the absolute offset in the named
    # spool — byte-exact across the tail cap, and a changed spool id
    # (controller-recreated pod) restarts the stream from 0. A 404 means
    # no logs spooled YET: keep polling like kubectl does, rather than
    # dying before the pod's first line. --follow-polls bounds the loop
    # for scripts/tests; default follows until interrupted.
    offset, spool, polls = 0, "", 0
    try:
        while args.follow_polls is None or polls < args.follow_polls:
            if polls:
                time.sleep(args.follow_interval)
            polls += 1
            try:
                from urllib.parse import quote

                body = _logs_request(
                    master, ns, pod, f"?offset={offset}&spool={quote(spool)}"
                )
            except urllib.error.HTTPError as e:  # type: ignore[attr-defined]
                if e.code == 404:
                    continue
                raise SystemExit(
                    f"tpuctl: logs unavailable ({e.code}) — is the "
                    "operator running with --dashboard?"
                ) from None
            chunk = body.get("logs") or ""
            if chunk:
                sys.stdout.write(chunk)
                sys.stdout.flush()
            offset = int(body.get("offset", offset))
            spool = body.get("spool", spool)
    except KeyboardInterrupt:
        pass
    return 0


def _gang_row(g: dict[str, Any]) -> list[str]:
    return [
        g.get("key", ""),
        g.get("priorityClass", "default"),
        g.get("chips", 0),
        g.get("slices", 0),
        g.get("pods", 0),
        g.get("requeues", 0),
        f"{g.get('waitedSeconds', 0):.0f}s",
    ]


def cmd_queue(args, master: str) -> int:
    """Render /debug/scheduler: the gang-admission queue, admitted set,
    fleet usage and per-namespace quota — the operator's answer to
    `kubectl get queue` on a Volcano/Kueue cluster."""
    url = f"{master.rstrip('/')}/debug/scheduler"
    req = urllib.request.Request(url)
    token = os.environ.get("TPU_OPERATOR_API_TOKEN")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            snap = json.loads(resp.read())
    except urllib.error.HTTPError as e:  # type: ignore[attr-defined]
        raise SystemExit(
            f"tpuctl: scheduler snapshot unavailable ({e.code}) — is the "
            "operator serving with gang admission enabled?"
        ) from None
    if args.output == "json":
        print(json.dumps(snap, indent=2))
        return 0
    total = snap.get("chipsTotal") or {}
    in_use = snap.get("chipsInUse") or {}
    if total:
        print("Fleet:")
        print(_table(
            [[gen, "x".join(str(d) for d in dims), in_use.get(gen, 0),
              total[gen]]
             for gen, dims in sorted((snap.get("capacity") or {}).items())],
            ["GENERATION", "MESH", "CHIPS-USED", "CHIPS-TOTAL"],
        ))
    else:
        print("Fleet: unbounded (no --tpu-capacity declared)")
    usage = snap.get("quotaUsage") or {}
    if usage:
        print("\nQuota usage:")
        print(_table(
            [[ns, u.get("chips", 0), u.get("slices", 0)]
             for ns, u in sorted(usage.items())],
            ["NAMESPACE", "CHIPS", "SLICES"],
        ))
    header = ["GANG", "CLASS", "CHIPS", "SLICES", "PODS", "REQUEUES", "WAITED"]
    print("\nAdmitted:")
    admitted = snap.get("admitted") or []
    print(_table([_gang_row(g) for g in admitted], header)
          if admitted else "  none")
    print("\nQueued (service order):")
    queued = snap.get("queued") or []
    if queued:
        print(_table(
            [_gang_row(g) + [g.get("effectivePriority", "")] for g in queued],
            header + ["EFF-PRIORITY"],
        ))
    else:
        print("  none")
    return 0


def _health_request(master: str, path: str, body: dict | None = None):
    """GET (body None) or POST against the operator's /debug/health API.
    Mutations ride the same bearer token as every other write."""
    url = f"{master.rstrip('/')}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method="POST" if body is not None else "GET"
    )
    if body is not None:
        req.add_header("Content-Type", "application/json")
    token = os.environ.get("TPU_OPERATOR_API_TOKEN")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:  # type: ignore[attr-defined]
        detail = ""
        try:
            detail = json.loads(e.read()).get("message", "")
        except Exception:
            pass
        raise SystemExit(
            f"tpuctl: debug API {path} unavailable ({e.code}"
            + (f": {detail}" if detail else "")
            + ") — is the operator serving with this subsystem enabled?"
        ) from None


def _parse_cli_cells(raw: list[str]) -> list[list[int]]:
    """"0,0,1" → [0, 0, 1] per argument."""
    cells = []
    for item in raw:
        try:
            cells.append([int(x) for x in item.split(",") if x != ""])
        except ValueError:
            raise SystemExit(
                f"tpuctl: cell must be comma-separated ints, got {item!r}"
            ) from None
    return cells


def cmd_health(args, master: str) -> int:
    """Render /debug/health: per-cell states, counts, and the tuning the
    monitor runs with — the fleet's answer to `kubectl get nodes` plus
    `kubectl describe node` rolled into mesh coordinates."""
    snap = _health_request(master, "/debug/health")
    if args.output == "json":
        print(json.dumps(snap, indent=2))
        return 0
    counts = snap.get("counts") or {}
    if counts:
        print("Cells with open suspicion/cordons: " + ", ".join(
            f"{state}={n}" for state, n in sorted(counts.items())
        ))
    else:
        print("Fleet healthy: no cells under suspicion or cordon")
    cells = snap.get("cells") or []
    if cells:
        print()
        print(_table(
            [[c.get("generation", ""),
              ",".join(str(x) for x in c.get("cell", [])),
              c.get("state", ""),
              f"{c.get('score', 0):.1f}",
              c.get("source", ""),
              "yes" if c.get("manual") else ""]
             for c in cells],
            ["GENERATION", "CELL", "STATE", "SCORE", "SOURCE", "PINNED"],
        ))
    return 0


def cmd_ckpt(args, master: str) -> int:
    """Render /debug/ckpt: per-job checkpoint records (acked step, save
    recency, staleness, in-flight eviction barriers) — the operator-side
    view of `where would this job resume from right now?`."""
    snap = _health_request(master, "/debug/ckpt")
    if args.output == "json":
        print(json.dumps(snap, indent=2))
        return 0
    jobs = snap.get("jobs") or []
    reporting = [j for j in jobs if j.get("latestStep") is not None]
    if not reporting:
        print("No jobs with checkpoint records")
        return 0
    print(_table(
        [[j.get("key", ""),
          j.get("latestStep", ""),
          j.get("ackedAt", "") or "-",
          j.get("reportingPods", 0),
          "yes" if j.get("stale") else "",
          "evicting" if j.get("signalGen") else "",
          j.get("directory", "")[:48]]
         for j in reporting],
        ["JOB", "STEP", "ACKED", "PODS", "STALE", "BARRIER", "DIR"],
    ))
    return 0


def cmd_serve(args, master: str) -> int:
    """Render /debug/fleet: per-TPUServe replica membership (state,
    endpoint, load, version), the autoscaler's current target and last
    reason — `kubectl get deploy` for serving fleets."""
    snap = _health_request(master, "/debug/fleet")
    if args.output == "json":
        print(json.dumps(snap, indent=2))
        return 0
    fleets = snap.get("fleets") or {}
    if not fleets:
        print("No TPUServe fleets")
        return 0
    for key, fleet in sorted(fleets.items()):
        counts = (fleet.get("membership") or {}).get("counts") or {}
        auto = fleet.get("autoscale") or {}
        line = (f"{key}: target={fleet.get('target', 0)} "
                + " ".join(f"{s}={n}" for s, n in sorted(counts.items())
                           if n))
        if auto.get("enabled"):
            line += (f"  autoscale=[{auto.get('min')}..{auto.get('max')}]"
                     + (f" last: {auto['last_reason']}"
                        if auto.get("last_reason") else ""))
        # Fleet-global prefix reuse: the decode pool's advertisement
        # directory (distinct hot-prefix digests / advertising replicas).
        pfx = fleet.get("prefixes") or {}
        if pfx.get("digests"):
            line += (f"  prefixes={pfx['digests']}"
                     f"@{pfx.get('replicas_advertising', 0)} replicas")
        if pfx.get("tier_digests"):
            # KV memory hierarchy: warm host-tier digests restorable
            # across the fleet (serve/tier.py, docs/kv-tiering.md).
            line += (f"  tier={pfx['tier_digests']}"
                     f"@{pfx.get('replicas_tier_advertising', 0)}")
        print(line)
        replicas = (fleet.get("membership") or {}).get("replicas") or []
        if replicas:
            print(_table(
                [[r.get("id", ""),
                  r.get("state", ""),
                  r.get("endpoint", ""),
                  f"{r.get('activeSlots', 0)}/{r.get('maxSlots', 0)}",
                  r.get("queueDepth", 0),
                  f"{r.get('load', 0):.2f}",
                  r.get("prefixesAdvertised", 0),
                  r.get("tierPrefixesAdvertised", 0),
                  r.get("modelVersion", "") or "-",
                  r.get("watchdogRestarts", 0)]
                 for r in replicas],
                ["REPLICA", "STATE", "ENDPOINT", "SLOTS", "QUEUE",
                 "LOAD", "PFX", "TIER", "VERSION", "RESTARTS"],
            ))
        # Disaggregated fleets: the prefill pool, same shape (its QUEUE
        # column is the pool's autoscale signal — prefill backlog).
        prefill = fleet.get("prefill") or {}
        prows = (prefill.get("membership") or {}).get("replicas") or []
        if prefill:
            pcounts = (prefill.get("membership") or {}).get("counts") or {}
            pline = (f"  prefill pool: target={prefill.get('target', 0)} "
                     + " ".join(f"{s}={n}"
                                for s, n in sorted(pcounts.items())
                                if n))
            pauto = prefill.get("autoscale") or {}
            if pauto.get("enabled"):
                pline += (f"  autoscale=[{pauto.get('min')}.."
                          f"{pauto.get('max')}]"
                          + (f" last: {pauto['last_reason']}"
                             if pauto.get("last_reason") else ""))
            print(pline)
        if prows:
            print(_table(
                [[r.get("id", ""),
                  r.get("state", ""),
                  r.get("endpoint", ""),
                  r.get("queueDepth", 0),
                  f"{r.get('load', 0):.2f}",
                  r.get("modelVersion", "") or "-"]
                 for r in prows],
                ["PREFILL", "STATE", "ENDPOINT", "QUEUE", "LOAD",
                 "VERSION"],
            ))
    return 0


def cmd_trace(args, master: str) -> int:
    """Assemble ONE fleet-wide Chrome trace on stdout: /debug/traces
    fetched from every live replica of a TPUServe fleet (endpoints read
    from the operator's /debug/fleet membership) plus any ``--router``
    front, merged by wall-clock epoch and keyed by the ``request_id``
    span attribute — pipe to a file and load at ui.perfetto.dev."""
    from tf_operator_tpu.fleet.router import http_fetch_traces
    from tf_operator_tpu.runtime.tracing import merge_chrome_traces

    snap = _health_request(master, "/debug/fleet")
    fleets = snap.get("fleets") or {}
    fleet = fleets.get(args.fleet)
    if fleet is None and "/" not in args.fleet:
        # Accept the bare name when it is unambiguous (keys are ns/name).
        matches = [k for k in fleets if k.split("/", 1)[-1] == args.fleet]
        if len(matches) == 1:
            fleet = fleets[matches[0]]
    if fleet is None:
        raise SystemExit(
            f"tpuctl: no TPUServe fleet {args.fleet!r} "
            f"(known: {', '.join(sorted(fleets)) or 'none'})"
        )

    docs = []
    if args.router:
        try:
            docs.append(("router", http_fetch_traces(args.router)))
        except (OSError, ValueError) as exc:
            print(f"tpuctl: router {args.router} unreachable: {exc}",
                  file=sys.stderr)
    skipped = []
    live = [rep for rep in
            (fleet.get("membership") or {}).get("replicas") or []
            if rep.get("state") != "dead" and rep.get("endpoint")]
    if live:
        from concurrent.futures import ThreadPoolExecutor

        def fetch_one(rep):
            try:
                # The router's own fetch helper — one implementation of
                # the /debug/traces wire contract.
                return rep, http_fetch_traces(rep["endpoint"])
            except (OSError, ValueError):
                return rep, None

        # Concurrent like the router's merge: a wedged replica costs
        # one timeout, not one per replica.
        with ThreadPoolExecutor(min(8, len(live))) as pool:
            for rep, doc in pool.map(fetch_one, live):
                if doc is None:
                    skipped.append(rep.get("id"))
                else:
                    docs.append((f"replica:{rep.get('id')}", doc))
    if skipped:
        print(f"tpuctl: skipped unreachable replica(s): "
              f"{', '.join(str(s) for s in skipped)}", file=sys.stderr)
    merged = merge_chrome_traces(docs)
    print(f"tpuctl: merged {len(docs)} source(s), "
          f"{sum(1 for e in merged['traceEvents'] if e.get('ph') == 'X')}"
          f" span(s)", file=sys.stderr)
    print(json.dumps(merged))
    return 0


def cmd_cordon(args, master: str, verb: str) -> int:
    """cordon/uncordon/drain: POST the verb to the operator. Drain carries
    a maintenance deadline (--at seconds from now) — the injected stand-in
    for a GCE maintenance event."""
    body: dict = {
        "generation": args.generation,
        "cells": _parse_cli_cells(args.cells),
    }
    if verb == "drain" and args.at is not None:
        body["deadlineSeconds"] = args.at
    out = _health_request(master, f"/debug/health/{verb}", body)
    cells = ";".join(",".join(str(x) for x in c) for c in out.get("cells", []))
    print(f"{verb}: {out.get('generation')} [{cells}]")
    migrated = out.get("migrated") or []
    for key in migrated:
        print(f"  migrating gang {key} off the cells")
    return 0


def cmd_wait(args, client: TPUJobClient) -> int:
    ns, name = _split_ref(args.ref)
    if args.condition == "Deleted":
        client.wait_for_delete(ns, name, timeout=args.timeout)
        print(f"tpujob {ns}/{name} deleted")
        return 0
    # Every wait also watches the terminal conditions: a job that goes
    # Failed (or Succeeded) while we wait for anything else must return
    # promptly with rc 1, not block until timeout — scripts rely on
    # `tpuctl wait ... --for <cond> && next-step`. This covers both the
    # Succeeded/Failed cross-watch and non-terminal targets (Running,
    # Created) on a job that races to terminal before reaching them.
    expected = tuple(dict.fromkeys(
        (args.condition, "Succeeded", "Failed")
    ))
    got = client.wait_for_condition(
        ns, name, expected, timeout=args.timeout
    )
    print(f"tpujob {ns}/{name}: {_state(got)}")
    # rc 0 iff the REQUESTED condition is True on the returned object —
    # not _state(), whose ranking would fail `--for Created` on a job
    # already Running. Two asymmetric terminal races: a job that raced
    # past a non-terminal target to Succeeded necessarily passed through
    # it (the status engine flips Running to False on terminal, so the
    # condition check alone would flake on fast jobs) — rc 0; one that
    # went Failed first gives no such guarantee — rc 1.
    reached = any(
        c.get("type") == args.condition and c.get("status") == "True"
        for c in got.get("status", {}).get("conditions", [])
    )
    if (not reached and args.condition not in ("Succeeded", "Failed")
            and _state(got) == "Succeeded"):
        reached = True
    return 0 if reached else 1


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpuctl", description=__doc__)
    p.add_argument("--master", default=DEFAULT_MASTER,
                   help=f"operator API URL (default {DEFAULT_MASTER})")
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("get", help="list/get jobs or pods")
    g.add_argument("kind", help="jobs | job | pods | tpujob(s)")
    g.add_argument("name", nargs="?", default=None,
                   help="NS/NAME (for `get job`; job selector for pods)")
    g.add_argument("-n", "--namespace", default=None)
    g.add_argument("-o", "--output", choices=("table", "json", "yaml"),
                   default="table")
    g.add_argument("-w", "--watch", action="store_true",
                   help="after listing, stream update rows (kubectl -w)")
    g.add_argument("--watch-events", type=int, default=None,
                   help="with -w: exit after N events (for scripts)")

    d = sub.add_parser("describe", help="show a job in detail")
    d.add_argument("ref", help="NAMESPACE/NAME")

    a = sub.add_parser("apply", help="create a TPUJob from a manifest")
    a.add_argument("-f", "--filename", required=True,
                   help="json or yaml manifest (- = stdin)")

    rm = sub.add_parser("delete", help="delete a job")
    rm.add_argument("ref", help="NAMESPACE/NAME")

    lg = sub.add_parser("logs", help="pod logs (via the dashboard API)")
    lg.add_argument("ref", help="NAMESPACE/POD")
    lg.add_argument("-f", "--follow", action="store_true",
                    help="stream appended log lines (kubectl logs -f)")
    lg.add_argument("--follow-interval", type=float, default=1.0,
                    help="poll interval seconds for --follow")
    lg.add_argument("--follow-polls", type=int, default=None,
                    help="stop --follow after N polls (scripts/tests; "
                         "default: until interrupted)")

    w = sub.add_parser("wait", help="block until a job condition")
    w.add_argument("ref", help="NAMESPACE/NAME")
    w.add_argument("--for", dest="condition", default="Succeeded",
                   help="Succeeded | Failed | Running | Created | Deleted")
    w.add_argument("--timeout", type=float, default=300.0)

    q = sub.add_parser("queue", help="gang-admission queue / fleet usage")
    q.add_argument("-o", "--output", choices=("table", "json"),
                   default="table")

    h = sub.add_parser("health", help="fleet health: cell states / cordons")
    h.add_argument("-o", "--output", choices=("table", "json"),
                   default="table")

    ck = sub.add_parser("ckpt",
                        help="checkpoint registry: acked steps / barriers")
    ck.add_argument("-o", "--output", choices=("table", "json"),
                    default="table")

    sv = sub.add_parser("serve",
                        help="TPUServe fleets: replica membership / "
                             "autoscale targets")
    sv.add_argument("-o", "--output", choices=("table", "json"),
                    default="table")

    tr = sub.add_parser("trace",
                        help="merge a TPUServe fleet's /debug/traces "
                             "into one Chrome trace on stdout")
    tr.add_argument("fleet", help="fleet key (NS/NAME, or bare NAME "
                                  "when unambiguous)")
    tr.add_argument("--router", default=None, metavar="HOST:PORT",
                    help="also include this fleet router front's "
                         "/debug/traces (dispatch/failover spans)")
    for verb, help_text in (
        ("cordon", "withdraw mesh cells from placement (operator-pinned)"),
        ("uncordon", "return mesh cells to service"),
        ("drain", "maintenance notice: cordon cells + migrate gangs now"),
    ):
        vp = sub.add_parser(verb, help=help_text)
        vp.add_argument("generation", help="TPU generation, e.g. v4")
        vp.add_argument("cells", nargs="+",
                        help='mesh cells as "x,y[,z]", e.g. 0,0,1')
        if verb == "drain":
            vp.add_argument("--at", type=float, default=None, metavar="SECS",
                            help="maintenance deadline, seconds from now "
                                 "(repair probing starts after it)")

    args = p.parse_args(argv)
    if args.cmd == "logs":
        return cmd_logs(args, args.master)
    if args.cmd == "queue":
        return cmd_queue(args, args.master)
    if args.cmd == "health":
        return cmd_health(args, args.master)
    if args.cmd == "ckpt":
        return cmd_ckpt(args, args.master)
    if args.cmd == "serve":
        return cmd_serve(args, args.master)
    if args.cmd == "trace":
        return cmd_trace(args, args.master)
    if args.cmd in ("cordon", "uncordon", "drain"):
        return cmd_cordon(args, args.master, args.cmd)
    client = TPUJobClient(RestClusterClient(args.master))
    try:
        return {
            "get": cmd_get,
            "describe": cmd_describe,
            "apply": cmd_apply,
            "delete": cmd_delete,
            "wait": cmd_wait,
        }[args.cmd](args, client)
    except (TimeoutError, TimeoutError_) as e:
        # TimeoutError_ is the client's own wait-timeout type (a plain
        # Exception subclass, NOT builtins.TimeoutError).
        print(f"tpuctl: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
