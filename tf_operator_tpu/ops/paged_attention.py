"""Pallas TPU paged-attention decode kernel: the block table, consumed
directly.

Every serving mode since the continuous engine landed bottoms out in
``_decode_attend_paged`` (models/transformer.py), whose GATHER path
materializes the pool back into the dense ``[b, max_seq_len, KV, Dh]``
layout each step — correct (it is what makes the bit-identity pins
cheap) but its HBM traffic scales with max-S, not actual lane lengths.
This kernel walks each lane's block list instead:

- the grid is ``(batch, table_len)`` with the table walk sequential; the
  block table, the per-lane counters, and the per-lane block counts
  ``nblk = ceil((pos + t) / blk)`` ride scalar prefetch, so the K/V pool
  BlockSpec index maps resolve ``table[b, j]`` on the host side of the
  pipeline — the kernel streams exactly the pool blocks a lane owns;
- beyond a lane's ``nblk`` the index map CLAMPS to the lane's last
  block: an unchanged block index means pallas skips the HBM->VMEM copy
  (the same trick the flash kernel's causal skip uses), so per-step HBM
  traffic is bounded by actual lane lengths — the whole point;
- kv-int8 dequant is fused with the exact dense factoring the engine
  pins: raw int8 keys enter the score dot (cast bf16, exact — |k8| <=
  127 needs 7 mantissa bits) and are rescaled on the score tensor; the
  value scale folds into the post-softmax probabilities;
- multi-query: ``t >= 1`` query rows per lane share one table walk, so
  the speculative VERIFY chunk (K+1 positions) rides the same kernel.

EXACTNESS over elegance — why this is copy-then-finalize, not online
softmax: the engine's reason to exist is the bit-identity pin chain
(paged == dense == solo, kv8 included), and a rescaling online softmax
(flash-style ``acc * alpha`` carries) cannot reproduce the gather
path's full-row softmax bit-for-bit — every chunk boundary perturbs
rounding. So the sequential grid steps only COPY each fetched block
into a VMEM-resident ``[S, KV, Dh]`` buffer (zero-filling the columns
of skipped blocks), and the last step runs per-KV-head score/mask/
softmax/value contractions with the same operand dtypes, reduction
extents, and op order as the gather oracle. Masked columns are exactly
``-1e30 -> softmax 0.0`` on both paths, so the zero-filled (kernel) vs
garbage-block (gather) column contents cancel bitwise. The HBM savings
— the decode bottleneck — are untouched by this choice: only VMEM-
resident VPU/MXU work runs at full S extent. The trade is a VMEM
ceiling of O(max_seq_len * KV/tp * Dh) per core (``paged_attend_
supported`` gates it; tensor parallelism divides it by tp).

The gather path stays the default and the reference oracle
(``TransformerConfig.kv_attend="gather"``); this kernel is selected
with ``kv_attend="pallas"`` and is pinned bit-identical to the oracle
in f32 CPU interpret mode by tests/test_paged_attention.py across
block geometry x {dense, kv8} x {single-token, K+1 VERIFY} x lane
spread. Interpret-mode selection follows flash_attention's discipline:
``on_tpu_backend()`` is the single TPU detection.

Tensor parallelism: a pallas call has no SPMD partitioning rule, so at
tp > 1 the kernel runs under shard_map over the tp axis — pool
``P(None, None, 'tp', None)``, query/output head-sharded, table and
counters replicated, ZERO collectives inside the attend (per-KV-head
math is shard-local, exactly the gather path's layout story).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tf_operator_tpu import parallel as parallel_compat
from tf_operator_tpu.ops.flash_attention import (
    _CompilerParams,
    on_tpu_backend,
)

_NEG_INF = -1e30

# VMEM ceiling for the copy-then-finalize buffers (K + V + kv8 scale
# sidecars at full S extent, per core). ~16 MiB is a core's VMEM; leave
# headroom for the q/out/pool-block tiles and Mosaic padding.
VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def paged_attend_vmem_bytes(
    max_seq_len: int,
    kv_heads: int,
    head_dim: int,
    *,
    kv_int8: bool = False,
    dtype_bytes: int = 2,
    tp: int = 1,
) -> int:
    """Unpadded bytes of the kernel's persistent VMEM scratch: the
    ``[S, KV/tp, Dh]`` key buffer (storage dtype; bf16 under kv8), the
    f32 value buffer, and the two f32 ``[S, KV/tp]`` scale sidecars
    under kv8. Pure arithmetic — usable without touching a device."""
    kv_local = kv_heads // tp if tp > 1 and kv_heads % tp == 0 else kv_heads
    k_bytes = 2 if kv_int8 else dtype_bytes
    total = max_seq_len * kv_local * head_dim * (k_bytes + 4)
    if kv_int8:
        total += 2 * max_seq_len * kv_local * 4
    return total


def paged_attend_supported(
    max_seq_len: int,
    kv_heads: int,
    head_dim: int,
    *,
    kv_int8: bool = False,
    dtype_bytes: int = 2,
    tp: int = 1,
    budget: int = VMEM_BUDGET_BYTES,
) -> bool:
    """True when paged_attend() will accept this geometry: the
    copy-then-finalize buffers must fit the VMEM budget. The single
    source of truth the config selector consults — a config requesting
    ``kv_attend="pallas"`` for an unsupported geometry fails loudly at
    trace time (never a silent gather fallback: a bench would measure
    the wrong kernel)."""
    return paged_attend_vmem_bytes(
        max_seq_len, kv_heads, head_dim,
        kv_int8=kv_int8, dtype_bytes=dtype_bytes, tp=tp,
    ) <= budget


def _paged_kernel(
    # scalar prefetch
    table_ref, idx_ref, nblk_ref,
    # inputs
    q_ref, kp_ref, vp_ref, *rest,
    blk: int, t: int, g: int, nj: int, kv8: bool, structural: bool,
):
    """Grid cell (b, j). Phase A (every j): land pool block j of lane b
    in the persistent buffers — the lane's own data below ``nblk[b]``,
    zeros above it (every column is written each lane, so no stale VMEM
    can leak across lanes and the compiled path can never read
    uninitialized scratch as NaN). Phase B (last j): the full-row
    attention.

    Two finalize bodies, same math: ``structural`` (interpret mode)
    mirrors the gather oracle's einsum subscripts exactly — same
    dot_general batch/contract structure minus the leading batch dim —
    which is what makes the f32 CPU bitwise pin hold (XLA picks its
    reduction strategy from the dot SHAPE; a merged-rows 2-D dot with a
    single row lowers as a gemv whose accumulation order differs from
    the batched einsum's by 1 ulp). The compiled TPU path uses a static
    per-KV-head loop of plain 2-D dots instead — Mosaic-friendly MXU
    work (it cannot lower rank-4 batched dot_generals) — bitwise parity
    across BACKENDS was never on the table (MXU vs host float paths),
    the oracle pin is an interpret-mode contract."""
    if kv8:
        ksp_ref, vsp_ref, o_ref, k_buf, v_buf, ks_buf, vs_buf = rest
    else:
        o_ref, k_buf, v_buf = rest
        ks_buf = vs_buf = None
    b, j = pl.program_id(0), pl.program_id(1)
    rows = pl.ds(j * blk, blk)
    live = j < nblk_ref[b]

    @pl.when(live)
    def _copy():
        k_buf[rows, :, :] = kp_ref[0].astype(k_buf.dtype)
        v_buf[rows, :, :] = vp_ref[0].astype(jnp.float32)
        if kv8:
            ks_buf[rows, :] = ksp_ref[0]
            vs_buf[rows, :] = vsp_ref[0]

    @pl.when(jnp.logical_not(live))
    def _zero():
        k_buf[rows, :, :] = jnp.zeros_like(k_buf[rows, :, :])
        v_buf[rows, :, :] = jnp.zeros_like(v_buf[rows, :, :])
        if kv8:
            ks_buf[rows, :] = jnp.zeros_like(ks_buf[rows, :])
            vs_buf[rows, :] = jnp.zeros_like(vs_buf[rows, :])

    @pl.when(j == nj - 1)
    def _attend():
        s_len = nj * blk
        kv_local = k_buf.shape[1]
        dh = k_buf.shape[2]
        if structural:
            # Interpret mode: the oracle's einsums verbatim (its batch
            # dim b is this grid cell; kv stays a dot batch dim).
            qg = q_ref[0].reshape(kv_local, t, g, dh)  # rows (t, g)
            s = jnp.einsum(
                "kqgd,skd->kgqs", qg, k_buf[:, :, :],
                preferred_element_type=jnp.float32,
            )
            if kv8:
                s = s * ks_buf[:, :].T[:, None, None, :]
            s = s * (dh ** -0.5)
            # Query row i (absolute position idx[b] + i) sees keys at
            # positions <= idx[b] + i; columns past the lane's length —
            # including every zero-filled skipped block — mask to the
            # oracle's exact -1e30 and softmax to exact 0.0.
            row_t = lax.broadcasted_iota(
                jnp.int32, (kv_local, g, t, s_len), 2
            )
            col = lax.broadcasted_iota(
                jnp.int32, (kv_local, g, t, s_len), 3
            )
            s = jnp.where(col <= idx_ref[b] + row_t, s, _NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            if kv8:
                # Value scale folds into the probabilities (exact 0s at
                # masked columns stay exact 0s).
                p = p * vs_buf[:, :].T[:, None, None, :]
            out = jnp.einsum("kgqs,skd->qkgd", p, v_buf[:, :, :])
            o_ref[0] = out.transpose(1, 0, 2, 3).reshape(
                kv_local, t * g, dh
            )
            return
        # Compiled path: static python loop over KV heads — each
        # iteration is plain 2-D MXU work (Mosaic-friendly), and
        # per-head independence is what keeps the tp shard_map
        # collective-free.
        for kk in range(kv_local):
            qh = q_ref[0, kk, :, :]  # [t*g, Dh], rows (t, g)-ordered
            s = jnp.dot(
                qh, k_buf[:, kk, :].T,
                preferred_element_type=jnp.float32,
            )
            s = s.reshape(t, g, s_len)
            if kv8:
                # The dense kv8 factoring: scores = (q . k8) * k_scale,
                # the scale applied on the score tensor BEFORE 1/sqrt(d)
                # — same order as the oracle, so the rounding matches.
                s = s * ks_buf[:, kk][None, None, :]
            s = s * (dh ** -0.5)
            row_t = lax.broadcasted_iota(jnp.int32, (t, g, s_len), 0)
            col = lax.broadcasted_iota(jnp.int32, (t, g, s_len), 2)
            s = jnp.where(col <= idx_ref[b] + row_t, s, _NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            if kv8:
                p = p * vs_buf[:, kk][None, None, :]
            o_ref[0, kk, :, :] = jnp.dot(
                p.reshape(t * g, s_len), v_buf[:, kk, :],
                preferred_element_type=jnp.float32,
            )


def _pool_index(block_shape):
    """Index map for pool-side inputs: fetch lane b's j-th block; past
    the lane's block count, CLAMP to its last block — an unchanged
    block index lets pallas skip the HBM->VMEM copy, which is what
    bounds per-step HBM traffic by actual lane lengths."""
    zeros = (0,) * (len(block_shape) - 1)
    return pl.BlockSpec(
        block_shape,
        lambda b, j, tbl, idx, nblk: (
            tbl[b, jnp.minimum(j, nblk[b] - 1)],
        ) + zeros,
    )


def _lane_index(block_shape):
    """Index map for lane-side q/out: one block per lane, constant
    across the table walk (fetched/flushed once per lane)."""
    zeros = (0,) * (len(block_shape) - 1)
    return pl.BlockSpec(
        block_shape, lambda b, j, tbl, idx, nblk: (b,) + zeros
    )


def _run_paged(table, idx, nblk, qr, pool_k, pool_v, *scale_pools,
               blk: int, t: int, g: int, interpret: bool):
    b, kv, rows, dh = qr.shape
    nj = table.shape[1]
    kv8 = bool(scale_pools)
    kernel = functools.partial(
        _paged_kernel, blk=blk, t=t, g=g, nj=nj, kv8=kv8,
        structural=interpret,
    )
    in_specs = [
        _lane_index((1, kv, rows, dh)),      # q
        _pool_index((1, blk, kv, dh)),       # key pool
        _pool_index((1, blk, kv, dh)),       # value pool
    ]
    scratch = [
        pltpu.VMEM((nj * blk, kv, dh), pool_k.dtype
                   if not kv8 else jnp.bfloat16),
        pltpu.VMEM((nj * blk, kv, dh), jnp.float32),
    ]
    if kv8:
        in_specs += [
            _pool_index((1, blk, kv)),       # key scale pool
            _pool_index((1, blk, kv)),       # value scale pool
        ]
        scratch += [
            pltpu.VMEM((nj * blk, kv), jnp.float32),
            pltpu.VMEM((nj * blk, kv), jnp.float32),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, nj),
        in_specs=in_specs,
        out_specs=_lane_index((1, kv, rows, dh)),
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv, rows, dh), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(table, idx, nblk, qr, pool_k, pool_v, *scale_pools)


def paged_attend(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    block_table: jax.Array,
    index: jax.Array,
    *,
    k_scale_pool: jax.Array | None = None,
    v_scale_pool: jax.Array | None = None,
    mesh=None,
    tp_axis: str = "tp",
    dp_axis: str = "dp",
    interpret: bool | None = None,
) -> jax.Array:
    """Paged decode attention straight off the block table.

    q: [b, t, H, Dh] (t >= 1 — the speculative VERIFY chunk is just
    t = K+1); pool_k/pool_v: [nb, blk, KV, Dh] (int8 when the scale
    pools are given); block_table: [b, table_len] int32; index: [b]
    int32 PRE-update counters (query row i of lane b sits at absolute
    position index[b] + i). Returns [b, t, H, Dh] float32 — the caller
    applies the storage-dtype cast, exactly like the gather oracle.

    Raises ValueError when the geometry exceeds the VMEM budget — the
    selector must not silently fall back (see paged_attend_supported).
    """
    if interpret is None:
        interpret = not on_tpu_backend()
    b, t, h, dh = q.shape
    nb, blk, kv, _ = pool_k.shape
    if t < 1:
        raise ValueError(f"t={t}: need at least one query row per lane")
    if h % kv:
        raise ValueError(f"n_heads={h} must be a multiple of KV={kv}")
    g = h // kv
    table_len = block_table.shape[1]
    kv8 = k_scale_pool is not None
    if kv8 != (v_scale_pool is not None):
        raise ValueError("kv8 needs BOTH scale pools (or neither)")
    tp = (mesh.shape.get(tp_axis, 1) if mesh is not None else 1)
    if tp > 1 and kv % tp:
        # The gather oracle degrades to a replicated einsum here; a
        # pallas call has no SPMD partitioning rule to degrade WITH, so
        # fail loudly instead of compiling something untileable.
        raise ValueError(
            f"paged_attend: KV={kv} does not tile tp={tp} — use "
            "kv_attend='gather' for this mesh"
        )
    dp = (mesh.shape.get(dp_axis, 1) if mesh is not None else 1)
    # A dp (batch-parallel) mesh axis splits the LANE axis of the
    # shard_map grid (the pod-scale tp×dp engine slot-shards its
    # lanes): each (dp, tp) cell runs the kernel over its own slot
    # slice. The POOL stays dp-UNMENTIONED — every cell sees the whole
    # pool, so the table's GLOBAL block indices stay valid inside the
    # kernel unchanged (the per-step dp all-gather of the pool is the
    # documented cost of the pallas path at dp>1; the gather attend
    # keeps the pool shard-local instead).
    bshard = dp > 1 and b % dp == 0
    shard = (tp > 1 and kv % tp == 0) or bshard
    if not paged_attend_supported(
        table_len * blk, kv, dh,
        kv_int8=kv8, dtype_bytes=pool_k.dtype.itemsize,
        tp=tp if tp > 1 and kv % tp == 0 else 1,
    ):
        raise ValueError(
            f"paged_attend: S={table_len * blk} x KV={kv}"
            f"{f'/tp={tp}' if shard else ''} x Dh={dh} "
            f"(kv8={kv8}) exceeds the VMEM budget "
            f"({VMEM_BUDGET_BYTES} bytes) — use kv_attend='gather'"
        )
    idx = index.astype(jnp.int32)
    nblk = (idx + t + blk - 1) // blk  # ceil: per-lane block count >= 1
    # [b, t, H, Dh] -> [b, KV, t*g, Dh]: head h = (kk, gg) splits as in
    # the oracle's q.reshape(b, t, kv, g, dh); rows are (t, g)-ordered.
    qr = q.reshape(b, t, kv, g, dh).transpose(0, 2, 1, 3, 4)
    qr = qr.reshape(b, kv, t * g, dh)
    run = functools.partial(_run_paged, blk=blk, t=t, g=g,
                            interpret=bool(interpret))
    scale_pools = (k_scale_pool, v_scale_pool) if kv8 else ()
    if shard:
        P = jax.sharding.PartitionSpec
        hdim = tp_axis if tp > 1 else None
        bdim = dp_axis if bshard else None
        pool_spec = P(None, None, hdim, None)
        lane_spec = P(bdim, hdim, None, None)
        in_specs = [P(bdim, None), P(bdim), P(bdim), lane_spec,
                    pool_spec, pool_spec]
        if kv8:
            in_specs += [P(None, None, hdim)] * 2
        out = parallel_compat.shard_map(
            run, mesh=mesh,
            in_specs=tuple(in_specs), out_specs=lane_spec,
            check_vma=False,
        )(block_table, idx, nblk, qr, pool_k, pool_v, *scale_pools)
    else:
        out = run(block_table, idx, nblk, qr, pool_k, pool_v,
                  *scale_pools)
    out = out.reshape(b, kv, t, g, dh).transpose(0, 2, 1, 3, 4)
    return out.reshape(b, t, h, dh)
