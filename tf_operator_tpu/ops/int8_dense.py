"""Pallas int8 weight-only matmul: dequantize IN VMEM, halve decode HBM.

Single-token decode is HBM-read-bound: every step re-reads every weight,
so tokens/sec scales with bytes-per-weight. Naive int8 storage does NOT
help — XLA hoists the int8->float convert out of the decode scan, so the
loop carry holds full-precision weights and streams them every step (the
round-3 negative result, docs/perf.md "Explored and rejected"). The fix
is a kernel that reads the int8 weights from HBM itself and dequantizes
in VMEM, where XLA cannot hoist: pallas pipelines [k, block_n] int8 tiles
in, upcasts in-register, runs the MXU dot in bf16 with f32 accumulation,
and scales the [m, block_n] output by the per-output-channel scale —
halving decode weight traffic vs bf16 (4x vs f32).

Quantization is symmetric per-output-channel (absmax / 127), the
standard weight-only scheme: activations stay bf16, so the only numerics
change is weight rounding (~0.4% RMS per channel).

The reference contains no kernels at all (SURVEY.md §2.9); this op backs
``TransformerConfig.int8_decode`` (models/transformer.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tf_operator_tpu.ops.flash_attention import on_tpu_backend

_LANE = 128  # TPU lane width: last block dim must align to it


def quantize_int8(w: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-output-channel int8 quantization of a 2-D [k, n]
    weight: returns (w_q int8 [k, n], scale f32 [n]) with
    dequant(w_q, scale) = w_q * scale ~= w."""
    if w.ndim != 2:
        raise ValueError(f"quantize_int8 takes [k, n], got {w.shape}")
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def _int8_matmul_kernel(x_ref, w_ref, s_ref, o_ref):
    # One [m, block_n] output tile: full-k dot of bf16 activations against
    # the int8 tile upcast HERE (in VMEM — the whole point), then the
    # per-channel scale on the small output tile (cheaper than scaling
    # the [k, block_n] weights, algebraically identical).
    acc = jnp.dot(
        x_ref[...], w_ref[...].astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )
    o_ref[...] = acc * s_ref[...]  # s_ref is [1, block_n]; broadcasts


@functools.partial(
    jax.jit, static_argnames=("block_n", "interpret", "out_dtype")
)
def int8_matmul(
    x: jax.Array, w_q: jax.Array, scale: jax.Array, *,
    block_n: int = 512, interpret: bool = False, out_dtype=jnp.float32,
) -> jax.Array:
    """x [m, k] (bf16/f32) @ dequant(w_q [k, n] int8, scale [n]) -> [m, n].

    Grid over n tiles; each program holds x fully (decode m is small) and
    one [k, block_n] int8 tile. f32 accumulation; ``out_dtype`` casts the
    result (bf16 for hidden layers, f32 for the logits head).
    """
    m, k = x.shape
    k2, n = w_q.shape
    if k != k2 or scale.shape != (n,):
        raise ValueError(f"shape mismatch: {x.shape} @ {w_q.shape}, "
                         f"scale {scale.shape}")
    bn = min(block_n, n)
    if n % bn:
        raise ValueError(f"n={n} not divisible by block_n={bn}")
    out = pl.pallas_call(
        _int8_matmul_kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((m, k), lambda i: (0, 0)),
            pl.BlockSpec((k, bn), lambda i: (0, i)),
            # Scale rides as [1, n]: Mosaic tiles trailing dims, so a 2-D
            # lane-aligned block beats a bare [n] vector.
            pl.BlockSpec((1, bn), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret,
    )(x.astype(jnp.bfloat16), w_q, scale.reshape(1, n))
    return out.astype(out_dtype)


def int8_matmul_xla(
    x: jax.Array, w_q: jax.Array, scale: jax.Array, *, out_dtype=jnp.float32
) -> jax.Array:
    """XLA reference path (also the non-TPU fallback): numerically the
    kernel's exact formula. Inside a decode scan XLA hoists the upcast
    (full-precision weights in the carry — no traffic saving); correct,
    just not the optimization."""
    acc = jnp.dot(
        x.astype(jnp.bfloat16), w_q.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )
    return (acc * scale[None, :]).astype(out_dtype)


def int8_apply(
    x: jax.Array, w_q: jax.Array, scale: jax.Array, *, out_dtype=jnp.float32
) -> jax.Array:
    """Dispatch: Pallas kernel on TPU when n tiles to the lane width,
    XLA formula otherwise. x may be [..., k]; output [..., n]."""
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    k, n = w_q.shape
    if on_tpu_backend() and n % _LANE == 0 and k % _LANE == 0:
        # Mosaic's bf16 min tile is (16, 128): pad the (tiny) decode batch
        # up to the sublane minimum and slice back — activation rows are
        # KBs where the weights are MBs, so the pad is free.
        m = x2.shape[0]
        pad = (-m) % 16
        if pad:
            x2 = jnp.pad(x2, ((0, pad), (0, 0)))
        bn = 512 if n % 512 == 0 else _LANE
        out = int8_matmul(x2, w_q, scale, block_n=bn, out_dtype=out_dtype)
        if pad:
            out = out[:m]
    else:
        out = int8_matmul_xla(x2, w_q, scale, out_dtype=out_dtype)
    return out.reshape(*lead, n)
