"""Pallas TPU flash attention (forward + custom-VJP backward), streaming.

The single-device hot op of the training stack. The reference delegates all
compute to the TensorFlow runtime inside user containers (SURVEY.md: zero
native/kernel code in-repo); here the framework owns its compute path, so
the attention inner loop is a hand-written TPU kernel:

- fully blocked: the grid walks (batch, head, q_block, kv_block); Q, K, V,
  dO only ever enter VMEM one [block, head_dim] tile at a time (pallas
  pipelines the HBM->VMEM streams across grid steps), and the softmax
  statistics / output accumulators live in VMEM scratch that persists
  across the innermost (sequential) kv dimension. Nothing is resident at
  O(T) — sequence length is bounded by HBM, not VMEM.
- MXU-friendly: all contractions via jnp.dot with
  preferred_element_type=float32; bf16 inputs supported.
- causal skip: masked grid cells are predicated off with pl.when AND their
  BlockSpec index maps clamp to the diagonal, so an unchanged block index
  lets pallas skip the HBM copy too — above-diagonal cells cost neither
  FLOPs nor bandwidth (~2x for LM training).
- backward = two streaming kernels (dq; dk/dv) recomputing probabilities
  from the saved logsumexp — the standard flash recomputation trade (HBM
  bandwidth is the bottleneck, FLOPs are cheap on the MXU).

Kernels run in [batch, heads, seq, head_dim] layout so Mosaic's tiling
constraint (block's trailing dims must be sublane/lane aligned) falls on
(seq_block, head_dim); the public API takes the framework convention
[batch, seq, heads, head_dim] (parallel/ring_attention.py) and transposes
at the boundary (XLA folds the transpose into neighboring ops). For
sequences sharded across chips, ring attention bounds its own per-chip
memory with chunked streaming softmax (ring_attention(kv_chunk=...)); this
kernel is the single-device path ops.attention dispatches to.

Falls back transparently (ops/__init__.attention) to the XLA reference
implementation when shapes don't tile or when not on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_BLOCK_CANDIDATES = (256, 128, 64, 32, 16, 8)

# Sanity bound on grid size / compile time, NOT a VMEM limit (per-program
# VMEM is O(block * head_dim) regardless of sequence length).
MAX_SEQ_LEN = 1 << 20


def on_tpu_backend() -> bool:
    """True when the default backend drives TPU hardware — including
    tunnel/plugin platforms whose backend NAME is not "tpu" (e.g. a
    forwarding plugin): fall back to sniffing the device kind. This is the
    single TPU-detection used for BOTH kernel dispatch (ops.attention) and
    interpret-mode selection below — if they ever diverged, a plugin
    platform would run the Pallas kernel in the interpreter, orders of
    magnitude slower than the XLA path it replaced."""
    if jax.default_backend() == "tpu":
        return True
    try:
        d = jax.devices()[0]
        kind = (getattr(d, "device_kind", "") or "").lower()
        plat = (getattr(d, "platform", "") or "").lower()
        return "tpu" in kind or "tpu" in plat
    except Exception:
        return False


def select_block(tq: int, tk: int, *, compiled: bool = False,
                 max_block: int = 256) -> int | None:
    """Largest KV block that tiles BOTH sequence lengths, or None.

    This is the single source of truth for flash dispatchability: the KV
    block must divide both lengths (the Q block is then grown
    independently — see select_block_pair), and under the Mosaic lowering
    (compiled=True) a trailing-two BlockSpec dim must be a multiple of 128
    or equal to the whole dimension on *that* side; interpret mode (CPU
    CI) has no such limit.
    """
    for b in _BLOCK_CANDIDATES:
        if b > max_block or tq % b or tk % b:
            continue
        if compiled and not (
            (b % 128 == 0 or b == tq) and (b % 128 == 0 or b == tk)
        ):
            continue
        return b
    if (
        compiled
        and tq == tk
        and tq % 16 == 0  # bf16 sublane alignment
        # the kernels materialize an f32 [block, block] score tile in VMEM;
        # cap the single-block fallback so it stays ~1 MiB, not ~16 MiB
        and tq <= 512
    ):
        return tq  # single block: equal-to-dim is always a legal BlockSpec
    return None


# Q-block growth cap: the score tile is [bq, bk] f32 in VMEM (1024x256 =
# 1 MiB, well inside a core's ~16 MiB) and the Q-side accumulators are
# [bq, head_dim] f32. Growing bq amortizes the K/V HBM streaming — per
# grid cell the kernel moves O(bk*d) K/V bytes for O(bq*bk*d) FLOPs, so
# arithmetic intensity scales linearly in bq. Measured on hardware
# (window_r05 flashblocks probe, 8k causal fwd+bwd, b4): bq256 9.0,
# bq512 11.0, bq1024 14.0 TFLOP/s — so the cap sits at 1024.
# Status: the interleaved probe_qblock run is the pending confirmation of
# that single-shot measurement; revert trigger is dispatch_auto failing
# to track direct_bq1024 (i.e. the auto path losing to the direct-dispatch
# bq1024 leg on the same probe), in which case drop the cap back to 512.
# The qblock stage now runs at the FRONT of window_autorun's unmeasured
# set (its old slot sat behind the 3600s bench_full and was never reached
# in r05), so the next UP window produces this arbitration data first.
# Re-checked (PR 9, 2026-08-03): window_r05 still carries only the
# single-shot flashblocks line (bq256 9.0 / bq512 11.0 / bq1024 14.0) —
# no probe_qblock arbitration output has landed, so the trigger stays
# OPEN and the cap stays 1024 on the strength of the single-shot data.
# Re-checked (PR 10, 2026-08-03): unchanged — window_r05 remains the
# newest window and holds no probe_qblock output; the qblock stage is
# still queued at the front of window_autorun's unmeasured set for the
# next hardware window, and the dispatch_auto-vs-direct_bq1024 revert
# trigger above stays armed.
# Re-checked (PR 11, 2026-08-03): unchanged — no new hardware window
# since r05 (docs/window_r05 is still the newest; only the single-shot
# flashblocks line exists). Trigger stays OPEN; cap stays 1024.
# Re-checked (PR 12, 2026-08-04): unchanged — window_r05 remains the
# newest window (both r05 stamps hold only the single-shot flashblocks
# line: bq256 9.0 / bq512 11.0 / bq1024 14.0 TFLOP/s; no probe_qblock
# arbitration output anywhere under docs/window_r05/). The qblock stage
# stays queued at the FRONT of window_autorun's unmeasured set; the
# dispatch_auto-vs-direct_bq1024 revert trigger above stays armed and
# the cap stays 1024.
# Re-checked (PR 14, 2026-08-04): unchanged — no window newer than
# window_r05 exists and neither r05 stamp holds probe_qblock output
# (still only the single-shot flashblocks line). Trigger stays OPEN;
# the cap stays 1024 on the single-shot data; the qblock stage remains
# at the front of window_autorun's unmeasured set for the next
# hardware window.
# Re-checked (PR 15, 2026-08-04): unchanged — window_r05 is still the
# newest window (no carrier newer than its two stamps) and no
# probe_qblock arbitration output has landed anywhere under
# docs/window_r05/. Trigger stays OPEN; cap stays 1024; the qblock
# stage keeps its front slot in window_autorun's unmeasured set.
# Re-checked (PR 16, 2026-08-07): unchanged — window_r05 (stamps
# 20260801T082804 + 20260801T091000_hostlocal) remains the newest
# window and neither stamp carries probe_qblock arbitration output
# (the 082804 run still lists only the single-shot flashblocks line).
# Trigger stays OPEN; cap stays 1024; qblock keeps its front slot in
# window_autorun's unmeasured set for the next hardware window.
# Re-checked (PR 17, 2026-08-07): unchanged — window_r05 is still the
# newest window (same two stamps) and no probe_qblock output exists
# under either (082804 carries only the single-shot flashblocks line;
# 091000_hostlocal only input.jsonl). Trigger stays OPEN; cap stays
# 1024; qblock keeps its front slot for the next hardware window.
# Re-checked (PR 18, 2026-08-07): unchanged — still no window newer
# than r05, so the 512->1024 arbitration data does not exist yet and
# the cap stays 1024 on the single-shot line; the revert trigger above
# stays armed. The carry-over is now FOLDED into shared machinery: a
# probe_kvblock stage (pallas paged-attend vs gather across kv_block
# sizes, ISSUE 18) rides directly behind qblock in window_autorun's
# attribution group, so the next UP window arbitrates both block-
# geometry questions — this retune and the paged kernel's chunk size —
# from one stage sequence.
# Re-checked (PR 19, 2026-08-07): unchanged — window_r05 remains the
# newest window (no stamp newer than 082804 / 091000_hostlocal, and
# neither carries probe_qblock or probe_kvblock arbitration output).
# Trigger stays OPEN; cap stays 1024; the qblock+kvblock stage pair
# keeps its front slot in window_autorun's unmeasured set for the
# next hardware window.
# Re-checked (PR 20, 2026-08-07): unchanged — window_r05 is still the
# newest window (only the 082804 / 091000_hostlocal stamps exist) and
# neither carries probe_qblock or probe_kvblock arbitration output.
# Trigger stays OPEN; cap stays 1024; the qblock+kvblock pair keeps
# its front slot for the next hardware window.
MAX_Q_BLOCK = 1024


def select_block_pair(
    tq: int, tk: int, *, compiled: bool = False,
    max_q_block: int = MAX_Q_BLOCK,
) -> tuple[int, int] | None:
    """(block_q, block_kv) or None: the KV block from select_block, with
    the Q block grown to the largest power-of-two multiple <= max_q_block
    that still divides tq (Mosaic sublane alignment is implied: multiples
    of a legal block stay legal on the sublane dim)."""
    bk = select_block(tq, tk, compiled=compiled)
    if bk is None:
        return None
    bq = bk
    while bq * 2 <= max_q_block and tq % (bq * 2) == 0:
        bq *= 2
    return bq, bk


def pick_block(seq_len: int, *, compiled: bool = False,
               max_block: int = 256) -> int | None:
    """Largest block tiling one sequence length (see select_block)."""
    return select_block(seq_len, seq_len, compiled=compiled,
                        max_block=max_block)


def flash_supported(tq: int, tk: int, head_dim: int, itemsize: int,
                    *, causal: bool, compiled: bool) -> bool:
    """True when flash_attention() will accept these shapes."""
    del head_dim, itemsize  # streaming kernels: VMEM use is O(block), not O(T)
    if causal and tq != tk:
        return False
    if max(tq, tk) > MAX_SEQ_LEN:
        return False
    return select_block(tq, tk, compiled=compiled) is not None


# ---------------------------------------------------------------------------
# kernels — grid (batch, head, q_block, kv_block); kv is the sequential
# ("arbitrary") dim, so VMEM scratch carries accumulators across it.
#
# Blocks are rectangular: bq rows of Q per cell, bk columns of K/V. Under
# causal masking, q-block i (rows [i*bq, (i+1)*bq)) interacts with
# kv-block j (cols [j*bk, (j+1)*bk)) iff j*bk <= (i+1)*bq - 1, i.e.
# j <= _last_kv(i) := ((i+1)*bq - 1) // bk; symmetrically the first
# active q-block for kv-block j is _first_q(j) := (j*bk) // bq.
# ---------------------------------------------------------------------------


def _last_kv(i, bq, bk):
    return ((i + 1) * bq - 1) // bk


def _first_q(j, bq, bk):
    return (j * bk) // bq


def _causal_clamps(causal, bq, bk):
    """(kv_clamp, q_clamp) index-map clamps for the causal block skip, or
    (None, None): kv_clamp keeps above-diagonal kv cells on the last
    active kv block of their q row-block (fwd/dq grids, x=q); q_clamp
    keeps below-diagonal q cells on the first active q block of their kv
    column-block (dkv grid, x=kv). Shared so the fwd and bwd pallas_calls
    cannot drift."""
    if not causal:
        return None, None
    return (
        lambda x, y: jnp.minimum(y, _last_kv(x, bq, bk)),
        lambda x, y: jnp.maximum(y, _first_q(x, bq, bk)),
    )


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l,
                *, bq, bk, causal, scale, nk):
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, _NEG_INF)
        l[:] = jnp.zeros_like(l)

    @pl.when(jnp.logical_or(not causal, j <= _last_kv(i, bq, bk)))
    def _compute():
        # Matmul inputs stay in their storage dtype (bf16 on the training
        # path) with f32 ACCUMULATION via preferred_element_type — an
        # explicit f32 upcast before the dot would run the MXU at its f32
        # rate, a fraction of bf16 throughput. Softmax statistics stay f32.
        q = q_ref[0, 0, :, :]
        k_blk = k_ref[0, 0, :, :]
        v_blk = v_ref[0, 0, :, :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * bq + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * bk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m[:]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        l[:] = l[:] * alpha + p.sum(axis=-1, keepdims=True)
        acc[:] = acc[:] * alpha + jnp.dot(
            p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32
        )
        m[:] = m_new

    last = _last_kv(i, bq, bk) if causal else nk - 1
    @pl.when(j == last)
    def _finalize():
        safe_l = jnp.maximum(l[:], 1e-30)
        o_ref[0, 0, :, :] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = m[:] + jnp.log(safe_l)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, bq, bk, causal, scale, nk):
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(jnp.logical_or(not causal, j <= _last_kv(i, bq, bk)))
    def _compute():
        q = q_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]
        k_blk = k_ref[0, 0, :, :]
        v_blk = v_ref[0, 0, :, :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)
        if causal:
            q_pos = i * bq + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * bk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k_blk.dtype)
        dq_acc[:] = dq_acc[:] + jnp.dot(
            ds, k_blk, preferred_element_type=jnp.float32
        )

    last = _last_kv(i, bq, bk) if causal else nk - 1
    @pl.when(j == last)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, bq, bk, causal, scale, ni):
    j, i = pl.program_id(2), pl.program_id(3)  # note: q blocks innermost

    @pl.when(i == (_first_q(j, bq, bk) if causal else 0))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(jnp.logical_or(not causal, i >= _first_q(j, bq, bk)))
    def _compute():
        k_blk = k_ref[0, 0, :, :]
        v_blk = v_ref[0, 0, :, :]
        q = q_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)
        if causal:
            q_pos = i * bq + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * bk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dv_acc[:] = dv_acc[:] + jnp.dot(
            p.astype(do.dtype).T, do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_acc[:] = dk_acc[:] + jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32
        )

    @pl.when(i == ni - 1)
    def _finalize():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


# BlockSpecs over [B, H, T, D] (data) and [B, H, T, 1] (rows: lse/delta).
# Grid is (b, h, x, y); which of x/y indexes the tensor differs per spec.
def _spec_x(blk, d):
    return pl.BlockSpec((1, 1, blk, d), lambda b, h, x, y: (b, h, x, 0))


def _spec_y(blk, d, *, clamp=None):
    """Block follows grid dim y; with `clamp` (a function of grid dim x
    giving the last/first active y), cells predicated off under causal
    masking re-request an already-active block — an unchanged block index
    means pallas skips the HBM->VMEM copy, so masked cells cost neither
    FLOPs nor bandwidth."""
    if clamp is not None:
        return pl.BlockSpec(
            (1, 1, blk, d), lambda b, h, x, y: (b, h, clamp(x, y), 0)
        )
    return pl.BlockSpec((1, 1, blk, d), lambda b, h, x, y: (b, h, y, 0))


# Shared grid contract: (batch, head) and the x block dim parallel; the
# innermost streamed dim sequential so scratch accumulators carry across it.
# jax renamed TPUCompilerParams -> CompilerParams (~0.4.3x); accept both.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)
_COMPILER_PARAMS = _CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
)


def _flash_fwd(q, k, v, causal, scale, bq, bk, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    ni, nk = tq // bq, tk // bk
    kernel = functools.partial(
        _fwd_kernel, bq=bq, bk=bk, causal=causal, scale=scale, nk=nk
    )
    kv_clamp, _ = _causal_clamps(causal, bq, bk)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, ni, nk),
        in_specs=[
            _spec_x(bq, d),
            _spec_y(bk, d, clamp=kv_clamp),
            _spec_y(bk, d, clamp=kv_clamp),
        ],
        out_specs=[_spec_x(bq, d), _spec_x(bq, 1)],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _flash_bwd_impl(q, k, v, o, lse, do, causal, scale, bq, bk, interpret):
    delta = jnp.einsum(
        "bhtd,bhtd->bht", do.astype(jnp.float32), o.astype(jnp.float32)
    )[..., None]
    return _flash_bwd_from_stats(q, k, v, do, lse, delta, causal, scale,
                                 bq, bk, interpret)


def _flash_bwd_from_stats(q, k, v, do, lse, delta, causal, scale, bq, bk,
                          interpret):
    """(dq, dk, dv) from softmax stats: lse/delta [B,H,T,1].

    The stats may be GLOBAL (ring attention's merged logsumexp and
    delta = sum(do*o_global)) — p = exp(s - lse) then yields each block's
    exact share of the global softmax, which is what makes the per-block
    ring backward communication-free beyond the rotation. Single home of
    the dq/dkv pallas_call configuration for both the single-device VJP
    and the ring backward."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    ni, nk = tq // bq, tk // bk

    kv_clamp, q_clamp = _causal_clamps(causal, bq, bk)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, bq=bq, bk=bk, causal=causal,
                          scale=scale, nk=nk),
        grid=(b, h, ni, nk),
        in_specs=[
            _spec_x(bq, d),                          # q by q-block
            _spec_y(bk, d, clamp=kv_clamp),          # k by kv-block
            _spec_y(bk, d, clamp=kv_clamp),          # v by kv-block
            _spec_x(bq, d),                          # do by q-block
            _spec_x(bq, 1),                          # lse by q-block
            _spec_x(bq, 1),                          # delta by q-block
        ],
        out_specs=_spec_x(bq, d),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dkv grid: (b, h, kv_block, q_block) — q blocks stream innermost.
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, bq=bq, bk=bk, causal=causal,
                          scale=scale, ni=ni),
        grid=(b, h, nk, ni),
        in_specs=[
            _spec_y(bq, d, clamp=q_clamp),           # q
            _spec_x(bk, d),                          # k by kv-block (dim 2)
            _spec_x(bk, d),                          # v by kv-block
            _spec_y(bq, d, clamp=q_clamp),           # do
            _spec_y(bq, 1, clamp=q_clamp),           # lse
            _spec_y(bq, 1, clamp=q_clamp),           # delta
        ],
        out_specs=[_spec_x(bk, d), _spec_x(bk, d)],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, bq, bk, interpret):
    o, _ = _flash_fwd(q, k, v, causal, scale, bq, bk, interpret)
    return o


def _flash_vjp_fwd(q, k, v, causal, scale, bq, bk, interpret):
    o, lse = _flash_fwd(q, k, v, causal, scale, bq, bk, interpret)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, scale, bq, bk, interpret, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, o, lse, do, causal, scale, bq, bk,
                                 interpret)
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block: int | None = None,
    block_q: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Blocked flash attention. q/k/v: [batch, seq, heads, head_dim].

    Requires kv seq divisible by ``block`` and q seq by ``block_q`` (both
    auto-picked when None; on TPU the blocks must also satisfy Mosaic
    tiling — see select_block_pair). Raises ValueError when no legal block
    exists — callers should use ops.attention() which falls back to the
    XLA path.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = not on_tpu_backend()
    tq, tk = q.shape[1], k.shape[1]
    if block is None:
        pair = select_block_pair(tq, tk, compiled=not interpret)
        block = pair[1] if pair else None
        if block_q is None and pair:
            block_q = pair[0]
    elif not interpret and block % 128 != 0:
        # A caller-supplied block must satisfy the same compiled-path
        # legality select_block enforces, or the failure surfaces later as
        # an opaque Mosaic lowering error: non-%128 blocks are only legal as
        # the equal-to-dim single block, with the same sublane-alignment and
        # VMEM-score-tile caps as select_block's fallback.
        if not (block == tq == tk and tq % 16 == 0 and tq <= 512):
            raise ValueError(
                f"block={block} is not Mosaic-legal for seq lengths "
                f"({tq},{tk}): a compiled-path block must be a multiple of "
                f"128, or equal to both sequence lengths with seq % 16 == 0 "
                f"and seq <= 512"
            )
    if block_q is None:
        block_q = block
    if block is None or tq % block_q or tk % block:
        raise ValueError(
            f"seq lengths ({tq},{tk}) don't tile "
            f"(block_q={block_q}, block={block})"
        )
    if not interpret and block_q != block and block_q % block != 0:
        # The causal block-skip arithmetic (_last_kv/_first_q) and the
        # Mosaic sublane legality both assume bq is a multiple of bk when
        # they differ.
        raise ValueError(f"block_q={block_q} must be a multiple of "
                         f"block={block}")
    if causal and tq != tk:
        raise ValueError("causal flash requires tq == tk")
    if max(tq, tk) > MAX_SEQ_LEN:
        raise ValueError(f"seq > MAX_SEQ_LEN ({MAX_SEQ_LEN})")
    # [B,T,H,D] -> [B,H,T,D] for the kernels; XLA folds the transposes.
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o = _flash(qt, kt, vt, causal, float(scale), int(block_q), int(block),
               bool(interpret))
    return o.transpose(0, 2, 1, 3)
