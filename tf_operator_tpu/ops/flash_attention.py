"""Pallas TPU flash attention (forward + custom-VJP backward).

The single-device hot op of the training stack. The reference delegates all
compute to the TensorFlow runtime inside user containers (SURVEY.md: zero
native/kernel code in-repo); here the framework owns its compute path, so
the attention inner loop is a hand-written TPU kernel:

- blocked streaming softmax: one Q block per grid program; K/V live in VMEM
  for the program (pipelined HBM->VMEM by pallas across grid steps) and are
  consumed block-by-block, so scores never materialize [T, T] — VMEM is
  O(block^2) for scores plus O(T*head_dim) for the resident K/V (budget
  enforced by flash_supported; sequences beyond it belong to ring
  attention's sharded path).
- MXU-friendly: all contractions via jnp.dot with
  preferred_element_type=float32; bf16 inputs supported.
- causal skip: grid program for Q block i only loops K blocks j <= i
  (dynamic fori_loop bound), halving FLOPs for causal LM training.
- backward = two kernels (dq; dk/dv) recomputing probabilities from the
  saved logsumexp — the standard flash recomputation trade (HBM bandwidth
  is the bottleneck, FLOPs are cheap on the MXU).

Kernels run in [batch, heads, seq, head_dim] layout so Mosaic's tiling
constraint (block's trailing dims must be sublane/lane aligned) falls on
(seq_block, head_dim); the public API takes the framework convention
[batch, seq, heads, head_dim] (parallel/ring_attention.py) and transposes at
the boundary (XLA folds the transpose into neighboring ops). Composes with
ring attention: ring shards the sequence across chips (ICI), this kernel is
the per-chip block compute.

Falls back transparently (ops/__init__.attention) to the XLA reference
implementation when shapes don't tile or when not on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG_INF = -1e30
_BLOCK_CANDIDATES = (256, 128, 64, 32, 16, 8)


# Per-(b,h) program the kernels hold two full-sequence tensors in VMEM
# (fwd/dq: K+V; dkv: Q+dO). Cap their combined footprint well under the
# ~16 MB VMEM so blocks/accumulators/double-buffering fit too.
_VMEM_SEQ_BUDGET_BYTES = 8 * 1024 * 1024


def select_block(tq: int, tk: int, *, compiled: bool = False,
                 max_block: int = 256) -> int | None:
    """Largest block that tiles BOTH sequence lengths, or None.

    This is the single source of truth for flash dispatchability: the same
    block is used on the Q side and the K side, so it must divide both
    lengths, and under the Mosaic lowering (compiled=True) a trailing-two
    BlockSpec dim must be a multiple of 128 or equal to the whole dimension
    on *that* side; interpret mode (CPU CI) has no such limit.
    """
    for b in _BLOCK_CANDIDATES:
        if b > max_block or tq % b or tk % b:
            continue
        if compiled and not (
            (b % 128 == 0 or b == tq) and (b % 128 == 0 or b == tk)
        ):
            continue
        return b
    if (
        compiled
        and tq == tk
        and tq % 16 == 0  # bf16 sublane alignment
        # the kernels materialize an f32 [block, block] score tile in VMEM;
        # cap the single-block fallback so it stays ~1 MiB, not ~16 MiB
        and tq <= 512
    ):
        return tq  # single block: equal-to-dim is always a legal BlockSpec
    return None


def pick_block(seq_len: int, *, compiled: bool = False,
               max_block: int = 256) -> int | None:
    """Largest block tiling one sequence length (see select_block)."""
    return select_block(seq_len, seq_len, compiled=compiled,
                        max_block=max_block)


def flash_supported(tq: int, tk: int, head_dim: int, itemsize: int,
                    *, causal: bool, compiled: bool) -> bool:
    """True when flash_attention() will accept these shapes."""
    if causal and tq != tk:
        return False
    if 2 * max(tq, tk) * head_dim * itemsize > _VMEM_SEQ_BUDGET_BYTES:
        return False
    return select_block(tq, tk, compiled=compiled) is not None


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, blk, causal, scale, nk):
    i = pl.program_id(2)
    q = q_ref[0, 0, :, :].astype(jnp.float32)
    bq, d = q.shape

    q_pos = i * blk + lax.broadcasted_iota(jnp.int32, (bq, blk), 0)

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, 0, pl.ds(j * blk, blk), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * blk, blk), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = j * blk + lax.broadcasted_iota(jnp.int32, (bq, blk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return acc, m_new, l

    acc = jnp.zeros((bq, d), jnp.float32)
    m = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    hi = lax.min(i + 1, nk) if causal else nk
    acc, m, l = lax.fori_loop(0, hi, body, (acc, m, l))

    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0, :, :] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0, :, :] = m + jnp.log(l)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               *, blk, causal, scale, nk):
    i = pl.program_id(2)
    q = q_ref[0, 0, :, :].astype(jnp.float32)
    do = do_ref[0, 0, :, :].astype(jnp.float32)
    lse = lse_ref[0, 0, :, :]
    delta = delta_ref[0, 0, :, :]
    bq, d = q.shape
    q_pos = i * blk + lax.broadcasted_iota(jnp.int32, (bq, blk), 0)

    def body(j, dq):
        k_blk = k_ref[0, 0, pl.ds(j * blk, blk), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * blk, blk), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)
        if causal:
            k_pos = j * blk + lax.broadcasted_iota(jnp.int32, (bq, blk), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    hi = lax.min(i + 1, nk) if causal else nk
    dq = lax.fori_loop(0, hi, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0, :, :] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, blk, causal, scale, ni):
    j = pl.program_id(2)
    k_blk = k_ref[0, 0, :, :].astype(jnp.float32)
    v_blk = v_ref[0, 0, :, :].astype(jnp.float32)
    bk, d = k_blk.shape
    k_pos = j * blk + lax.broadcasted_iota(jnp.int32, (blk, bk), 1)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * blk, blk), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(i * blk, blk), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * blk, blk), :]
        delta = delta_ref[0, 0, pl.ds(i * blk, blk), :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)
        if causal:
            q_pos = i * blk + lax.broadcasted_iota(jnp.int32, (blk, bk), 0)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    lo = j if causal else 0
    dk, dv = lax.fori_loop(
        lo, ni, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)),
    )
    dk_ref[0, 0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0, :, :] = dv.astype(dv_ref.dtype)


# BlockSpecs over [B, H, T, D] (data) and [B, H, T, 1] (rows: lse/delta).
def _blk_spec(blk, d):
    return pl.BlockSpec((1, 1, blk, d), lambda b, h, i: (b, h, i, 0))


def _full_spec(t, d):
    return pl.BlockSpec((1, 1, t, d), lambda b, h, i: (b, h, 0, 0))


def _flash_fwd(q, k, v, causal, scale, blk, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    nk = tk // blk
    grid = (b, h, tq // blk)
    kernel = functools.partial(
        _fwd_kernel, blk=blk, causal=causal, scale=scale, nk=nk
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[_blk_spec(blk, d), _full_spec(tk, d), _full_spec(tk, d)],
        out_specs=[_blk_spec(blk, d), _blk_spec(blk, 1)],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, tq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _flash_bwd_impl(q, k, v, o, lse, do, causal, scale, blk, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    ni, nk = tq // blk, tk // blk
    delta = jnp.einsum(
        "bhtd,bhtd->bht", do.astype(jnp.float32), o.astype(jnp.float32)
    )[..., None]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, blk=blk, causal=causal, scale=scale, nk=nk),
        grid=(b, h, ni),
        in_specs=[
            _blk_spec(blk, d),
            _full_spec(tk, d),
            _full_spec(tk, d),
            _blk_spec(blk, d),
            _blk_spec(blk, 1),
            _blk_spec(blk, 1),
        ],
        out_specs=_blk_spec(blk, d),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, blk=blk, causal=causal, scale=scale, ni=ni),
        grid=(b, h, nk),
        in_specs=[
            _full_spec(tq, d),
            _blk_spec(blk, d),
            _blk_spec(blk, d),
            _full_spec(tq, d),
            _full_spec(tq, 1),
            _full_spec(tq, 1),
        ],
        out_specs=[_blk_spec(blk, d), _blk_spec(blk, d)],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, blk, interpret):
    o, _ = _flash_fwd(q, k, v, causal, scale, blk, interpret)
    return o


def _flash_vjp_fwd(q, k, v, causal, scale, blk, interpret):
    o, lse = _flash_fwd(q, k, v, causal, scale, blk, interpret)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, scale, blk, interpret, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, o, lse, do, causal, scale, blk,
                                 interpret)
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Blocked flash attention. q/k/v: [batch, seq, heads, head_dim].

    Requires seq divisible by ``block`` (auto-picked when None; on TPU the
    block must also satisfy Mosaic tiling — see pick_block). Raises
    ValueError when no legal block exists — callers should use
    ops.attention() which falls back to the XLA path.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tq, tk = q.shape[1], k.shape[1]
    if block is None:
        block = select_block(tq, tk, compiled=not interpret)
    if block is None or tq % block or tk % block:
        raise ValueError(f"seq lengths ({tq},{tk}) don't tile (block={block})")
    if causal and tq != tk:
        raise ValueError("causal flash requires tq == tk")
    if 2 * max(tq, tk) * q.shape[-1] * q.dtype.itemsize > _VMEM_SEQ_BUDGET_BYTES:
        raise ValueError(
            f"sequence ({max(tq, tk)} x {q.shape[-1]}) exceeds the kernel's "
            "full-sequence VMEM budget; use ring attention to shard the "
            "sequence, or the XLA fallback (ops.attention)"
        )
    # [B,T,H,D] -> [B,H,T,D] for the kernels; XLA folds the transposes.
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o = _flash(qt, kt, vt, causal, float(scale), int(block), bool(interpret))
    return o.transpose(0, 2, 1, 3)
