"""TPU kernels for the hot ops (pallas), with XLA fallbacks.

The dispatch rule lives here: ``attention()`` picks the pallas flash kernel
when running on TPU with tileable shapes, otherwise the XLA reference path
(which XLA still fuses well on CPU/small shapes). Models call this one entry
point so the kernel choice is a deployment detail, not a model concern.

``TPU_OPERATOR_ATTN=xla`` forces the XLA path (``flash`` forces the kernel
where legal) — the bench-day A/B knob: it reaches every model's attention
through this dispatch without code edits.
"""

from __future__ import annotations

import os

import jax

from tf_operator_tpu.ops.flash_attention import (
    flash_attention,
    flash_supported,
    on_tpu_backend,
    pick_block,
    select_block,
)
from tf_operator_tpu.ops.paged_attention import (
    paged_attend,
    paged_attend_supported,
    paged_attend_vmem_bytes,
)


def attention_kernel(tq: int, tk: int, head_dim: int, itemsize: int,
                     *, causal: bool = True) -> str:
    """Which kernel attention() will run for these shapes on THIS backend:
    "pallas-flash" or "xla". The single source of truth for the dispatch —
    attention() consults it, and benchmarks label their output with it (so
    the label can never drift from what actually executed).
    TPU_OPERATOR_ATTN overrides ("xla" always honored; "flash" honored
    when the shapes tile)."""
    forced = os.environ.get("TPU_OPERATOR_ATTN", "").strip().lower()
    if forced and forced not in ("xla", "flash"):
        # A typo must not silently measure the kernel an A/B run meant to
        # exclude.
        raise ValueError(
            f"TPU_OPERATOR_ATTN={forced!r}: expected 'xla' or 'flash'"
        )
    if forced == "xla":
        return "xla"
    on_tpu = on_tpu_backend()
    if forced == "flash":
        # Only meaningful on TPU: off-TPU the kernel would run in the
        # Pallas INTERPRETER, orders of magnitude slower than the XLA
        # path it displaces (see on_tpu_backend).
        if on_tpu and flash_supported(
            tq, tk, head_dim, itemsize, causal=causal, compiled=True
        ):
            return "pallas-flash"
        return "xla"
    if on_tpu and flash_supported(
        tq, tk, head_dim, itemsize, causal=causal, compiled=True
    ):
        return "pallas-flash"
    return "xla"


def attention(q, k, v, *, causal: bool = True, scale: float | None = None,
              use_flash: bool | None = None) -> jax.Array:
    """Single-device attention: flash kernel on TPU, XLA elsewhere."""
    from tf_operator_tpu.parallel.ring_attention import reference_attention

    if use_flash is None:
        choice = attention_kernel(
            q.shape[1], k.shape[1], q.shape[-1], q.dtype.itemsize,
            causal=causal,
        )
    elif use_flash and flash_supported(
        q.shape[1], k.shape[1], q.shape[-1], q.dtype.itemsize,
        causal=causal, compiled=on_tpu_backend(),
    ):
        choice = "pallas-flash"
    else:
        choice = "xla"
    if choice == "pallas-flash":
        return flash_attention(q, k, v, causal=causal, scale=scale)
    return reference_attention(q, k, v, causal=causal, scale=scale)


__all__ = [
    "attention",
    "attention_kernel",
    "flash_attention",
    "flash_supported",
    "on_tpu_backend",
    "paged_attend",
    "paged_attend_supported",
    "paged_attend_vmem_bytes",
    "pick_block",
    "select_block",
]
