"""TPU kernels for the hot ops (pallas), with XLA fallbacks.

The dispatch rule lives here: ``attention()`` picks the pallas flash kernel
when running on TPU with tileable shapes, otherwise the XLA reference path
(which XLA still fuses well on CPU/small shapes). Models call this one entry
point so the kernel choice is a deployment detail, not a model concern.
"""

from __future__ import annotations

import jax

from tf_operator_tpu.ops.flash_attention import (
    flash_attention,
    flash_supported,
    pick_block,
    select_block,
)


def attention(q, k, v, *, causal: bool = True, scale: float | None = None,
              use_flash: bool | None = None) -> jax.Array:
    """Single-device attention: flash kernel on TPU, XLA elsewhere."""
    from tf_operator_tpu.parallel.ring_attention import reference_attention

    on_tpu = jax.default_backend() == "tpu"
    if use_flash is None:
        use_flash = on_tpu
    if use_flash and flash_supported(
        q.shape[1], k.shape[1], q.shape[-1], q.dtype.itemsize,
        causal=causal, compiled=on_tpu,
    ):
        return flash_attention(q, k, v, causal=causal, scale=scale)
    return reference_attention(q, k, v, causal=causal, scale=scale)


__all__ = [
    "attention",
    "flash_attention",
    "flash_supported",
    "pick_block",
    "select_block",
]
