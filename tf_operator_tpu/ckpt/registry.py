"""CheckpointRegistry: the operator-side source of truth for per-job
checkpoint state.

The reference delegates checkpointing entirely to user code — the
operator's only contribution is stable pod identity so resume *can* work
(tf_job_design_doc.md, SURVEY §5). This registry closes the loop: workers
report durable saves through pod annotations (lifted from the ack file by
the local executor, or patched directly on a real cluster —
ckpt/protocol.py), and every controller sync rolls them up into one
job-level record:

- ``ckpt.tpuflow.org/latest-step`` / ``acked-at`` / ``dir`` annotations on
  the TPUJob — persisted annotation-first with the same crash discipline
  as the gang scheduler's admissions, so a controller restart recovers the
  exact resume state from the store with no side channel;
- ``status.lastCheckpointStep`` + the CheckpointStale / CheckpointSkipped
  conditions (stamped by the controller from the same annotations);
- the ``TPU_RESUME_STEP`` / ``TPU_CKPT_DIR`` env injected into replacement
  pods (resume_env), which is how a preempted/migrated gang resumes from
  its last acked step instead of step 0.

The roll-up is the MIN over reporting pods — conservative: a step is
recorded only once every pod that reports at all has it durable. Pods that
never report cannot hold the record back (they also can never ack an
eviction signal; the grace deadline covers them). The record is monotone:
checkpoint steps on disk only grow.

The registry also serves the eviction barrier (scheduler/core.py): it
caches each pod's acked generation from the latest sync observation, and
``barrier_acked`` answers "has every gang pod acked signal generation G?"
under the scheduler's lock. Lock ordering: scheduler lock → registry lock,
always; the registry never calls into the scheduler.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any

from tf_operator_tpu.api.types import TPUJob
from tf_operator_tpu.ckpt import protocol
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ApiError, ClusterClient
from tf_operator_tpu.runtime.metrics import (
    CKPT_ACKS_TOTAL,
    CKPT_JOBS_REPORTING,
    CKPT_SKIPPED_TOTAL,
    CKPT_STALE_JOBS,
)
from tf_operator_tpu.utils import logger
from tf_operator_tpu.utils.times import parse_rfc3339

EVENT_CKPT_SKIPPED = "CheckpointSkipped"


@dataclass
class CkptConfig:
    # A Running job whose checkpoint roll-up has not advanced for this many
    # seconds gets the CheckpointStale condition (0 disables).
    stale_after: float = 600.0


@dataclass
class CheckpointRecord:
    """One job's checkpoint state, mirrored from its annotations plus the
    per-pod ack cache from the latest sync observation."""

    key: str
    directory: str = ""
    latest_step: int | None = None
    acked_at: str = ""  # RFC3339 of the last roll-up advance
    signal_gen: int = 0
    skipped_at: str = ""
    stale: bool = False
    # pod uid -> acked generation (0 = never), refreshed every observe.
    pod_acks: dict[str, int] = field(default_factory=dict)
    # pod uid -> latest reported step.
    pod_steps: dict[str, int] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "key": self.key,
            "directory": self.directory,
            "latestStep": self.latest_step,
            "ackedAt": self.acked_at,
            "reportingPods": len(self.pod_steps),
            "stale": self.stale,
        }
        if self.signal_gen:
            d["signalGen"] = self.signal_gen
        if self.skipped_at:
            d["skippedAt"] = self.skipped_at
        return d


@dataclass
class BarrierStatus:
    """The eviction barrier, read from a job's persisted annotations + its
    live pods: exactly one of acked / expired / waiting holds."""

    gen: int
    acked: bool = False
    expired: bool = False
    waiting: bool = False
    remaining: float = 0.0


class CheckpointRegistry:
    def __init__(
        self,
        scheduler: Any,
        client: ClusterClient | None = None,
        config: CkptConfig | None = None,
        recorder: Any | None = None,
    ) -> None:
        self.scheduler = scheduler
        scheduler.ckpt = self
        self.client = client if client is not None else scheduler.client
        self.config = config or CkptConfig()
        self.recorder = recorder
        self._lock = threading.RLock()
        self._records: dict[str, CheckpointRecord] = {}
        # (job key, signal gen) pairs already marked skipped: the scheduler
        # barrier and the controller recovery path can both observe one
        # expired barrier in a single sync; the marker lands once.
        self._skipped: set[tuple[str, int]] = set()
        # Incrementally-maintained gauge inputs (see observe/forget).
        self._reporting = 0
        self._stale = 0
        self.log = logger.with_fields(component="ckpt-registry")

    def attach(self, client: ClusterClient, recorder: Any | None = None) -> None:
        """Late binding, mirroring GangScheduler.attach."""
        if self.client is None:
            self.client = client
        if self.recorder is None:
            self.recorder = recorder

    # -- sync-time observation (controller-driven) ----------------------------

    def observe(self, job: TPUJob, pods: list[dict[str, Any]]) -> None:
        """Roll per-pod checkpoint reports up into the job record.

        Persist-first: an advanced roll-up lands on the job's annotations
        BEFORE the in-memory record or status reflect it — a crash at any
        point leaves the store carrying exactly what recovery will read
        back. A failed persist changes nothing; the next sync retries.
        """
        ann = job.metadata.annotations or {}
        acks: dict[str, int] = {}
        steps: dict[str, int] = {}
        reported_dir = ""
        for pod in pods:
            uid = objects.uid_of(pod)
            acks[uid] = protocol.pod_ack_gen(pod)
            step = protocol.pod_step(pod)
            if step is not None:
                steps[uid] = step
                if not reported_dir:
                    reported_dir = objects.annotations_of(pod).get(
                        protocol.POD_DIR, ""
                    )

        cur = _parse_int(ann.get(protocol.JOB_STEP))
        cur_dir = ann.get(protocol.JOB_DIR, "")
        rolled = min(steps.values()) if steps else None
        patch: dict[str, str] = {}
        if rolled is not None and (cur is None or rolled > cur):
            patch[protocol.JOB_STEP] = str(rolled)
            patch[protocol.JOB_ACKED_AT] = objects.now_iso()
        if reported_dir and reported_dir != cur_dir and not cur_dir:
            patch[protocol.JOB_DIR] = reported_dir
        if patch and self._persist(job, patch) and protocol.JOB_STEP in patch:
            CKPT_ACKS_TOTAL.inc()

        ann = job.metadata.annotations or {}  # refreshed by _persist
        with self._lock:
            rec = self._records.setdefault(
                job.key, CheckpointRecord(key=job.key)
            )
            was_reporting, was_stale = rec.latest_step is not None, rec.stale
            rec.pod_acks = acks
            rec.pod_steps = steps
            rec.latest_step = _parse_int(ann.get(protocol.JOB_STEP))
            rec.directory = ann.get(protocol.JOB_DIR, "")
            rec.acked_at = ann.get(protocol.JOB_ACKED_AT, "")
            rec.signal_gen = _parse_int(ann.get(protocol.JOB_SIGNAL_GEN)) or 0
            rec.skipped_at = ann.get(protocol.JOB_SKIPPED_AT, "")
            rec.stale = self._is_stale(rec, job)
            # Incremental gauge maintenance: a sync must stay O(this job),
            # not O(all records) — the control-plane hot path PR 3 paid
            # for must not regress to an O(jobs²) resync wave here.
            self._reporting += (rec.latest_step is not None) - was_reporting
            self._stale += rec.stale - was_stale
        job.status.last_checkpoint_step = rec.latest_step
        self._export_gauges()

    def _is_stale(self, rec: CheckpointRecord, job: TPUJob) -> bool:
        if self.config.stale_after <= 0 or not rec.acked_at:
            return False
        last = parse_rfc3339(rec.acked_at)
        if last is None:
            return False
        running = any(
            c.type == "Running" and c.status == "True"
            for c in job.status.conditions
        )
        return running and time.time() - last > self.config.stale_after

    # -- eviction barrier (scheduler + controller recovery) -------------------

    def barrier_acked(self, key: str, gen: int, expected_pods: int) -> bool:
        """True when every expected pod (per the latest sync observation)
        has acked signal generation ``gen``. Called under the scheduler's
        lock; reads only registry state."""
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                return False
            acks = rec.pod_acks
        return len(acks) >= expected_pods and bool(acks) and all(
            a >= gen for a in acks.values()
        )

    def barrier_status(
        self, job: TPUJob, pods: list[dict[str, Any]],
        now: float | None = None,
    ) -> BarrierStatus | None:
        """The persisted barrier for a queued-with-pods job, or None when
        no barrier was ever signaled (plain interrupted eviction — the
        caller deletes immediately, the pre-barrier behavior). Computed
        purely from annotations + live pods, so a successor controller
        recovers the exact barrier its predecessor left."""
        ann = job.metadata.annotations or {}
        gen = _parse_int(ann.get(protocol.JOB_SIGNAL_GEN)) or 0
        deadline = parse_rfc3339(ann.get(protocol.JOB_EVICT_DEADLINE) or "")
        if not gen or deadline is None:
            return None
        if protocol.all_pods_acked(pods, gen):
            return BarrierStatus(gen=gen, acked=True)
        now = now if now is not None else time.time()
        if now >= deadline:
            return BarrierStatus(gen=gen, expired=True)
        return BarrierStatus(gen=gen, waiting=True, remaining=deadline - now)

    def note_skipped(
        self,
        namespace: str,
        name: str,
        gen: int,
        typed: TPUJob | None = None,
    ) -> None:
        """Record that an eviction proceeded past the grace deadline with
        no ack — once per (job, signal generation). Best-effort: the skip
        marker is observability and must never block the eviction."""
        key = f"{namespace}/{name}"
        with self._lock:
            if (key, gen) in self._skipped:
                return
            if len(self._skipped) >= 4096:
                self._skipped.clear()
            self._skipped.add((key, gen))
        CKPT_SKIPPED_TOTAL.inc()
        stamp = {protocol.JOB_SKIPPED_AT: objects.now_iso()}
        if typed is not None:
            self._persist(typed, stamp)
            return
        if self.client is None:
            return
        try:
            self.client.patch_merge(
                objects.TPUJOBS, namespace, name,
                {"metadata": {"annotations": stamp}},
            )
        except ApiError:
            self.log.warning(
                "checkpoint-skipped marker persist failed for %s/%s",
                namespace, name,
            )

    def clear_barrier(self, job: TPUJob) -> None:
        """Retire a completed barrier's annotations (merge-patch null).
        Best-effort: stale keys are only ever consulted together with
        state=queued AND live pods, which the completed eviction removed."""
        self._persist(job, {
            protocol.JOB_SIGNAL_GEN: None,
            protocol.JOB_EVICT_DEADLINE: None,
        })

    # -- resume injection -----------------------------------------------------

    def resume_env(self, job: TPUJob) -> dict[str, str]:
        """The env contract injected into (replacement) pods: the last
        acked step and directory from the job's durable record."""
        ann = job.metadata.annotations or {}
        env: dict[str, str] = {}
        step = _parse_int(ann.get(protocol.JOB_STEP))
        if step is not None:
            env[protocol.ENV_RESUME_STEP] = str(step)
        directory = ann.get(protocol.JOB_DIR, "")
        if directory:
            env[protocol.ENV_CKPT_DIR] = directory
        return env

    # -- lifecycle / introspection -------------------------------------------

    def forget(self, key: str) -> None:
        with self._lock:
            rec = self._records.pop(key, None)
            if rec is not None:
                self._reporting -= rec.latest_step is not None
                self._stale -= rec.stale
        self._export_gauges()

    def record_of(self, key: str) -> CheckpointRecord | None:
        with self._lock:
            rec = self._records.get(key)
            return None if rec is None else CheckpointRecord(
                key=rec.key, directory=rec.directory,
                latest_step=rec.latest_step, acked_at=rec.acked_at,
                signal_gen=rec.signal_gen, skipped_at=rec.skipped_at,
                stale=rec.stale, pod_acks=dict(rec.pod_acks),
                pod_steps=dict(rec.pod_steps),
            )

    def snapshot(self) -> dict[str, Any]:
        """JSON-friendly view for /debug/ckpt and `tpuctl ckpt`."""
        with self._lock:
            records = [
                rec.to_dict()
                for rec in sorted(
                    self._records.values(), key=lambda r: r.key
                )
            ]
        return {
            "jobs": records,
            "config": {"staleAfter": self.config.stale_after},
        }

    # -- internals ------------------------------------------------------------

    def _persist(self, job: TPUJob, annotations: dict[str, Any]) -> bool:
        """Merge-patch annotations onto the job (None deletes the key),
        refreshing the typed object's RV so the sync's later status write
        does not self-conflict (same shape as GangScheduler._persist)."""

        def apply_typed() -> None:
            for k, v in annotations.items():
                if v is None:
                    job.metadata.annotations.pop(k, None)
                else:
                    job.metadata.annotations[k] = v

        if self.client is None:
            apply_typed()
            return True
        try:
            patched = self.client.patch_merge(
                objects.TPUJOBS, job.metadata.namespace, job.metadata.name,
                {"metadata": {"annotations": dict(annotations)}},
            )
        except ApiError:
            self.log.warning(
                "checkpoint annotation persist failed for %s", job.key
            )
            return False
        apply_typed()
        job.metadata.resource_version = str(
            objects.meta(patched).get("resourceVersion", "")
        )
        return True

    def _export_gauges(self) -> None:
        with self._lock:
            reporting, stale = self._reporting, self._stale
        CKPT_JOBS_REPORTING.set(reporting)
        CKPT_STALE_JOBS.set(stale)


def _parse_int(raw: str | None) -> int | None:
    if raw is None:
        return None
    try:
        return int(raw)
    except (TypeError, ValueError):
        return None
