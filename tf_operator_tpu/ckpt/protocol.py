"""The checkpoint-coordination wire protocol: annotations, env vars, and
the ack file.

Three parties speak it:

- **Workloads** save checkpoints and *ack* them. Under the local executor
  the ack is a small JSON file (``$TPU_CKPT_ACK_FILE``, written by
  ``train/checkpoint.py`` after a durable save) that the executor lifts
  into pod annotations; on a real cluster a workload (or sidecar) patches
  its own pod's annotations directly. Either way the operator sees the
  same thing: per-pod ``ckpt.tpuflow.org/step`` / ``saved-at`` / ``ack``.
- **The scheduler** signals: before an eviction (preemption or health
  migration) it stamps ``ckpt.tpuflow.org/signal`` = <generation> on every
  gang pod and persists the generation + grace deadline on the job, then
  holds the deletion loop until every pod acks the generation or the
  deadline passes (scheduler/core.py).
- **The controller** rolls per-pod reports up into job-level state
  (``ckpt/registry.py``): the job annotations below are the durable resume
  record a restarted controller recovers from, and the source of the
  ``TPU_RESUME_STEP`` / ``TPU_CKPT_DIR`` env injected into replacement
  pods.

Signal generations are millisecond-epoch integers: monotone across
controller incarnations without any persisted counter, so a recovered
barrier compares acks against the persisted generation and a *stale* ack
(from an earlier eviction) can never satisfy a newer signal.

This module is dependency-light on purpose — the executor, the scheduler,
the registry and the training stack all import it, and none of them may
drag in the others (or jax).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Any

# -- pod annotations (worker → operator reports, scheduler → worker signal)

# Latest durably-saved checkpoint step this pod reports.
POD_STEP = "ckpt.tpuflow.org/step"
# RFC3339 stamp of that save.
POD_SAVED_AT = "ckpt.tpuflow.org/saved-at"
# Checkpoint directory the pod writes to.
POD_DIR = "ckpt.tpuflow.org/dir"
# Eviction checkpoint signal: the generation the scheduler stamped.
POD_SIGNAL = "ckpt.tpuflow.org/signal"
# The generation this pod has acked (a durable save completed at-or-after
# the signal); the eviction barrier waits for ack >= signal on every pod.
POD_ACK = "ckpt.tpuflow.org/ack"

# -- job annotations (the operator's durable checkpoint record)

# Latest job-level acked step: the min over reporting pods, monotone.
JOB_STEP = "ckpt.tpuflow.org/latest-step"
# RFC3339 stamp of the last roll-up advance.
JOB_ACKED_AT = "ckpt.tpuflow.org/acked-at"
# Checkpoint directory (first reported; also user-presettable).
JOB_DIR = "ckpt.tpuflow.org/dir"
# Generation of the most recent eviction checkpoint signal.
JOB_SIGNAL_GEN = "ckpt.tpuflow.org/signal-gen"
# RFC3339 grace deadline of the in-flight eviction barrier. Retired
# (null-deleted, along with signal-gen) when the eviction completes;
# should the retirement patch fail, the stale pair is harmless — it is
# only ever consulted together with state=queued AND live pods, a
# combination the completed deletion loop removed.
JOB_EVICT_DEADLINE = "ckpt.tpuflow.org/evict-deadline"
# RFC3339 stamp of the last eviction that proceeded WITHOUT an ack (grace
# expired); keys the CheckpointSkipped condition until a newer ack lands.
JOB_SKIPPED_AT = "ckpt.tpuflow.org/skipped-at"

# -- env vars injected into pods

# Where the workload writes its ack file (local executor contract).
ENV_ACK_FILE = "TPU_CKPT_ACK_FILE"
# Resume contract injected into replacement pods from the job record.
ENV_RESUME_STEP = "TPU_RESUME_STEP"
ENV_CKPT_DIR = "TPU_CKPT_DIR"


def new_signal_gen(now: float | None = None) -> int:
    """Millisecond-epoch signal generation — monotone across restarts."""
    return int((now if now is not None else time.time()) * 1000)


def fmt_deadline(epoch: float) -> str:
    """RFC3339 with fractional seconds (grace deadlines can be sub-second
    in tests; utils.times.parse_rfc3339 reads this back exactly)."""
    import datetime

    dt = datetime.datetime.fromtimestamp(epoch, tz=datetime.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z"


def pod_step(pod: dict[str, Any]) -> int | None:
    return _int_ann(pod, POD_STEP)


def pod_ack_gen(pod: dict[str, Any]) -> int:
    """The generation this pod has acked (0 = never acked)."""
    return _int_ann(pod, POD_ACK) or 0


def pod_signal_gen(pod: dict[str, Any]) -> int:
    return _int_ann(pod, POD_SIGNAL) or 0


def _int_ann(obj: dict[str, Any], key: str) -> int | None:
    from tf_operator_tpu.runtime import objects

    raw = objects.annotations_of(obj).get(key)
    if raw is None:
        return None
    try:
        return int(raw)
    except (TypeError, ValueError):
        return None


def all_pods_acked(pods: list[dict[str, Any]], gen: int) -> bool:
    """The barrier predicate: every pod still standing has acked the
    signal generation (pods deleted mid-eviction no longer block; pods
    that never report can only be released by the grace deadline)."""
    return bool(pods) and all(pod_ack_gen(p) >= gen for p in pods)


# -- the ack file (workload ↔ local executor) -------------------------------


@dataclass
class Ack:
    """One durable-save report, as written to the ack file."""

    step: int
    directory: str = ""
    saved_at: str = ""

    def to_dict(self) -> dict[str, Any]:
        return {"step": self.step, "dir": self.directory,
                "savedAt": self.saved_at}


def write_ack(path: str, step: int, directory: str = "") -> None:
    """Atomically write the ack file: the executor may read it mid-write,
    so the JSON lands via rename, never a partial file."""
    ack = Ack(step=int(step), directory=directory,
              saved_at=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(ack.to_dict(), f)
    os.replace(tmp, path)


def read_ack(path: str) -> Ack | None:
    """Parse an ack file; None when absent or (transiently) malformed."""
    try:
        with open(path) as f:
            d = json.load(f)
        return Ack(step=int(d["step"]), directory=str(d.get("dir", "")),
                   saved_at=str(d.get("savedAt", "")))
    except (OSError, ValueError, KeyError, TypeError):
        return None


def ack_path_for(namespace: str, pod_name: str, uid: str) -> str:
    """Per-pod-incarnation ack file, next to the pod log spool."""
    from tf_operator_tpu.runtime import podlogs

    safe_uid = (uid or "nouid")[:8]
    return os.path.join(
        podlogs.log_dir(), f"{namespace}_{pod_name}_{safe_uid}.ckpt-ack.json"
    )
