"""Checkpoint retention sweeper: GC for checkpoint step directories of
finished jobs.

Orbax already enforces ``max_to_keep`` *while a job runs*; what nobody
owns is the tail — a Succeeded job leaves its last N step directories on
disk forever. The sweeper closes that gap operator-side: it walks
Succeeded TPUJobs whose checkpoint directory is recorded on the job
(``ckpt.tpuflow.org/dir``, rolled up by ckpt/registry.py), and prunes
step directories beyond the retention policy:

- keep the newest ``keep`` steps (a Succeeded job usually wants exactly
  one restorable checkpoint for eval/serving),
- additionally drop any step older than ``ttl`` seconds (0 = no TTL) —
  with a TTL even the newest step expires once the job is old news.

Only directories that LOOK like orbax steps (all-digit basenames directly
under the recorded directory) are ever touched, and the checkpoint root
itself is never removed. The sweeper runs where the checkpoint storage is
reachable — the local-executor runtime by construction; on a real cluster
it would run wherever the shared filesystem is mounted.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from dataclasses import dataclass

from tf_operator_tpu.ckpt import protocol
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ApiError, ClusterClient
from tf_operator_tpu.runtime.metrics import CKPT_GC_STEPS_TOTAL
from tf_operator_tpu.utils import logger


@dataclass
class SweepConfig:
    keep: int = 1  # newest steps retained per Succeeded job
    ttl: float = 0.0  # seconds after which even retained steps expire (0 = never)
    interval: float = 60.0  # seconds between sweeps


class CheckpointSweeper:
    def __init__(
        self,
        client: ClusterClient,
        config: SweepConfig | None = None,
        namespace: str | None = None,
    ) -> None:
        self._client = client
        self.config = config or SweepConfig()
        self._namespace = namespace
        self._log = logger.with_fields(component="ckpt-gc")

    def start(self, stop: threading.Event) -> None:
        def loop() -> None:
            while not stop.wait(self.config.interval):
                try:
                    self.sweep()
                except Exception:
                    self._log.exception("checkpoint sweep failed")

        threading.Thread(target=loop, name="ckpt-gc", daemon=True).start()

    def sweep(self, now: float | None = None) -> int:
        """One pass: prune step dirs of every Succeeded job. Returns how
        many step directories were removed."""
        now = now if now is not None else time.time()
        try:
            jobs = self._client.list(objects.TPUJOBS, self._namespace)
        except ApiError:
            return 0
        removed = 0
        for job in jobs:
            if not _succeeded(job):
                continue
            directory = (
                objects.meta(job).get("annotations") or {}
            ).get(protocol.JOB_DIR, "")
            if directory:
                removed += self.sweep_dir(directory, now)
        return removed

    def sweep_dir(self, directory: str, now: float | None = None) -> int:
        """Prune one checkpoint directory per the retention policy."""
        now = now if now is not None else time.time()
        try:
            entries = os.listdir(directory)
        except OSError:
            return 0
        steps = sorted(
            (int(e), os.path.join(directory, e))
            for e in entries
            if e.isdigit() and os.path.isdir(os.path.join(directory, e))
        )
        doomed = steps[: max(0, len(steps) - max(0, self.config.keep))]
        if self.config.ttl > 0:
            for step, path in steps[len(doomed):]:
                try:
                    age = now - os.path.getmtime(path)
                except OSError:
                    continue
                if age > self.config.ttl:
                    doomed.append((step, path))
        removed = 0
        for step, path in doomed:
            try:
                shutil.rmtree(path)
                removed += 1
            except OSError:
                self._log.warning("could not remove checkpoint step %s", path)
        if removed:
            CKPT_GC_STEPS_TOTAL.inc(removed)
            self._log.info(
                "pruned %d checkpoint step(s) under %s", removed, directory
            )
        return removed


def _succeeded(job: dict) -> bool:
    for cond in (job.get("status") or {}).get("conditions", []):
        if cond.get("type") == "Succeeded" and cond.get("status") == "True":
            return True
    return False
