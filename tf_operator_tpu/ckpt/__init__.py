"""Checkpoint coordination: operator-owned checkpoint registry, ack'd
graceful eviction, resume injection, and checkpoint GC.

- ``protocol``: annotations, env vars, ack file — the wire contract.
- ``registry``: per-job roll-up + the eviction-barrier ack source.
- ``gc``: retention sweeper for finished jobs' checkpoint directories.
- ``httpapi``: the /debug/ckpt endpoint.

Re-exports resolve lazily (PEP 562): workload-side importers reach
``ckpt.protocol`` through this package too, and must not drag the
operator-side registry/GC modules (runtime client, metrics, api types)
into every training process just by importing the package.

See docs/checkpoint.md for the state machine, the ack protocol, grace
semantics, and the GC policy; tools/ckpt_smoke.py runs the marked test
subset.
"""

_EXPORTS = {
    "BarrierStatus": "registry",
    "CheckpointRecord": "registry",
    "CheckpointRegistry": "registry",
    "CkptConfig": "registry",
    "CheckpointSweeper": "gc",
    "SweepConfig": "gc",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(
        importlib.import_module(f"{__name__}.{module}"), name
    )
