"""/debug/ckpt HTTP surface: the checkpoint registry snapshot `tpuctl
ckpt` renders.

Mounts on the operator's ApiServer via its extra-handler hook, exactly
like /debug/scheduler and /debug/health. Read-only: the checkpoint record
is written by workers (acks) and the controller (roll-up), never by hand.

    GET /debug/ckpt → CheckpointRegistry.snapshot()
"""

from __future__ import annotations

import json
from typing import Any

from tf_operator_tpu.utils import logger

LOG = logger.with_fields(component="ckpt-api")


class CkptApiHandler:
    def __init__(self, registry: Any) -> None:
        self._registry = registry

    def __call__(self, req: Any) -> bool:
        path = req.path.split("?", 1)[0]
        if req.command != "GET" or path != "/debug/ckpt":
            return False
        body = json.dumps(self._registry.snapshot(), indent=2).encode()
        req.send_response(200)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)
        return True


def mount_ckpt(api_server: Any, registry: Any) -> CkptApiHandler:
    handler = CkptApiHandler(registry)
    api_server.add_handler(handler)
    LOG.info("checkpoint API mounted at /debug/ckpt")
    return handler
