"""KV-cache storage for continuous batching: the dense slot tensor, the
block-paged pool with copy-on-write prefix sharing, and their host-side
allocators.

Two layouts, one engine (serve/engine.py picks per ``kv_paged``):

**Dense slot tensor** (the PR-5 layout, now the ``--kv-dense`` escape
hatch). The solo decode cache pytree (models/transformer.py,
``decode=True``: per-layer ``cached_key``/``cached_value`` of
``[1, max_seq_len, KV, Dh]`` plus scalar counters) stacked over a
leading ``max_slots`` axis. One allocation up front; occupancy changes
never allocate; the engine's decode step is a plain ``jax.vmap`` of the
solo single-token step. Simple — but every slot pre-pays ``max_seq_len``
rows whether its request uses 200 of them or all of them.

**Block-paged pool** (the default). Per layer, ONE pooled tensor of
``[kv_num_blocks, kv_block, KV, Dh]`` token blocks; each slot carries a
``[max_seq_len // kv_block]`` int32 block table (gather indices into the
pool — runtime DATA, so table contents never recompile) and a per-lane
position counter. ``BlockAllocator`` hands out refcounted blocks to
ACTUAL lengths (prompt + max new tokens), so the admission limit becomes
"enough free blocks", not "a free max-len row" — the occupancy/memory
multiplier for HBM-bound serving. Block 0 is RESERVED: the pinned
garbage block that unused table entries point at (always masked by the
position counters, never allocated, never read into results).

**Prefix sharing + copy-on-write.** ``PrefixCache`` keys live prompts by
block-aligned prefix hash: a new request whose prompt extends a
registered prefix maps those table entries to the donor's physical
blocks (refcount bumps) and prefills only its suffix; an EXACT
whole-prompt match also reuses the donor's stored last-position logits
and skips prefill entirely. Shared full blocks hold only immutable
prompt rows and are never written; the one writable case — an exact
match whose last block is PARTIAL (the sharer's first generated token
lands in it) — is handled by copy-on-write: the engine copies the block
to a privately-owned one right before the first step that would write
it (``make_cow_fn``). Entries reference live blocks only: when the last
holder of a block releases it, every entry touching that block drops —
reuse spans concurrently-live requests (where the serving win is); a
persistent prefix store would need an eviction policy against the same
pool and is future work.

A slot's lifecycle is unchanged from PR 5 — acquire → insert a finished
solo prefill → in-place decode steps → release — and nothing is cleared
on release in either layout: the next occupant's insert overwrites (or
the reallocated blocks' next owner does), and decode attention masks
positions beyond each lane's own counter, so stale K/V are unreachable
garbage, never data.
"""

from __future__ import annotations

import hashlib
import heapq
import threading
from collections.abc import Mapping
from dataclasses import dataclass
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

# Position-counter leaf names in the decode cache (the two MUST move in
# lockstep — see transformer.set_cache_index, which owns that contract).
INDEX_KEYS = ("cache_index", "pos_index")

# Paged pool leaf -> the dense/solo leaf holding the same rows. The
# kv-int8 scale sidecars (f32 [nb, blk, KV] per-block pools riding the
# same block tables — present only when cfg.kv_int8) address their rows
# through the IDENTICAL table[pos // B] * B + pos % B math as the K/V
# blocks, so one generic walk serves scatter, gather, and copy-on-write
# for all four leaves.
POOL_KEYS = {
    "pool_key": "cached_key",
    "pool_value": "cached_value",
    "pool_key_scale": "key_scale",
    "pool_value_scale": "value_scale",
}

# Paged pool leaf -> the part name its rows travel under in the
# shipped-KV wire format (serve/disagg.py): K/V rows as "key"/"value"
# (wire v1 since PR 14), the kv-int8 per-(token, head) f32 scale
# sidecars as "key_scale"/"value_scale" ([S, KV] rows — 2-D, no Dh
# axis). One mapping shared by the export (disagg.export_shipment walks
# the dense twins), the ingest scatter (make_pool_write_fn), and the
# engine's coverage check, so a new pool leaf cannot silently miss the
# wire.
POOL_WIRE_PARTS = {
    "pool_key": "key",
    "pool_value": "value",
    "pool_key_scale": "key_scale",
    "pool_value_scale": "value_scale",
}


def plain_tree(tree: Any) -> Any:
    """Rebuild a cache pytree's mappings as plain dicts: flax versions
    disagree about FrozenDict vs dict, and the stacked tree must share
    one treedef with every solo cache that gets inserted into it."""
    if isinstance(tree, Mapping):
        return {k: plain_tree(v) for k, v in tree.items()}
    return tree


def solo_cache_template(model: Any) -> Any:
    """The (empty) solo decode cache pytree for one request: what
    ``model.init`` builds for a [1, 1] token batch — leaves
    [1, max_seq_len, KV, Dh] plus scalar counters."""
    return plain_tree(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32))[
            "cache"
        ]
    )


def _maybe_shard(tree: Any, mesh: Any, tp_axis: str,
                 dp_pool: bool = False) -> Any:
    """Place a freshly-built cache tree per the engine's mesh layout
    (serve/sharding.py): K/V storage head-sharded over ``tp_axis``,
    per-slot state dp-sharded when the mesh carries a ``dp`` axis (and
    the pool's block axis too under ``dp_pool`` — the tp×dp engine's
    extent-allocated layout). mesh None = single-chip, tree
    untouched."""
    if mesh is None:
        return tree
    from tf_operator_tpu.serve.sharding import shard_engine_state

    return shard_engine_state(mesh, tree, tp_axis=tp_axis,
                              dp_pool=dp_pool)


def stack_slots(template: Any, max_slots: int, mesh: Any = None,
                tp_axis: str = "tp") -> Any:
    """Preallocate the dense slot tensor: every solo leaf grows a leading
    [max_slots] axis, zero-filled. One allocation up front — occupancy
    changes never allocate or reshape anything again. Under a mesh the
    K/V rows are head-sharded at allocation (each chip holds KV/tp heads
    of every row); a dp axis additionally splits the slot axis so each
    dp group holds only its own slots' rows."""
    return _maybe_shard(
        jax.tree.map(
            lambda x: jnp.zeros((max_slots,) + x.shape, x.dtype),
            plain_tree(template),
        ),
        mesh, tp_axis,
    )


def paged_cache_template(model: Any, max_slots: int,
                         mesh: Any = None, tp_axis: str = "tp",
                         dp_pool: bool = False) -> Any:
    """The paged engine's whole cache state in one init: a [max_slots, 1]
    token batch through the kv_paged model builds the per-layer pools
    ([kv_num_blocks, kv_block, KV, Dh]), per-lane block tables
    ([max_slots, table_len] int32, all entries on the pinned block 0),
    and per-lane counters ([max_slots] int32). Under a mesh the pools
    are head-sharded at allocation — the per-chip pool footprint divides
    by tp, which is what lets ``--kv-pool-blocks`` grow with the slice;
    ``dp_pool=True`` (the tp×dp engine) splits the block axis over dp
    too, on the promise that each dp shard's slots allocate only from
    their own block extent."""
    return _maybe_shard(
        plain_tree(
            model.init(
                jax.random.PRNGKey(0), jnp.zeros((max_slots, 1), jnp.int32)
            )["cache"]
        ),
        mesh, tp_axis, dp_pool,
    )


def mask_inactive_indices(cache: Any, active: jax.Array) -> Any:
    """Zero the position counters of inactive slots (traced; ``active``
    is [N] bool). Inactive slots still execute the fixed-shape decode
    step — that is the whole design — and without this reset their dead
    counters would keep advancing: past max_seq_len the K/V write clamps
    onto the last row and the position-embedding gather goes out of
    range. Active slots' counters pass through untouched, so the reset
    is invisible to real requests. (The paged attend additionally DROPS
    the writes of index-0 lanes, so a retired lane's stale block table
    can never corrupt a reallocated block.)"""

    def walk(node):
        if isinstance(node, Mapping):
            return {
                k: (jnp.where(active, v, 0) if k in INDEX_KEYS else walk(v))
                for k, v in node.items()
            }
        return node

    return walk(cache)


def make_insert_fn(constraint=None):
    """Jitted (stacked, slot, solo) → stacked with that slot row replaced
    by the solo cache (dense layout). ``slot`` is a TRACED int32
    argument, so one executable serves every slot; the stacked tree is
    donated — a join updates the slot tensor in place rather than
    doubling it. ``constraint`` (mesh engines) pins the output tree to
    the engine's canonical shardings so the donated buffer round-trips
    with an identical layout."""

    def insert(stacked, slot, solo):
        out = jax.tree.map(
            lambda full, one: full.at[slot].set(one), stacked, solo
        )
        return constraint(out) if constraint is not None else out

    return jax.jit(insert, donate_argnums=(0,))


def make_paged_insert_fn(num_blocks: int, block: int, constraint=None):
    """Jitted (paged, slot, write_table, read_table, solo) → paged with:

    - the solo dense cache's K/V rows scattered into pool blocks through
      ``write_table`` — entries pointing at block 0 dump their rows into
      the pinned garbage block, which is how shared-prefix rows (already
      resident in the donor's blocks) and rows past the prompt are
      skipped WITHOUT a dynamic-length scatter;
    - the slot's block-table row set to ``read_table`` (the real blocks,
      shared ones included);
    - the slot's counters set from the solo counters.

    slot and both tables are traced DATA: one executable serves every
    join, every table content, every sharing pattern. The paged tree is
    donated (in-place on device); ``constraint`` pins mesh layouts as in
    ``make_insert_fn``."""

    def insert(paged, slot, write_table, read_table, solo):
        def walk(p, s):
            if not isinstance(p, Mapping):
                return p
            out = {}
            for name, leaf in p.items():
                if name in POOL_KEYS:
                    rows = s[POOL_KEYS[name]][0]  # [S, KV, Dh]
                    pos = jnp.arange(rows.shape[0])
                    flat = write_table[pos // block] * block + pos % block
                    flat_pool = leaf.reshape(
                        (num_blocks * block,) + leaf.shape[2:]
                    )
                    out[name] = flat_pool.at[flat].set(rows).reshape(
                        leaf.shape
                    )
                elif name == "block_table":
                    out[name] = leaf.at[slot].set(read_table)
                elif name in INDEX_KEYS:
                    out[name] = leaf.at[slot].set(
                        jnp.asarray(s[name], jnp.int32)
                    )
                else:
                    out[name] = walk(leaf, s[name])
            return out

        out = walk(paged, solo)
        return constraint(out) if constraint is not None else out

    return jax.jit(insert, donate_argnums=(0,))


def make_table_insert_fn(constraint=None):
    """Jitted (paged, slot, read_table, index) → paged with only the
    slot's block-table row and counters set — the exact-prefix-match
    join, where every prompt row already lives in shared blocks and
    there is nothing to scatter. ``constraint`` pins mesh layouts."""

    def insert(paged, slot, read_table, index):
        def walk(p):
            if not isinstance(p, Mapping):
                return p
            out = {}
            for name, leaf in p.items():
                if name == "block_table":
                    out[name] = leaf.at[slot].set(read_table)
                elif name in INDEX_KEYS:
                    out[name] = leaf.at[slot].set(index)
                else:
                    out[name] = walk(leaf)
            return out

        out = walk(paged)
        return constraint(out) if constraint is not None else out

    return jax.jit(insert, donate_argnums=(0,))


def make_pool_write_fn(num_blocks: int, block: int, constraint=None):
    """Jitted (paged, write_table, rows) → paged with SHIPPED K/V rows
    scattered into pool blocks through ``write_table`` — the
    disaggregated-prefill ingest (serve/disagg.py): a prefill replica's
    finished rows land in freshly-allocated blocks WITHOUT touching any
    slot's table or counters (the request that owns them joins later
    through the ordinary exact-prefix table-insert path, which is what
    makes shipped decode bit-identical to local).

    ``rows`` maps each attention layer's cache path ("/"-joined module
    names) to ``{"key": [S, KV, Dh], "value": [S, KV, Dh]}`` — plus, on
    kv-int8 pools, ``{"key_scale"/"value_scale": [S, KV]}`` f32 scale
    sidecars riding the SAME write table (POOL_WIRE_PARTS names the
    leaves; the engine's coverage check guarantees the rows dict matches
    the pool before this traces) — padded to the full ``max_seq_len``
    row count so ONE executable serves every shipment; entries of
    ``write_table`` beyond the shipment's blocks are 0 and dump the pad
    rows into the pinned garbage block, exactly the
    ``make_paged_insert_fn`` trick. The paged tree is donated;
    ``constraint`` pins mesh layouts."""

    def write(paged, write_table, rows):
        def walk(p, path):
            if not isinstance(p, Mapping):
                return p
            out = {}
            for name, leaf in p.items():
                if name in POOL_WIRE_PARTS:
                    r = rows["/".join(path)][
                        POOL_WIRE_PARTS[name]
                    ]  # [S, KV, Dh] (K/V) or [S, KV] (scales)
                    pos = jnp.arange(r.shape[0])
                    flat = write_table[pos // block] * block + pos % block
                    flat_pool = leaf.reshape(
                        (num_blocks * block,) + leaf.shape[2:]
                    )
                    out[name] = flat_pool.at[flat].set(r).reshape(
                        leaf.shape
                    )
                elif isinstance(leaf, Mapping):
                    out[name] = walk(leaf, path + (name,))
                else:
                    out[name] = leaf
            return out

        out = walk(paged, ())
        return constraint(out) if constraint is not None else out

    return jax.jit(write, donate_argnums=(0,))


def make_gather_fn(block: int):
    """Jitted (paged, table) → a SOLO dense cache whose K/V rows are the
    table's blocks in order (counters zero): the seed for a shared-prefix
    SUFFIX prefill — gather the donor's prefix rows back into the dense
    layout, ``set_cache_index(n)``, and run the remaining prompt through
    the ordinary dense prefill path. Rows beyond the shared prefix
    gather whatever the table's private/garbage blocks hold; the suffix
    prefill overwrites [n:L) before reading them and masks the rest, so
    only the prefix rows matter — and those are bitwise the donor's."""

    def gather(paged, table):
        def walk(p):
            if not isinstance(p, Mapping):
                return p
            out = {}
            for name, leaf in p.items():
                if name in POOL_KEYS:
                    rows = leaf[table].reshape(
                        (table.shape[0] * block,) + leaf.shape[2:]
                    )
                    out[POOL_KEYS[name]] = rows[None]
                elif name == "block_table":
                    continue  # paged-only bookkeeping
                elif name in INDEX_KEYS:
                    out[name] = jnp.zeros((), jnp.int32)
                else:
                    out[name] = walk(leaf)
            return out

        return walk(paged)

    return jax.jit(gather)


def make_cow_fn(constraint=None):
    """Jitted (paged, slot, entry, src, dst) → paged with every layer's
    pool block ``src`` copied into ``dst`` and the slot's table entry
    switched to ``dst`` — the copy-on-write step, run by the engine right
    before the first decode write into a shared partial block. All
    indices traced; one executable serves every copy; the tree is
    donated. Under a mesh the copy is shard-local (each chip copies its
    KV/tp heads of the block — no collective) and ``constraint`` pins
    the output layout."""

    def cow(paged, slot, entry, src, dst):
        def walk(p):
            if not isinstance(p, Mapping):
                return p
            out = {}
            for name, leaf in p.items():
                if name in POOL_KEYS:
                    out[name] = leaf.at[dst].set(leaf[src])
                elif name == "block_table":
                    out[name] = leaf.at[slot, entry].set(dst)
                else:
                    out[name] = walk(leaf)
            return out

        out = walk(paged)
        return constraint(out) if constraint is not None else out

    return jax.jit(cow, donate_argnums=(0,))


class SlotAllocator:
    """Free-slot bookkeeping for the slot tensor (host-side, thread-safe).

    Lowest-free-index policy — deterministic, which the exactness matrix
    and the serve bench's seeded schedules rely on — served from a heap:
    acquire is O(log n) where the original list scan (`min` + `remove`)
    was O(n) per call. Tracks a high-water mark and cumulative acquire
    count for the /debug surface.

    ``dp`` > 1 (the pod-scale tp×dp engine) partitions the slot space
    into ``dp`` contiguous slices per ``sharding.shard_of_slot`` — one
    heap per slice, so ``acquire(shard=i)`` hands out the lowest free
    slot OWNED by dp shard i. ``acquire()`` with no shard stays the
    global lowest-free policy (the head of the first non-empty slice
    heap), which makes dp=1 behavior bit-identical to the original
    single heap."""

    def __init__(self, max_slots: int, dp: int = 1) -> None:
        if max_slots < 1:
            raise ValueError(f"max_slots={max_slots} must be >= 1")
        if dp < 1 or max_slots % dp:
            raise ValueError(
                f"dp={dp} must be >= 1 and divide max_slots={max_slots}"
            )
        self.max_slots = max_slots
        self.dp = dp
        self._per = max_slots // dp
        # Ascending ranges == already heaps; slice i owns
        # [i*per, (i+1)*per), matching P(dp) on a slot-leading axis.
        self._heaps = [
            list(range(i * self._per, (i + 1) * self._per))
            for i in range(dp)
        ]
        self._free_set = set(range(max_slots))
        self._lock = threading.Lock()
        self.acquired_total = 0
        self.high_water = 0

    def acquire(self, shard: int | None = None) -> int | None:
        """Lowest free slot index — globally (``shard=None``), or within
        dp shard ``shard``'s slot slice. None when the chosen scope is
        fully occupied."""
        with self._lock:
            if shard is None:
                heap = next((h for h in self._heaps if h), None)
            else:
                heap = self._heaps[shard]
            if not heap:
                return None
            slot = heapq.heappop(heap)
            self._free_set.discard(slot)
            self.acquired_total += 1
            self.high_water = max(self.high_water, self.in_use)
            return slot

    def release(self, slot: int) -> None:
        with self._lock:
            if not 0 <= slot < self.max_slots:
                raise ValueError(f"slot {slot} out of range")
            if slot in self._free_set:
                raise ValueError(f"slot {slot} double-released")
            heapq.heappush(self._heaps[slot // self._per], slot)
            self._free_set.add(slot)

    def free_in(self, shard: int) -> int:
        """Free slots in dp shard ``shard``'s slice (admission's
        per-shard capacity check)."""
        with self._lock:
            return len(self._heaps[shard])

    def reset_high_water(self) -> None:
        """Start a fresh high-water window at the current occupancy (the
        serve bench measures admitted concurrency over its timed pass
        only, after the untimed warmup)."""
        with self._lock:
            self.high_water = self.in_use

    @property
    def in_use(self) -> int:
        return self.max_slots - len(self._free_set)

    @property
    def free(self) -> int:
        return len(self._free_set)


class BlockAllocator:
    """Refcounted allocator for the paged KV block pool (host-side,
    thread-safe — the engine loop allocates, /debug and /metrics threads
    read). Block indices below ``reserved`` (the pinned garbage block 0)
    are never handed out. Same lowest-free-index heap policy as
    ``SlotAllocator``, for the same determinism reasons.

    Refcounts: an exclusively-owned block has refcount 1; prefix sharing
    bumps it per sharer. ``free`` decrements and returns the blocks that
    actually hit zero (the caller invalidates PrefixCache entries that
    referenced them).

    ``dp`` > 1 (the pod-scale tp×dp engine) partitions the block-index
    space into per-shard extents per ``sharding.shard_block_extent`` —
    one heap per extent, so ``alloc(k, shard=i)`` grants only blocks
    INSIDE dp shard i's pool slice (what makes the dp-sharded pool
    layout legal: every table entry of a shard's slots points at its
    own slice). ``alloc(k)`` with no shard stays the global lowest-free
    policy, bit-identical to the original single heap at dp=1."""

    def __init__(self, num_blocks: int, reserved: int = 1,
                 dp: int = 1) -> None:
        if num_blocks <= reserved:
            raise ValueError(
                f"num_blocks={num_blocks} must exceed the {reserved} "
                "reserved block(s)"
            )
        if dp < 1:
            raise ValueError(f"dp={dp} must be >= 1")
        if dp > 1 and num_blocks // dp <= reserved:
            raise ValueError(
                f"num_blocks={num_blocks} leaves dp shard 0 no "
                f"allocatable blocks past the {reserved} reserved "
                f"(need num_blocks // dp > reserved at dp={dp})"
            )
        from tf_operator_tpu.serve.sharding import shard_block_extent

        self.num_blocks = num_blocks
        self.reserved = reserved
        self.dp = dp
        self._per = num_blocks // dp
        self._extents = [
            shard_block_extent(i, num_blocks, dp, reserved)
            for i in range(dp)
        ]
        self._heaps = [list(range(lo, hi)) for lo, hi in self._extents]
        self._free_set = set().union(*map(set, self._heaps))
        self._refs: dict[int, int] = {}
        self._lock = threading.Lock()
        self.high_water = 0

    def _shard_of(self, blk: int) -> int:
        return min(blk // self._per, self.dp - 1)

    def alloc(self, k: int, shard: int | None = None) -> list[int] | None:
        """The k lowest free blocks at refcount 1 — globally
        (``shard=None``) or from dp shard ``shard``'s extent — or None
        when fewer than k are free in the chosen scope (all-or-nothing:
        a partial grant would deadlock two half-admitted requests
        against each other)."""
        with self._lock:
            if shard is not None:
                heaps = [self._heaps[shard]]
            else:
                heaps = self._heaps
            if k > sum(len(h) for h in heaps):
                return None
            out: list[int] = []
            for _ in range(k):
                heap = min((h for h in heaps if h), key=lambda h: h[0])
                out.append(heapq.heappop(heap))
            for blk in out:
                self._free_set.discard(blk)
                self._refs[blk] = 1
            self.high_water = max(self.high_water, self.used)
            return out

    def ref(self, blocks) -> None:
        """Bump refcounts of LIVE blocks (prefix sharing)."""
        with self._lock:
            for blk in blocks:
                if blk not in self._refs:
                    raise ValueError(f"block {blk} is not live")
                self._refs[blk] += 1

    def free(self, blocks) -> list[int]:
        """Decrement refcounts; blocks hitting zero return to the pool.
        Returns the fully-freed blocks (their prefix entries are now
        invalid)."""
        freed: list[int] = []
        with self._lock:
            for blk in blocks:
                rc = self._refs.get(blk)
                if rc is None:
                    raise ValueError(f"block {blk} double-freed")
                if rc > 1:
                    self._refs[blk] = rc - 1
                    continue
                del self._refs[blk]
                heapq.heappush(self._heaps[self._shard_of(blk)], blk)
                self._free_set.add(blk)
                freed.append(blk)
        return freed

    def free_in(self, shard: int) -> int:
        """Free blocks in dp shard ``shard``'s extent (admission's
        per-shard capacity check / shard-choice tiebreak)."""
        with self._lock:
            return len(self._heaps[shard])

    def shard_extent(self, shard: int) -> tuple[int, int]:
        """[lo, hi) of the global block indices shard ``shard`` owns —
        the ``within`` bound extent-aware prefix probes use."""
        return self._extents[shard]

    @property
    def free_blocks(self) -> int:
        return len(self._free_set)

    @property
    def used(self) -> int:
        return self.num_blocks - self.reserved - len(self._free_set)

    @property
    def shared(self) -> int:
        """Blocks currently referenced by more than one holder."""
        with self._lock:
            return sum(1 for rc in self._refs.values() if rc >= 2)


@dataclass
class _PrefixEntry:
    tokens: np.ndarray           # the prefix itself (collision guard)
    n: int                       # prefix length in tokens
    blocks: tuple[int, ...]      # physical blocks holding rows [0:n)
    logits: np.ndarray | None    # last-position logits (exact entries)


class PrefixCache:
    """Block-aligned prefix registry for copy-on-write prefix sharing.

    Keys are CHAINED per-block SHA-1 digests — ``D_k = sha1(D_{k-1} +
    block_k_bytes)``, the exact (partial-tail) key chained once more
    over the tail — so registering or probing ALL of a prompt's aligned
    prefixes hashes each token exactly once: O(L) per admission, not
    the O(L²/block) of rehashing every prefix from scratch (the feature
    targets long contexts, where that difference sits on the admission
    hot path). Entries for one prompt share views of a single stored
    token copy; the view is compared on a digest hit, so a collision
    degrades to a miss, never to wrong K/V. For an admitted prompt of L
    tokens the engine registers every full-block prefix (k*block tokens
    → the first k table blocks) plus the exact prompt (all its blocks,
    partial last block included, with the last-position logits) — so a
    later request can share as much block-aligned prefix as it matches,
    and an identical prompt skips prefill entirely.

    Entries reference LIVE blocks only — the cache itself never pins:
    when the last holder of a block releases it (``BlockAllocator.free``
    reports it), ``invalidate_blocks`` drops every entry referencing it.
    Persistence past a request's own slot is the ENGINE's job: with
    retention enabled (``ContinuousEngine.prefix_retain_max`` > 0) the
    engine takes one extra pool reference per exact-entry block at
    registration (``exact_hold`` is its read), so the entry outlives
    its slot until the bounded retained set evicts it — that is what
    fleet-global prefix advertisement and ``/prefix/<digest>`` exports
    serve from."""

    def __init__(self, block: int) -> None:
        self.block = block
        self._entries: dict[bytes, _PrefixEntry] = {}
        self._by_block: dict[int, set[bytes]] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    _SEED = hashlib.sha1(b"tpu-kv-prefix").digest()

    def _chain_keys(self, tokens: np.ndarray) -> list[tuple[int, bytes]]:
        """[(n_tokens, digest)] for every full-block-aligned prefix plus
        the exact length, LONGEST first, hashing each token exactly
        once. For an aligned prompt the exact key IS the last
        full-block key — which is how an exact admission upgrades that
        entry with its sampling logits."""
        L, B = len(tokens), self.block
        digest = self._SEED
        keys: list[tuple[int, bytes]] = []
        for k in range(L // B):
            digest = hashlib.sha1(
                digest + tokens[k * B:(k + 1) * B].tobytes()
            ).digest()
            keys.append(((k + 1) * B, digest))
        if L % B:
            keys.append((L, hashlib.sha1(
                digest + tokens[(L // B) * B:].tobytes()
            ).digest()))
        keys.reverse()
        return keys

    def _match(self, tokens: np.ndarray,
               within: tuple[int, int] | None = None):
        """Longest usable entry for ``tokens`` (caller holds the lock):
        ``(n, key, entry)`` or None. ``within=(lo, hi)`` (the tp×dp
        engine's dp-shard block extent) skips entries holding any block
        outside that range — a shard can only table-reference blocks in
        its own pool slice, so a donor living on another shard is a
        miss FOR THAT SHARD even though the digest is registered."""
        L = len(tokens)
        for n, key in self._chain_keys(tokens):
            e = self._entries.get(key)
            if (
                e is None
                or e.n != n
                or not np.array_equal(e.tokens, tokens[:n])
            ):
                continue
            if n == L and e.logits is None:
                continue  # full-length but no sampling row: downgrade
            if within is not None and any(
                not (within[0] <= b < within[1]) for b in e.blocks
            ):
                continue
            return n, key, e
        return None

    def lookup(self, tokens: np.ndarray,
               within: tuple[int, int] | None = None):
        """Longest usable prefix of ``tokens`` ([L] int32): the exact
        whole prompt first (may end mid-block — sharing that partial
        block is what makes copy-on-write reachable), else the longest
        registered full-block prefix. Returns (n_tokens, blocks,
        logits | None); logits only on an exact whole-prompt match (the
        donor's last-position row — the sharer's first sampling input).
        An exact-length match WITHOUT stored logits (the digest was
        registered as a longer prompt's aligned prefix) is skipped in
        favor of a shorter match: sharing it would leave nothing to
        prefill yet no logits to sample from. ``within`` restricts the
        match to entries whose blocks all sit inside one dp shard's
        extent (see ``_match``)."""
        tokens = np.ascontiguousarray(
            np.asarray(tokens, np.int32).reshape(-1)
        )
        L = len(tokens)
        with self._lock:
            m = self._match(tokens, within)
            if m is not None:
                n, key, e = m
                self.hits += 1
                # Recency refresh: dict order IS the LRU order the
                # fleet advertisement (``advertise``) reads — a hit
                # moves the entry to the hot end.
                self._entries[key] = self._entries.pop(key)
                return n, tuple(e.blocks), (
                    e.logits if n == L else None
                )
            self.misses += 1
        return 0, (), None

    def peek(self, tokens: np.ndarray,
             within: tuple[int, int] | None = None):
        """``lookup`` without side effects: no hit/miss counters, no LRU
        refresh. The tp×dp admission planner probes EVERY dp shard's
        extent with this to pick the shard owning the deepest usable
        prefix — only the chosen shard's subsequent real ``lookup``
        should count and refresh."""
        tokens = np.ascontiguousarray(
            np.asarray(tokens, np.int32).reshape(-1)
        )
        L = len(tokens)
        with self._lock:
            m = self._match(tokens, within)
            if m is None:
                return 0, (), None
            n, _, e = m
            return n, tuple(e.blocks), (e.logits if n == L else None)

    def register(self, tokens: np.ndarray, blocks,
                 logits: np.ndarray | None = None) -> None:
        """Register an admitted prompt: ``blocks`` are its table entries
        ([ceil(L/block)] physical blocks, shared ones included — their
        digests already exist and are kept, first writer wins).
        ``logits`` (the last prompt position's row) lands on the exact
        full-length entry so identical prompts skip prefill."""
        tokens = np.ascontiguousarray(
            np.array(tokens, np.int32, copy=True).reshape(-1)
        )
        blocks = [int(b) for b in blocks]
        L, B = len(tokens), self.block
        with self._lock:
            for n, key in self._chain_keys(tokens):
                # Every entry stores a VIEW of the one copy made above
                # — O(L) memory for the whole prefix family.
                self._add(key, tokens[:n], n, blocks[: -(-n // B)],
                          logits if n == L else None)

    def _add(self, key, toks, n, blks, logits):
        e = self._entries.get(key)
        if e is not None:
            if (logits is not None and e.logits is None and e.n == n
                    and np.array_equal(e.tokens, toks)):
                # The digest was first registered as a longer prompt's
                # aligned prefix; this exact admission supplies the
                # sampling row that upgrade needs.
                e.logits = np.array(logits, copy=True)
            return
        self._entries[key] = _PrefixEntry(
            toks, n, tuple(blks),
            None if logits is None else np.array(logits, copy=True),
        )
        for b in blks:
            self._by_block.setdefault(b, set()).add(key)

    def invalidate_blocks(self, freed) -> list[_PrefixEntry]:
        """Drop every entry referencing a block whose last holder just
        released it (``BlockAllocator.free``'s return value). Returns
        the dropped entries — the engine's host-tier spill hook
        (serve/tier.py): the pool rows they reference stay intact until
        the freed blocks are REALLOCATED, so a caller that serializes
        them before its next allocation reads valid K/V. Callers
        without a tier ignore the return value."""
        dropped: list[_PrefixEntry] = []
        with self._lock:
            for blk in freed:
                for key in self._by_block.pop(blk, ()):
                    e = self._entries.pop(key, None)
                    if e is None:
                        continue
                    dropped.append(e)
                    for other in e.blocks:
                        if other != blk:
                            peers = self._by_block.get(other)
                            if peers is not None:
                                peers.discard(key)
        return dropped

    @property
    def entries(self) -> int:
        return len(self._entries)

    # -- fleet-global prefix reuse (fleet/prefixes.py) --------------------

    def advertise(self, cap: int = 32) -> list[str]:
        """The replica's hot-prefix advertisement: hex digests of up to
        ``cap`` entries, most-recently-used first (dict order is the LRU
        order — ``lookup`` hits refresh it, registrations append at the
        hot end). Rides the /healthz readiness payload so the fleet
        router can score prefix hits; entries reference LIVE blocks
        only, so a digest can go stale between the advertisement and a
        pull — that race is why ``/prefix/<digest>`` answers with the
        typed ``prefix_not_found`` instead of trusting this list."""
        if cap <= 0:
            return []  # NOT [-0:], which would be the whole table
        with self._lock:
            keys = list(self._entries)[-int(cap):]
        keys.reverse()
        return [k.hex() for k in keys]

    def entry_for_hex(self, digest_hex: str):
        """The live EXACT entry (stored sampling logits) under a hex
        digest, as ``(tokens, n, blocks, logits)`` copies — the
        ``GET /prefix/<digest>`` export's read. None when the digest
        names nothing live, or only a longer prompt's aligned prefix
        (no logits: the wire format cannot ship it, and the puller
        could not exact-join it)."""
        try:
            key = bytes.fromhex(digest_hex)
        except ValueError:
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.logits is None:
                return None
            return (
                np.array(e.tokens, np.int32, copy=True),
                e.n,
                tuple(e.blocks),
                np.array(e.logits, copy=True),
            )

    def exact_hold(self, tokens) -> tuple[bytes, tuple[int, ...]] | None:
        """``(digest, blocks)`` of the live exact-length entry for
        ``tokens`` (sampling row present) — the engine's retention
        hook: the blocks it must extra-reference to keep this entry
        alive past its last slot. None when the exact digest is
        unregistered, collided, or only a longer prompt's aligned
        prefix (nothing worth pinning: it could never exact-join or
        export)."""
        tokens = np.ascontiguousarray(
            np.asarray(tokens, np.int32).reshape(-1)
        )
        with self._lock:
            n, key = self._chain_keys(tokens)[0]
            e = self._entries.get(key)
            if (
                e is None
                or e.logits is None
                or e.n != n
                or not np.array_equal(e.tokens, tokens)
            ):
                return None
            return key, tuple(e.blocks)
