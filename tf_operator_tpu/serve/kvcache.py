"""Slot-based KV cache for continuous batching: the solo decode cache,
stacked over a leading SLOT axis, plus a free-slot allocator.

The solo decode path (models/transformer.py, ``decode=True``) keeps one
cache pytree per request: per-layer ``cached_key``/``cached_value``
buffers of ``[1, max_seq_len, KV, Dh]`` (int8 + per-(token, head) scale
sidecars under ``kv_int8``) and scalar position counters. Continuous
batching needs ``max_slots`` of those living side by side so requests can
occupy and release rows INDEPENDENTLY — so this module stacks that exact
pytree over a new leading axis: every leaf becomes ``[N, *solo_shape]``
(scalar counters become ``[N]`` int32 vectors). Nothing about the solo
layout changes, which is what makes the engine's per-slot decode step a
plain ``jax.vmap`` of the solo single-token step — the per-slot math is
the solo math, the exactness pins in tests/test_serve_engine.py hold
bit-for-bit, and the kv-int8 variant comes along for free.

A slot's lifecycle: ``SlotAllocator.acquire`` (host-side bookkeeping) →
the engine writes a freshly prefilled solo cache into the slot row
(``make_insert_fn`` — one jitted executable, slot index a traced
argument, so joins never recompile) → decode steps mutate the row in
place (the engine donates the stacked tree through its step) →
``SlotAllocator.release``. Nothing is cleared on release: the next
occupant's prefill insert overwrites the whole row, and decode attention
masks cache positions beyond the slot's own counter, so a previous
occupant's K/V rows are unreachable garbage, never data.
"""

from __future__ import annotations

import threading
from collections.abc import Mapping
from typing import Any

import jax
import jax.numpy as jnp

# Position-counter leaf names in the decode cache (the two MUST move in
# lockstep — see transformer.set_cache_index, which owns that contract).
INDEX_KEYS = ("cache_index", "pos_index")


def plain_tree(tree: Any) -> Any:
    """Rebuild a cache pytree's mappings as plain dicts: flax versions
    disagree about FrozenDict vs dict, and the stacked tree must share
    one treedef with every solo cache that gets inserted into it."""
    if isinstance(tree, Mapping):
        return {k: plain_tree(v) for k, v in tree.items()}
    return tree


def solo_cache_template(model: Any) -> Any:
    """The (empty) solo decode cache pytree for one request: what
    ``model.init`` builds for a [1, 1] token batch — leaves
    [1, max_seq_len, KV, Dh] plus scalar counters."""
    return plain_tree(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32))[
            "cache"
        ]
    )


def stack_slots(template: Any, max_slots: int) -> Any:
    """Preallocate the slot tensor: every solo leaf grows a leading
    [max_slots] axis, zero-filled. One allocation up front — occupancy
    changes never allocate or reshape anything again."""
    return jax.tree.map(
        lambda x: jnp.zeros((max_slots,) + x.shape, x.dtype),
        plain_tree(template),
    )


def mask_inactive_indices(cache: Any, active: jax.Array) -> Any:
    """Zero the position counters of inactive slots (traced; ``active``
    is [N] bool). Inactive slots still execute the fixed-shape decode
    step — that is the whole design — and without this reset their dead
    counters would keep advancing: past max_seq_len the K/V write clamps
    onto the last row and the position-embedding gather goes out of
    range. Active slots' counters pass through untouched, so the reset
    is invisible to real requests."""

    def walk(node):
        if isinstance(node, Mapping):
            return {
                k: (jnp.where(active, v, 0) if k in INDEX_KEYS else walk(v))
                for k, v in node.items()
            }
        return node

    return walk(cache)


def make_insert_fn():
    """Jitted (stacked, slot, solo) → stacked with that slot row replaced
    by the solo cache. ``slot`` is a TRACED int32 argument, so one
    executable serves every slot; the stacked tree is donated — a join
    updates the slot tensor in place rather than doubling it."""

    def insert(stacked, slot, solo):
        return jax.tree.map(
            lambda full, one: full.at[slot].set(one), stacked, solo
        )

    return jax.jit(insert, donate_argnums=(0,))


class SlotAllocator:
    """Free-slot bookkeeping for the slot tensor (host-side, thread-safe).

    Lowest-free-index policy — deterministic, which the exactness matrix
    and the serve bench's seeded schedules rely on. Tracks a high-water
    mark and cumulative acquire count for the /debug surface."""

    def __init__(self, max_slots: int) -> None:
        if max_slots < 1:
            raise ValueError(f"max_slots={max_slots} must be >= 1")
        self.max_slots = max_slots
        self._free = list(range(max_slots))
        self._lock = threading.Lock()
        self.acquired_total = 0
        self.high_water = 0

    def acquire(self) -> int | None:
        """Lowest free slot index, or None when fully occupied."""
        with self._lock:
            if not self._free:
                return None
            slot = min(self._free)
            self._free.remove(slot)
            self.acquired_total += 1
            self.high_water = max(self.high_water, self.in_use)
            return slot

    def release(self, slot: int) -> None:
        with self._lock:
            if not 0 <= slot < self.max_slots:
                raise ValueError(f"slot {slot} out of range")
            if slot in self._free:
                raise ValueError(f"slot {slot} double-released")
            self._free.append(slot)

    @property
    def in_use(self) -> int:
        return self.max_slots - len(self._free)

    @property
    def free(self) -> int:
        return len(self._free)
