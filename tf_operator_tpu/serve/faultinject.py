"""Deterministic, seeded fault injection for the serving stack.

Chaos testing a serving engine is only useful when the faults are
REPRODUCIBLE: a flaky failure that cannot be replayed teaches nothing.
Every fault here is therefore either positional (fire at the N-th
invocation of a named fault point — the same schedule every run) or
probabilistic under a seeded per-point rng (the same coin flips every
run for a given seed). The injector is passive: production code calls
``fire(point)`` at each fault point and the injector decides; with no
spec armed every call is a counter bump on an always-``None`` path, so
the hooks cost nothing in real serving.

Fault points (the names are the public contract — specs, tests,
serve_bench's chaos mix, and /debug/serve all use them):

- ``step_raise``   — the decode step raises ``InjectedFault`` (the
  engine-crash path: the serving loop dies mid-decode).
- ``step_stall``   — the decode step blocks for ``arg`` seconds before
  running (the wedged-step path the watchdog must catch).
- ``alloc_exhaust`` — ``plan_admission`` reports no capacity (block/
  slot-pool exhaustion without having to actually fill the pool).
- ``slow_prefill`` — each prefill slice sleeps ``arg`` seconds first
  (TTFT/queue pressure; exercises queue TTLs under load).
- ``ack_loss``     — the serving loop's heartbeat write is dropped (the
  false-positive stall: the watchdog fires on a HEALTHY engine, so
  restart + replay must be loss-free even when nothing was wrong).

Spec grammar (``TPU_SERVE_FAULTS`` env var or serve_lm ``--faults``)::

    spec  := entry ("," entry)*
    entry := point "@" HIT ["x" COUNT] [":" ARG]   # positional
           | point "%" PROB [":" ARG]              # probabilistic

``point@12`` fires at the 12th invocation of that point (1-based), once;
``x3`` extends to the 12th..14th; ``:0.5`` attaches a float argument
(stall/sleep seconds). ``point%0.05:0.01`` fires each invocation with
seeded probability 5%. Multiple entries for one point all apply.

One injector instance is shared by the engine, the scheduler, and the
supervisor — invocation counters persist across watchdog engine
rebuilds, so ``step_raise@40x999`` keeps crashing every rebuilt engine
(the bounded-restart / replica-dead path) while ``step_raise@40`` crashes
exactly one.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from dataclasses import dataclass

import numpy as np

FAULT_POINTS = frozenset({
    "step_raise", "step_stall", "alloc_exhaust", "slow_prefill", "ack_loss",
})

ENV_SPEC = "TPU_SERVE_FAULTS"
ENV_SEED = "TPU_SERVE_FAULT_SEED"


class InjectedFault(RuntimeError):
    """Raised by a triggered ``step_raise`` (and available to tests as
    the marker type proving a failure came from the injector, not a real
    bug)."""

    def __init__(self, point: str) -> None:
        super().__init__(f"injected fault: {point}")
        self.point = point


@dataclass
class _Arm:
    """One armed spec entry. Positional: fire while ``hit <= invocation
    < hit + count``. Probabilistic: fire on each invocation whose seeded
    draw lands under ``prob``."""

    point: str
    hit: int | None = None
    count: int = 1
    prob: float | None = None
    arg: float | None = None
    fired: int = 0

    def wants(self, invocation: int, rng: np.random.Generator) -> bool:
        if self.hit is not None:
            return self.hit <= invocation < self.hit + self.count
        return float(rng.random()) < float(self.prob or 0.0)


def _parse_entry(raw: str) -> _Arm:
    entry = raw.strip()
    arg = None
    if ":" in entry:
        entry, argtxt = entry.split(":", 1)
        arg = float(argtxt)
    if "@" in entry:
        point, postxt = entry.split("@", 1)
        count = 1
        if "x" in postxt:
            postxt, counttxt = postxt.split("x", 1)
            count = int(counttxt)
        hit = int(postxt)
        if hit < 1 or count < 1:
            raise ValueError(f"fault entry {raw!r}: hit/count must be >= 1")
        armed = _Arm(point.strip(), hit=hit, count=count, arg=arg)
    elif "%" in entry:
        point, probtxt = entry.split("%", 1)
        prob = float(probtxt)
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"fault entry {raw!r}: prob must be in [0, 1]")
        armed = _Arm(point.strip(), prob=prob, arg=arg)
    else:
        raise ValueError(
            f"fault entry {raw!r}: expected point@hit[xN][:arg] or "
            f"point%prob[:arg]"
        )
    if armed.point not in FAULT_POINTS:
        raise ValueError(
            f"unknown fault point {armed.point!r} (have "
            f"{sorted(FAULT_POINTS)})"
        )
    return armed


class FaultInjector:
    """Seeded fault-point registry. Thread-safe (the serving loop, HTTP
    handler threads, and the watchdog all pass through it); ``arm`` may
    be called on a live injector (tests re-arm between chaos phases)."""

    def __init__(self, spec: str = "", seed: int = 0) -> None:
        self._lock = threading.Lock()
        self.seed = int(seed)
        self._arms: list[_Arm] = []
        self.invocations: dict[str, int] = {p: 0 for p in FAULT_POINTS}
        self.fired: dict[str, int] = {p: 0 for p in FAULT_POINTS}
        self.last_fired: tuple[str, int] | None = None
        # Per-point rng streams: probabilistic determinism must not
        # depend on how OTHER points' invocations interleave.
        self._rngs = {
            p: np.random.default_rng([self.seed, zlib.crc32(p.encode())])
            for p in FAULT_POINTS
        }
        if spec:
            self.arm(spec)

    @classmethod
    def from_env(cls, env=None) -> "FaultInjector":
        env = os.environ if env is None else env
        return cls(env.get(ENV_SPEC, ""), seed=int(env.get(ENV_SEED, "0")))

    @property
    def enabled(self) -> bool:
        with self._lock:
            return bool(self._arms)

    def arm(self, spec: str) -> "FaultInjector":
        """Parse and ADD entries (existing arms and counters persist)."""
        arms = [_parse_entry(e) for e in spec.split(",") if e.strip()]
        with self._lock:
            self._arms.extend(arms)
        return self

    def disarm(self, point: str | None = None) -> None:
        """Drop armed entries (all, or one point's). Invocation counters
        keep counting — they are history, not configuration."""
        with self._lock:
            self._arms = [
                a for a in self._arms
                if point is not None and a.point != point
            ]

    # -- the hook -----------------------------------------------------------

    def fire(self, point: str) -> float | None:
        """Count one invocation of ``point``; return the triggering
        entry's arg (0.0 if it carried none) when a fault fires, else
        None. THE single decision function — every fault-point hook is
        a ``fire`` call plus the point-specific behavior."""
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {point!r}")
        with self._lock:
            self.invocations[point] += 1
            n = self.invocations[point]
            for a in self._arms:
                if a.point == point and a.wants(n, self._rngs[point]):
                    a.fired += 1
                    self.fired[point] += 1
                    self.last_fired = (point, n)
                    return a.arg if a.arg is not None else 0.0
        return None

    def maybe_raise(self, point: str) -> None:
        if self.fire(point) is not None:
            raise InjectedFault(point)

    def maybe_sleep(self, point: str, default: float = 0.05) -> bool:
        arg = self.fire(point)
        if arg is None:
            return False
        time.sleep(arg or default)
        return True

    # -- observability ------------------------------------------------------

    def snapshot(self) -> dict:
        """The /debug/serve ``faults`` payload."""
        with self._lock:
            return {
                "armed": [
                    {"point": a.point, "hit": a.hit, "count": a.count,
                     "prob": a.prob, "arg": a.arg, "fired": a.fired}
                    for a in self._arms
                ],
                "seed": self.seed,
                "invocations": {k: v for k, v in self.invocations.items()
                                if v},
                "fired": {k: v for k, v in self.fired.items() if v},
                "last_fired": list(self.last_fired)
                if self.last_fired else None,
            }


#: Shared disabled instance: the default ``faults`` everywhere, so the
#: hooks in the hot path are one attribute read + a short locked counter
#: bump and never allocate.
NULL_INJECTOR = FaultInjector()
