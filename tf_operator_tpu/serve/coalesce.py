"""The legacy batch-window coalescer (serve_lm ``--engine coalesce``).

Batches concurrent same-shape greedy requests into one lock-step decode:
rows sharing (prompt_len, num_steps) that arrive within the window run
as ONE decode call, padded up to the next power-of-two row count so the
set of compiled batch shapes stays small. Greedy-only (batching is
output-invariant for argmax decoding; sampled requests carry per-request
rngs and run solo), lock-step (every row rides to the longest horizon —
they share one), same-shape-only — the three restrictions the
continuous engine (serve/engine.py) exists to remove. Kept as its own
module so serve_lm's legacy path and the serve bench's comparison leg
(tools/serve_bench.py) drive the SAME implementation.

Extracted verbatim from examples/serve_lm.py, parameterized by the
decode callable and the shutdown event it previously closed over.
"""

from __future__ import annotations

import threading
from typing import Callable

import jax.numpy as jnp


class Coalescer:
    """Batch concurrent same-shape greedy requests into one decode.

    ``decode_fn(rows, num_steps) -> tokens`` runs the batched greedy
    decode (callers bake their own device locking into it); ``stop``
    ends the loop — which still drains everything already queued, and
    answers whatever remains with an error, never abandoning a waiter.
    """

    def __init__(self, window_s: float, max_rows: int,
                 decode_fn: Callable, stop: threading.Event) -> None:
        self.window_s = window_s
        self.max_rows = max_rows
        self.decode_fn = decode_fn
        self.stop = stop
        self.cond = threading.Condition()
        self.pending: list[dict] = []
        self.closed = False   # loop exited: no consumer remains
        self.batches = 0      # stats for /healthz (and tests)
        self.max_rows_seen = 0

    def submit(self, prompt, num_steps: int):
        item = {
            "key": (prompt.shape[1], num_steps),
            "rows": prompt,
            "event": threading.Event(),
            "out": None,
            "err": None,
        }
        with self.cond:
            if self.closed:
                # The batcher has exited (shutdown): failing fast
                # beats queueing where no consumer will ever look.
                raise RuntimeError("server shutting down")
            self.pending.append(item)
            self.cond.notify()
        if not item["event"].wait(timeout=300.0):
            raise TimeoutError("coalesced decode timed out")
        if item["err"] is not None:
            raise item["err"]
        return item["out"]

    def _key_rows(self, key) -> int:
        return sum(p["rows"].shape[0] for p in self.pending
                   if p["key"] == key)

    def _take_batch(self) -> list[dict]:
        with self.cond:
            # Wake exactly on submit()'s notify (or shutdown).
            self.cond.wait_for(
                lambda: self.pending or self.stop.is_set(), timeout=1.0
            )
            if not self.pending:
                return []
            key = self.pending[0]["key"]
            # Hold the window open until the batch fills (or closes).
            self.cond.wait_for(
                lambda: self._key_rows(key) >= self.max_rows
                or self.stop.is_set(),
                timeout=self.window_s,
            )
            take: list[dict] = []
            total = 0
            for p in [p for p in self.pending if p["key"] == key]:
                n = p["rows"].shape[0]
                if take and total + n > self.max_rows:
                    break
                take.append(p)
                total += n
            for p in take:
                self.pending.remove(p)
        return take

    def loop(self):
        # Keep draining after shutdown begins: requests already
        # queued must be answered (the direct path serves its
        # in-flight requests too), never left to hang in submit().
        try:
            self._loop()
        finally:
            # Whatever is left when the consumer stops (including a
            # crash) is answered with an error, never abandoned.
            with self.cond:
                self.closed = True
                leftovers, self.pending = self.pending, []
            for p in leftovers:
                p["err"] = RuntimeError("server shutting down")
                p["event"].set()

    def _loop(self):
        # lint: ok guarded-attr — racy liveness peek; _take_batch re-reads pending under cond
        while not self.stop.is_set() or self.pending:
            batch = self._take_batch()
            if not batch:
                continue
            try:
                num_steps = batch[0]["key"][1]
                rows = jnp.concatenate(
                    [p["rows"] for p in batch], axis=0)
                k = rows.shape[0]
                bucket = 1
                while bucket < k:
                    bucket *= 2
                if bucket > k:  # pad: bounded set of batch shapes
                    rows = jnp.concatenate(
                        [rows, jnp.zeros((bucket - k, rows.shape[1]),
                                         rows.dtype)], axis=0)
                out = self.decode_fn(rows, num_steps)
                self.batches += 1
                self.max_rows_seen = max(self.max_rows_seen, k)
                at = 0
                for p in batch:
                    n = p["rows"].shape[0]
                    p["out"] = out[at:at + n]
                    at += n
            except Exception as exc:  # noqa: BLE001 — a failed batch
                # must answer its clients AND leave the loop alive.
                for p in batch:
                    p["err"] = exc
            for p in batch:
                p["event"].set()
