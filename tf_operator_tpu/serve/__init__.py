"""Continuous-batching LM serving: slot-based KV cache, an occupancy-
invariant compiled decode step, and the serving loop that joins/retires
requests mid-stream.

- ``kvcache``: KV storage — the block-paged pool (refcounted
  ``BlockAllocator``, ``PrefixCache`` for copy-on-write shared-prefix
  reuse) and the dense slot tensor escape hatch, plus the free-slot
  allocator.
- ``engine``: ONE compiled decode step over all slots with per-slot
  position/length/rng — sampled requests batch too, and occupancy
  changes, block-table growth, and CoW copies never recompile.
  Admission is planned: "free slot AND enough free blocks".
  ``spec_k >= 1`` turns each iteration into a batch-wide SPECULATIVE
  round (one draft executable + one batched verify, per-slot accept
  counters — slots advance different amounts; composes with kv-int8
  in both layouts and with the tp mesh).
- ``scheduler``: the serving loop — token-budgeted chunked prefill
  interleaved with decode, admission into free slots, EOS/max-tokens
  retirement, and the SIGTERM drain (in-flight finishes, queued 503s).
- ``resilience``: the typed-error taxonomy (``ServeError`` and
  friends), request deadlines, load shedding/degraded-mode config, and
  the ``EngineSupervisor`` watchdog that rebuilds a crashed/stalled
  engine and replays in-flight requests bit-identically.
- ``faultinject``: deterministic, seeded fault points
  (``TPU_SERVE_FAULTS``) for the chaos tests and serve_bench's chaos
  mix.
- ``coalesce``: the legacy same-shape batch-window coalescer
  (serve_lm --engine coalesce), kept selectable for the exactness
  matrix and as the bench's comparison leg.
- ``httpapi``: the /debug/serve endpoint, the shared stdlib-handler
  base (``QuietHandler``, incl. the /debug/traces export of the
  data-plane span ring), and the /healthz readiness payload.
- ``disagg``: disaggregated prefill/decode — dedicated prefill
  replicas (``PrefillWorker``/``PrefillServer``), the shipped-KV wire
  format (``export_shipment``/``decode_shipment``), and the digest
  chain; the two-stage router lives in fleet/router.py. See
  docs/disaggregation.md.

Re-exports resolve lazily (PEP 562): importing the package must not
drag jax into processes that only mount the debug surface.

See docs/serving.md for the architecture, the slot lifecycle, and the
bench how-to; tools/serve_smoke.py runs the marked test subset.
"""

_EXPORTS = {
    "SlotAllocator": "kvcache",
    "BlockAllocator": "kvcache",
    "PrefixCache": "kvcache",
    "AdmissionPlan": "engine",
    "ChunkedPrefill": "engine",
    "ContinuousEngine": "engine",
    "ContinuousScheduler": "scheduler",
    "ServeRequest": "scheduler",
    "ShuttingDown": "scheduler",
    "EngineSupervisor": "resilience",
    "ResilienceConfig": "resilience",
    "ServeError": "resilience",
    "error_payload": "resilience",
    "FaultInjector": "faultinject",
    "InjectedFault": "faultinject",
    "Coalescer": "coalesce",
    "ServeDebugHandler": "httpapi",
    "mount_serve": "httpapi",
    "Shipment": "disagg",
    "PrefillWorker": "disagg",
    "PrefillServer": "disagg",
    "FakePrefillBackend": "disagg",
    "export_shipment": "disagg",
    "decode_shipment": "disagg",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(
        importlib.import_module(f"{__name__}.{module}"), name
    )
