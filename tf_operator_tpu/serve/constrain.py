"""Structured & constrained decoding: the host-side constraint
compiler and the paged constraint pool (ISSUE 19, ROADMAP item 6).

A per-request spec — ``{"json_schema": …}`` / ``{"regex": …}`` /
``{"choices": […]}`` / ``{"stop": […]}`` — compiles into a token-level
DFA over the model vocabulary:

- ``regex`` goes regex → Thompson NFA → subset-construction DFA over
  the VOCAB CHARSET (only characters that appear in some token string
  can ever be generated, so the alphabet is exactly that set), then a
  tokenizer-closure pass walks every vocab token's string through the
  char DFA to produce token-level ``allow``/``next`` tables.
- ``json_schema`` compiles a schema-driven grammar to a regex over
  CANONICAL JSON (no whitespace, fixed property order) and rides the
  same pipeline — everything stays regular, so the whole constraint is
  one finite automaton, never a pushdown interpreter on the hot path.
- ``choices`` build a character trie directly (states are prefixes of
  the allowed literals) and close over the tokenizer the same way.
- ``stop`` sequences are NOT a DFA concern: they compile to token-id
  sequences matched host-side at delivery with a bounded tail buffer
  (:func:`match_stop` / :func:`apply_stop`), trimmed exactly like the
  post-hoc solo semantics.

Two liveness prunes keep generation from ever dead-ending: char-level
states that cannot reach an accept state are dropped during subset
construction, and after the tokenizer closure a token-level prune
removes transitions into states from which no TOKEN path reaches an
accept state (a char path may exist that no whole token realizes).
After both, every reachable state either extends toward an accept
state or is ``complete`` — accepting with nothing left to emit — and
the scheduler retires the slot there. When the request carries an
``eos_id`` the compiler additionally allows eos at every accepting
state, so open-ended grammars (``[0-9]+``) terminate naturally.

The result is a :class:`CompiledProgram`: fixed-shape numpy tables
``allow [n_states, vocab] bool`` and ``next [n_states, vocab] int32``
plus ``accept``/``complete`` flags, keyed by a digest of (spec, eos,
vocab). :class:`ConstraintCompiler` caches programs LRU by that digest
and raises the typed :class:`~tf_operator_tpu.serve.resilience.InvalidGrammar`
(a 400) on malformed/unsupported/unsatisfiable specs — it runs OFF the
device lock (scheduler enqueue, HTTP threads), so compile latency never
stalls decode.

On the device side :class:`ProgramPool` materializes programs into a
paged constraint pool: ONE ``allow_pool [rows, vocab] bool`` and one
``next_pool [rows, vocab] int32`` (absolute row indices), row 0 the
always-allow garbage program (mask all-pass, next always 0) so
unconstrained lanes pay one gather and zero branches. Per-slot FSM
state is then just an int32 row index riding the compiled decode step
as DATA — the same constraints-as-data discipline as temperature/top_p
(PR 5) and the spec-accept counters (PR 15) — so constrained and
unconstrained slots mix freely with zero decode recompiles. Programs
occupy contiguous row ranges with refcounts; refcount-0 programs evict
LRU when the pool is full (``tpu_serve_constrain_evictions_total``),
and the resident count is the ``tpu_serve_constrain_programs`` gauge.

The additive mask is materialized IN-STEP as
``logits + where(allow_row, 0.0, -1e30)`` (the ``_nucleus_filter``
fill convention): storing the pool as bool instead of f32 costs one
``where`` per step and divides pool HBM by 4, and ``x + 0.0`` keeps
unconstrained lanes bitwise on their solo law (argmax and categorical
are invariant to the +0.0).

:func:`constrained_generate` is the solo oracle: ``generate``'s exact
prefill + lax.scan loop with the mask add and FSM advance inserted at
the same op positions as the engine's ``_sample_token``, so a
constrained slot pins bit-identical against it the same way free slots
pin against ``generate`` (tests/test_serve_constrain.py). The
speculative composition oracle lives in models/spec_decode.py
(``speculative_generate(..., program=)``): the draft walks the FSM to
mask its proposals, verify re-masks the target chunk rows with the
same state chain, and a mask violation is just a rejection — the PR 15
rewind machinery is unchanged.

At dp > 1 (pod scale, ISSUE 20) the pools stay REPLICATED over the dp
axis while per-slot FSM rows shard with the slot axis: every dp shard
gathers its own slots' ``allow``/``next`` rows from a full local copy
(the rows are vocab-wide and shared across slots — slicing them per
shard would tear the gather), so constrained decode at tp x dp is the
same data path with zero extra collectives; the tpdp cells in
tools/serve_tp_check.py ride the same pinned step.

See docs/constrained-decoding.md for the memory math, the spec-decode
composition table, and the stop/logprobs/n-best response semantics.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict
from collections.abc import Sequence
from typing import Any

import numpy as np

from tf_operator_tpu.runtime.metrics import (
    SERVE_CONSTRAIN_EVICTIONS,
    SERVE_CONSTRAIN_PROGRAMS,
)
from tf_operator_tpu.serve.resilience import InvalidGrammar

# The additive-mask fill, matching _nucleus_filter's: large enough that
# softmax/argmax can never resurrect a masked token, finite so f32
# arithmetic (logsumexp shifts, temperature division) stays NaN-free.
NEG_MASK = -1e30

# Compile-budget caps: a DFA past these is a client error (typed 400),
# not an OOM — the pool rows are the real resource.
MAX_DFA_STATES = 512
MAX_REPEAT = 64


# ---------------------------------------------------------------------------
# regex → NFA (Thompson construction over the vocab charset)
# ---------------------------------------------------------------------------

_ESCAPE_CLASSES = {
    "d": "0123456789",
    "w": ("ABCDEFGHIJKLMNOPQRSTUVWXYZ"
          "abcdefghijklmnopqrstuvwxyz0123456789_"),
    "s": " \t\n\r",
}


class _Nfa:
    """Mutable Thompson NFA: per-state char→{states} plus ε-edges."""

    def __init__(self) -> None:
        self.chars: list[dict[str, set[int]]] = []
        self.eps: list[set[int]] = []

    def state(self) -> int:
        self.chars.append({})
        self.eps.append(set())
        return len(self.chars) - 1

    def edge(self, a: int, ch: str, b: int) -> None:
        self.chars[a].setdefault(ch, set()).add(b)

    def eedge(self, a: int, b: int) -> None:
        self.eps[a].add(b)


class _RegexParser:
    """Recursive-descent parser for the supported regex subset:
    literals, ``.``, escapes (incl. ``\\d \\w \\s``), ``[...]`` classes
    with ranges and negation, grouping ``( )``, alternation ``|``, and
    the quantifiers ``* + ? {m} {m,} {m,n}`` (bounded expansion). The
    AST is tuples; compilation resolves classes against the vocab
    alphabet (chars outside it can never be generated, so they simply
    have no edges)."""

    def __init__(self, pattern: str) -> None:
        self.p = pattern
        self.i = 0

    def fail(self, why: str) -> "InvalidGrammar":
        return InvalidGrammar(
            f"regex error at offset {self.i}: {why} (pattern {self.p!r})"
        )

    def peek(self) -> str | None:
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self) -> str:
        if self.i >= len(self.p):
            raise self.fail("unexpected end of pattern")
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self):
        node = self.alt()
        if self.i != len(self.p):
            raise self.fail(f"unexpected {self.p[self.i]!r}")
        return node

    def alt(self):
        branches = [self.concat()]
        while self.peek() == "|":
            self.take()
            branches.append(self.concat())
        return ("alt", branches) if len(branches) > 1 else branches[0]

    def concat(self):
        parts = []
        while self.peek() not in (None, "|", ")"):
            parts.append(self.repeat())
        if not parts:
            return ("empty",)
        return ("cat", parts) if len(parts) > 1 else parts[0]

    def repeat(self):
        node = self.atom()
        while self.peek() in ("*", "+", "?", "{"):
            op = self.take()
            if op == "*":
                node = ("rep", node, 0, None)
            elif op == "+":
                node = ("rep", node, 1, None)
            elif op == "?":
                node = ("rep", node, 0, 1)
            else:
                node = ("rep", node, *self._bounds())
        return node

    def _bounds(self) -> tuple[int, int | None]:
        digits = ""
        while (c := self.peek()) is not None and c.isdigit():
            digits += self.take()
        if not digits:
            raise self.fail("expected digits in {m,n}")
        lo = int(digits)
        hi: int | None = lo
        if self.peek() == ",":
            self.take()
            digits = ""
            while (c := self.peek()) is not None and c.isdigit():
                digits += self.take()
            hi = int(digits) if digits else None
        if self.take() != "}":
            raise self.fail("unterminated {m,n}")
        if hi is not None and hi < lo:
            raise self.fail(f"bad repeat bounds {{{lo},{hi}}}")
        if lo > MAX_REPEAT or (hi or 0) > MAX_REPEAT:
            raise self.fail(f"repeat bound exceeds {MAX_REPEAT}")
        return lo, hi

    def atom(self):
        ch = self.take()
        if ch == "(":
            node = self.alt()
            if self.peek() != ")":
                raise self.fail("unterminated group")
            self.take()
            return node
        if ch == "[":
            return self._char_class()
        if ch == ".":
            return ("any",)
        if ch == "\\":
            return self._escape(in_class=False)
        if ch in "*+?{":
            raise self.fail(f"quantifier {ch!r} with nothing to repeat")
        return ("lit", ch)

    def _escape(self, *, in_class: bool):
        ch = self.take()
        if ch in _ESCAPE_CLASSES:
            return ("class", frozenset(_ESCAPE_CLASSES[ch]), False)
        if ch == "n":
            return ("lit", "\n")
        if ch == "t":
            return ("lit", "\t")
        if ch == "r":
            return ("lit", "\r")
        # Everything else escapes to its literal self (\. \\ \[ \" …).
        return ("lit", ch)

    def _char_class(self):
        negated = False
        if self.peek() == "^":
            self.take()
            negated = True
        chars: set[str] = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise self.fail("unterminated character class")
            if c == "]" and not first:
                self.take()
                break
            first = False
            c = self.take()
            if c == "\\":
                sub = self._escape(in_class=True)
                if sub[0] == "class":
                    chars |= set(sub[1])
                    continue
                c = sub[1]
            if self.peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self.take()
                hi = self.take()
                if hi == "\\":
                    hi = self._escape(in_class=True)[1]
                if ord(hi) < ord(c):
                    raise self.fail(f"bad class range {c}-{hi}")
                chars |= {chr(o) for o in range(ord(c), ord(hi) + 1)}
            else:
                chars.add(c)
        return ("class", frozenset(chars), negated)


def _nfa_compile(node, nfa: _Nfa, alphabet: Sequence[str]) -> tuple[int, int]:
    """Thompson-construct ``node`` into ``nfa``; returns (start, end).
    Classes/``.``/negations resolve against ``alphabet`` — the vocab
    charset — here, so the DFA never carries unreachable characters."""
    kind = node[0]
    if kind == "empty":
        s = nfa.state()
        return s, s
    if kind == "lit":
        a, b = nfa.state(), nfa.state()
        nfa.edge(a, node[1], b)
        return a, b
    if kind == "any":
        a, b = nfa.state(), nfa.state()
        for ch in alphabet:
            if ch != "\n":
                nfa.edge(a, ch, b)
        return a, b
    if kind == "class":
        _, chars, negated = node
        a, b = nfa.state(), nfa.state()
        for ch in alphabet:
            if (ch in chars) != negated:
                nfa.edge(a, ch, b)
        return a, b
    if kind == "alt":
        a, b = nfa.state(), nfa.state()
        for br in node[1]:
            s, e = _nfa_compile(br, nfa, alphabet)
            nfa.eedge(a, s)
            nfa.eedge(e, b)
        return a, b
    if kind == "cat":
        start = prev = None
        for part in node[1]:
            s, e = _nfa_compile(part, nfa, alphabet)
            if start is None:
                start = s
            else:
                nfa.eedge(prev, s)
            prev = e
        return start, prev
    if kind == "rep":
        _, inner, lo, hi = node
        start = prev = nfa.state()
        for _ in range(lo):
            s, e = _nfa_compile(inner, nfa, alphabet)
            nfa.eedge(prev, s)
            prev = e
        if hi is None:
            # Kleene tail: loop the inner once-or-more, skippable.
            s, e = _nfa_compile(inner, nfa, alphabet)
            nfa.eedge(prev, s)
            nfa.eedge(e, s)
            end = nfa.state()
            nfa.eedge(prev, end)
            nfa.eedge(e, end)
            return start, end
        end = nfa.state()
        nfa.eedge(prev, end)
        for _ in range(hi - lo):
            s, e = _nfa_compile(inner, nfa, alphabet)
            nfa.eedge(prev, s)
            prev = e
            nfa.eedge(prev, end)
        return start, end
    raise InvalidGrammar(f"unsupported regex node {kind!r}")


def _eps_closure(nfa: _Nfa, states: frozenset[int]) -> frozenset[int]:
    out = set(states)
    stack = list(states)
    while stack:
        for nxt in nfa.eps[stack.pop()]:
            if nxt not in out:
                out.add(nxt)
                stack.append(nxt)
    return frozenset(out)


def _char_dfa(pattern: str, alphabet: Sequence[str],
              max_states: int) -> tuple[list[dict[str, int]], list[bool]]:
    """regex → char-level DFA over ``alphabet`` (subset construction),
    with dead (accept-unreachable) states pruned. Returns
    (transitions, accept); state 0 is the start."""
    ast = _RegexParser(pattern).parse()
    nfa = _Nfa()
    start, end = _nfa_compile(ast, nfa, alphabet)
    start_set = _eps_closure(nfa, frozenset((start,)))
    index = {start_set: 0}
    order = [start_set]
    trans: list[dict[str, int]] = [{}]
    todo = [start_set]
    while todo:
        cur = todo.pop()
        ci = index[cur]
        for ch in alphabet:
            nxt = set()
            for st in cur:
                nxt |= nfa.chars[st].get(ch, set())
            if not nxt:
                continue
            closed = _eps_closure(nfa, frozenset(nxt))
            if closed not in index:
                if len(index) >= max_states:
                    raise InvalidGrammar(
                        f"constraint DFA exceeds {max_states} states — "
                        "simplify the pattern or bound its repeats"
                    )
                index[closed] = len(order)
                order.append(closed)
                trans.append({})
                todo.append(closed)
            trans[ci][ch] = index[closed]
    accept = [end in st for st in order]
    return _prune_char_dead(trans, accept)


def _prune_char_dead(
    trans: list[dict[str, int]], accept: list[bool],
) -> tuple[list[dict[str, int]], list[bool]]:
    """Drop states that cannot reach an accept state (reverse BFS), so
    the token closure never offers a char path that strands generation."""
    n = len(trans)
    rev: list[set[int]] = [set() for _ in range(n)]
    for s, edges in enumerate(trans):
        for d in edges.values():
            rev[d].add(s)
    live = {s for s in range(n) if accept[s]}
    stack = list(live)
    while stack:
        for p in rev[stack.pop()]:
            if p not in live:
                live.add(p)
                stack.append(p)
    if 0 not in live:
        raise InvalidGrammar(
            "constraint matches nothing expressible with this vocabulary"
        )
    remap = {old: new for new, old in enumerate(sorted(live))}
    out_trans = [
        {ch: remap[d] for ch, d in trans[old].items() if d in live}
        for old in sorted(live)
    ]
    out_accept = [accept[old] for old in sorted(live)]
    return out_trans, out_accept


def _choices_dfa(
    choices: Sequence[str],
) -> tuple[list[dict[str, int]], list[bool]]:
    """Character trie of the literal choices — states are prefixes.
    Equivalent to the DFA of an escaped alternation, built directly."""
    if not choices:
        raise InvalidGrammar("choices must be a non-empty list of strings")
    trans: list[dict[str, int]] = [{}]
    accept = [False]
    for c in choices:
        if not isinstance(c, str) or not c:
            raise InvalidGrammar(
                f"choices entries must be non-empty strings, got {c!r}"
            )
        cur = 0
        for ch in c:
            nxt = trans[cur].get(ch)
            if nxt is None:
                trans.append({})
                accept.append(False)
                nxt = len(trans) - 1
                trans[cur][ch] = nxt
            cur = nxt
        accept[cur] = True
    return trans, accept


# ---------------------------------------------------------------------------
# JSON schema → regex (canonical JSON, everything regular)
# ---------------------------------------------------------------------------

_REGEX_META = set("\\^$.|?*+()[]{}")


def regex_escape(text: str) -> str:
    return "".join(("\\" + c) if c in _REGEX_META else c for c in text)


# Canonical string body charset: the vocab minus the quote, backslash,
# and ALL control characters below 0x20 (RFC 8259 says those MUST be
# escaped inside a JSON string — excluding them outright means no
# escape sequences, which keeps the automaton small and every emitted
# string loads with strict json.loads unchanged). The controls are
# spelled as literal characters: the grammar parser has no \xNN escape.
_JSON_STRING_CLASS = '[^"\\\\' + "".join(map(chr, range(0x20))) + "]"
_JSON_INT = r"-?(0|[1-9][0-9]*)"
_JSON_NUMBER = _JSON_INT + r"(\.[0-9]+)?"


def schema_to_regex(schema: Any, *, depth: int = 0) -> str:
    """Compile the supported json_schema subset to a regex over
    CANONICAL JSON (``json.dumps(..., separators=(',', ':'))`` — no
    whitespace, properties in declared order). Supported: ``object``
    (properties emitted in declared order; ``required`` defaults to all),
    ``string`` (``minLength``/``maxLength``/``pattern``), ``integer``,
    ``number``, ``boolean``, ``null``, ``enum``/``const``, ``array``
    (``items`` + ``minItems``/``maxItems``). Anything else is a typed
    ``invalid_grammar``."""
    if depth > 8:
        raise InvalidGrammar("json_schema nests deeper than 8 levels")
    if not isinstance(schema, dict):
        raise InvalidGrammar(f"json_schema must be an object, got {schema!r}")
    if "const" in schema:
        return regex_escape(
            json.dumps(schema["const"], separators=(",", ":"))
        )
    if "enum" in schema:
        vals = schema["enum"]
        if not isinstance(vals, list) or not vals:
            raise InvalidGrammar("enum must be a non-empty list")
        return "(" + "|".join(
            regex_escape(json.dumps(v, separators=(",", ":")))
            for v in vals
        ) + ")"
    t = schema.get("type")
    if t == "object":
        props = schema.get("properties") or {}
        if not isinstance(props, dict) or not props:
            raise InvalidGrammar(
                "object schemas need non-empty 'properties'"
            )
        required = schema.get("required")
        keep = (props if required is None
                else {k: v for k, v in props.items() if k in required})
        if not keep:
            raise InvalidGrammar("object schema with no required property")
        body = ",".join(
            regex_escape(json.dumps(k) + ":") + schema_to_regex(
                v, depth=depth + 1
            )
            for k, v in keep.items()
        )
        return r"\{" + body + r"\}"
    if t == "string":
        lo = int(schema.get("minLength", 0))
        hi = schema.get("maxLength")
        if schema.get("pattern") is not None:
            return '"' + str(schema["pattern"]) + '"'
        if hi is None:
            body = _JSON_STRING_CLASS + (f"{{{lo},}}" if lo else "*")
        else:
            body = _JSON_STRING_CLASS + f"{{{lo},{int(hi)}}}"
        return '"' + body + '"'
    if t == "integer":
        return _JSON_INT
    if t == "number":
        return _JSON_NUMBER
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "array":
        item = schema_to_regex(schema.get("items") or {"type": "integer"},
                               depth=depth + 1)
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems")
        item = "(" + item + ")"
        if lo == 0:
            inner = (f"({item}(,{item})*)?" if hi is None
                     else f"({item}(,{item}){{0,{max(0, int(hi) - 1)}}})?")
        else:
            tail = (f"(,{item})*" if hi is None
                    else f"(,{item}){{{lo - 1},{max(0, int(hi) - 1)}}}")
            inner = item + tail
        return r"\[" + inner + r"\]"
    raise InvalidGrammar(f"unsupported json_schema type {t!r}")


# ---------------------------------------------------------------------------
# tokenizer closure → CompiledProgram
# ---------------------------------------------------------------------------

class CompiledProgram:
    """One constraint compiled to token-level tables (host numpy; the
    :class:`ProgramPool` materializes them on device):

    - ``allow [n_states, vocab] bool`` — token legal from this state
    - ``next  [n_states, vocab] int32`` — LOCAL successor state (0 where
      disallowed — never followed, the mask forbids it first)
    - ``accept [n_states] bool`` — the emitted-so-far text matches
    - ``complete [n_states] bool`` — accepting with no way to extend:
      the scheduler retires the slot here (finish_reason
      ``grammar_complete``)

    State 0 is the start. ``digest`` keys the LRU caches (spec + eos +
    vocab fingerprint)."""

    def __init__(self, *, allow: np.ndarray, nxt: np.ndarray,
                 accept: np.ndarray, complete: np.ndarray, digest: str,
                 kind: str, spec: Any) -> None:
        self.allow = allow
        self.next = nxt
        self.accept = accept
        self.complete = complete
        self.digest = digest
        self.kind = kind
        self.spec = spec
        self.n_states = int(allow.shape[0])

    def walk(self, state: int, token: int) -> int:
        """Host-side FSM advance for ONE delivered token (the scheduler
        re-derives per-request state from emitted tokens — replay after
        a crash reconstructs it for free)."""
        return int(self.next[state, token])

    def describe(self) -> dict:
        return {"kind": self.kind, "digest": self.digest[:12],
                "n_states": self.n_states}


def _token_closure(
    trans: list[dict[str, int]], accept: list[bool],
    vocab: Sequence[str], eos_id: int | None,
) -> CompiledProgram:
    """Walk every vocab token's string through the char DFA from every
    state → token-level ``allow``/``next``; then prune token-level-dead
    transitions (a char path no whole token realizes) so generation can
    always either extend or finish."""
    n, v = len(trans), len(vocab)
    allow = np.zeros((n, v), np.bool_)
    nxt = np.zeros((n, v), np.int32)
    for tid, text in enumerate(vocab):
        if not text:
            continue  # empty tokens would advance nothing, forever
        for s in range(n):
            cur = s
            for ch in text:
                cur = trans[cur].get(ch, -1)
                if cur < 0:
                    break
            if cur >= 0:
                allow[s, tid] = True
                nxt[s, tid] = cur
    acc = np.asarray(accept, np.bool_)
    # Token-level liveness: a state must reach an accept state via
    # TOKEN edges (or be accepting itself); edges into token-dead
    # states are removed. One pass suffices: surviving states keep the
    # very edge that made them live.
    live = set(np.flatnonzero(acc).tolist())
    changed = True
    while changed:
        changed = False
        for s in range(n):
            if s in live:
                continue
            dests = nxt[s][allow[s]]
            if any(int(d) in live for d in dests):
                live.add(s)
                changed = True
    if 0 not in live:
        raise InvalidGrammar(
            "constraint cannot be completed with this vocabulary"
        )
    for s in range(n):
        for tid in np.flatnonzero(allow[s]):
            if int(nxt[s, tid]) not in live:
                allow[s, tid] = False
                nxt[s, tid] = 0
    if eos_id is not None and 0 <= eos_id < v:
        # eos is legal exactly at accepting states (and self-loops —
        # the scheduler retires on it before another step runs).
        allow[:, eos_id] = acc
        nxt[:, eos_id] = np.where(acc, np.arange(n), 0)
    # complete = accepting with no non-eos continuation: retire here.
    cont = allow.copy()
    if eos_id is not None and 0 <= eos_id < v:
        cont[:, eos_id] = False
    complete = acc & ~cont.any(axis=1)
    return CompiledProgram(
        allow=allow, nxt=nxt, accept=acc, complete=complete,
        digest="", kind="", spec=None,
    )


# ---------------------------------------------------------------------------
# the compiler (LRU, off the device lock)
# ---------------------------------------------------------------------------

_SPEC_KINDS = ("json_schema", "regex", "choices")


def default_vocab(vocab_size: int) -> list[str]:
    """Token id → string for toy/byte models: identity ``chr(i)`` — the
    mapping serve_lm and the tests use when no tokenizer exists. Real
    deployments pass their tokenizer's id→piece table instead."""
    return [chr(i) for i in range(vocab_size)]


def detokenize(vocab: Sequence[str], ids: Sequence[int]) -> str:
    return "".join(vocab[int(i)] for i in ids)


class ConstraintCompiler:
    """spec dict → :class:`CompiledProgram`, LRU-cached by digest.

    Thread-safe and device-free: the scheduler calls :meth:`compile`
    at ENQUEUE time on HTTP threads, off the device lock, so a cold
    compile costs queue latency only. All failures raise the typed
    :class:`InvalidGrammar` (400, not retryable)."""

    def __init__(self, vocab: Sequence[str], *,
                 max_states: int = MAX_DFA_STATES,
                 cache_programs: int = 64) -> None:
        self.vocab = [str(t) for t in vocab]
        self.max_states = int(max_states)
        self.cache_programs = max(1, int(cache_programs))
        self.alphabet = sorted({ch for t in self.vocab for ch in t})
        self._fingerprint = hashlib.sha1(
            "\x00".join(self.vocab).encode()
        ).hexdigest()[:16]
        # Single-char reverse map for stop-string encoding (first id
        # wins, matching detokenize round-trips for identity vocabs).
        self._char_token: dict[str, int] = {}
        for tid, t in enumerate(self.vocab):
            if len(t) == 1 and t not in self._char_token:
                self._char_token[t] = tid
        self._lock = threading.Lock()
        self._cache: OrderedDict[str, CompiledProgram] = OrderedDict()
        self.compiles = 0
        self.cache_hits = 0

    def digest_of(self, spec: Any, eos_id: int | None) -> str:
        blob = json.dumps({"spec": spec, "eos": eos_id,
                           "vocab": self._fingerprint},
                          sort_keys=True, default=str)
        return hashlib.sha1(blob.encode()).hexdigest()

    def compile(self, spec: dict, *,
                eos_id: int | None = None) -> CompiledProgram:
        if not isinstance(spec, dict):
            raise InvalidGrammar(
                f"constraint spec must be an object, got {type(spec).__name__}"
            )
        kinds = [k for k in _SPEC_KINDS if spec.get(k) is not None]
        if len(kinds) != 1:
            raise InvalidGrammar(
                "constraint spec needs exactly one of "
                f"{'/'.join(_SPEC_KINDS)}, got {kinds or 'none'}"
            )
        kind = kinds[0]
        digest = self.digest_of({kind: spec[kind]}, eos_id)
        with self._lock:
            prog = self._cache.get(digest)
            if prog is not None:
                self._cache.move_to_end(digest)
                self.cache_hits += 1
                return prog
        prog = self._compile_cold(kind, spec[kind], eos_id, digest)
        with self._lock:
            self.compiles += 1
            self._cache[digest] = prog
            self._cache.move_to_end(digest)
            while len(self._cache) > self.cache_programs:
                self._cache.popitem(last=False)
                SERVE_CONSTRAIN_EVICTIONS.inc(tier="cache")
        return prog

    def _compile_cold(self, kind: str, body: Any, eos_id: int | None,
                      digest: str) -> CompiledProgram:
        if kind == "choices":
            trans, accept = _choices_dfa(body)
            if len(trans) > self.max_states:
                raise InvalidGrammar(
                    f"choices trie exceeds {self.max_states} states"
                )
        else:
            pattern = (body if kind == "regex"
                       else schema_to_regex(body))
            if not isinstance(pattern, str) or not pattern:
                raise InvalidGrammar("regex must be a non-empty string")
            trans, accept = _char_dfa(pattern, self.alphabet,
                                      self.max_states)
        prog = _token_closure(trans, accept, self.vocab, eos_id)
        prog.digest = digest
        prog.kind = kind
        prog.spec = {kind: body}
        return prog

    def encode_stop(self, stop: Any) -> tuple[tuple[int, ...], ...]:
        """Stop entries → token-id sequences: int lists pass through;
        strings encode char-by-char via the single-char reverse map (the
        identity-vocab case — real tokenizers pass id lists)."""
        if stop is None:
            return ()
        if not isinstance(stop, (list, tuple)) or not stop:
            raise InvalidGrammar("stop must be a non-empty list")
        out = []
        for entry in stop:
            if isinstance(entry, str):
                if not entry:
                    raise InvalidGrammar("empty stop string")
                try:
                    out.append(tuple(self._char_token[c] for c in entry))
                except KeyError as exc:
                    raise InvalidGrammar(
                        f"stop string {entry!r} has no token for "
                        f"character {exc.args[0]!r}"
                    ) from None
            elif isinstance(entry, (list, tuple)) and entry and all(
                    isinstance(t, int) and not isinstance(t, bool)
                    for t in entry):
                out.append(tuple(int(t) for t in entry))
            else:
                raise InvalidGrammar(
                    f"stop entries must be strings or token-id lists, "
                    f"got {entry!r}"
                )
        return tuple(out)

    def debug(self) -> dict:
        with self._lock:
            return {
                "cached_programs": len(self._cache),
                "cache_limit": self.cache_programs,
                "compiles": self.compiles,
                "cache_hits": self.cache_hits,
                "alphabet": len(self.alphabet),
            }


# ---------------------------------------------------------------------------
# stop sequences (host-side, bounded tail buffer)
# ---------------------------------------------------------------------------

def max_stop_len(stops: Sequence[Sequence[int]]) -> int:
    return max((len(s) for s in stops), default=0)


def match_stop(out: Sequence[int],
               stops: Sequence[Sequence[int]]) -> int:
    """Incremental check after each delivered token: does any stop
    sequence end EXACTLY at the current tail? Returns the matched
    length (trim that many) or 0. Only the last ``max_stop_len``
    tokens are examined — the bounded tail buffer."""
    for s in stops:
        k = len(s)
        if k and len(out) >= k and tuple(out[-k:]) == tuple(s):
            return k
    return 0


def apply_stop(tokens: Sequence[int],
               stops: Sequence[Sequence[int]]) -> list[int]:
    """Post-hoc solo semantics: cut at the FIRST position where any
    stop sequence completes, excluding the stop tokens themselves. The
    incremental :func:`match_stop` loop produces exactly this — pinned
    by tests so the two can never drift."""
    toks = list(tokens)
    for j in range(len(toks)):
        for s in stops:
            k = len(s)
            if k and j + 1 >= k and tuple(toks[j + 1 - k:j + 1]) == tuple(s):
                return toks[:j + 1 - k]
    return toks


# ---------------------------------------------------------------------------
# the paged constraint pool (device tables, programs as row ranges)
# ---------------------------------------------------------------------------

class ProgramPool:
    """Fixed-shape device tables every compiled step reads as DATA:

    - ``allow_pool [rows, vocab] bool`` — True = token legal
    - ``next_pool  [rows, vocab] int32`` — ABSOLUTE successor row

    Row 0 is the always-allow garbage program (all-True mask, next
    always 0): unconstrained lanes gather row 0, add +0.0, and stay
    bitwise on their solo law. A program binds into a contiguous row
    range (its local states offset by the base row) with a refcount;
    refcount-0 programs stay resident for reuse and evict LRU when a
    bind needs their rows. All updates are EAGER host-side scatters —
    the decode step's jit cache never sees them, so the zero-recompile
    contract holds across arbitrary program churn.

    Single-threaded by design: bind/release run on the scheduler's
    serving loop (join/retire), exactly like the block allocator."""

    def __init__(self, rows: int, vocab_size: int, *, put=None) -> None:
        import jax.numpy as jnp

        if rows < 2:
            raise ValueError(f"constrain_rows={rows} must be >= 2")
        self.rows = int(rows)
        self.vocab_size = int(vocab_size)
        self._put = put if put is not None else (lambda x: x)
        self.allow_pool = self._put(
            jnp.ones((self.rows, self.vocab_size), jnp.bool_)
        )
        self.next_pool = self._put(
            jnp.zeros((self.rows, self.vocab_size), jnp.int32)
        )
        # digest -> [base, n_states, refs, last_used_tick]
        self._resident: dict[str, list[int]] = {}
        self._free: list[tuple[int, int]] = [(1, self.rows - 1)]
        self._tick = 0
        self.evictions = 0
        self.binds = 0

    # -- allocation -----------------------------------------------------

    def _alloc_range(self, n: int) -> int | None:
        for i, (start, length) in enumerate(self._free):
            if length >= n:
                if length == n:
                    self._free.pop(i)
                else:
                    self._free[i] = (start + n, length - n)
                return start
        return None

    def _free_range(self, start: int, n: int) -> None:
        self._free.append((start, n))
        self._free.sort()
        merged: list[tuple[int, int]] = []
        for s, ln in self._free:
            if merged and merged[-1][0] + merged[-1][1] == s:
                merged[-1] = (merged[-1][0], merged[-1][1] + ln)
            else:
                merged.append((s, ln))
        self._free = merged

    def _evict_one(self) -> bool:
        victims = [(ent[3], dig) for dig, ent in self._resident.items()
                   if ent[2] == 0]
        if not victims:
            return False
        _, dig = min(victims)
        base, n, _, _ = self._resident.pop(dig)
        self._free_range(base, n)
        self.evictions += 1
        SERVE_CONSTRAIN_EVICTIONS.inc(tier="pool")
        SERVE_CONSTRAIN_PROGRAMS.set(len(self._resident))
        return True

    # -- the public surface --------------------------------------------

    def bind(self, prog: CompiledProgram) -> int | None:
        """Make ``prog`` resident and take a reference; returns its base
        row (slot fsm row = base + local state), or None when every
        resident program is still referenced and nothing can evict —
        the caller requeues, exactly like KV-block exhaustion."""
        import jax.numpy as jnp

        self._tick += 1
        ent = self._resident.get(prog.digest)
        if ent is not None:
            ent[2] += 1
            ent[3] = self._tick
            self.binds += 1
            return ent[0]
        n = prog.n_states
        if n > self.rows - 1:
            raise InvalidGrammar(
                f"program needs {n} rows; the constraint pool has "
                f"{self.rows - 1} (raise constrain_rows)"
            )
        base = self._alloc_range(n)
        while base is None:
            if not self._evict_one():
                return None
            base = self._alloc_range(n)
        # Absolute successor rows; disallowed entries point at the
        # garbage row (never followed — the mask forbids the token).
        nxt_abs = np.where(prog.allow, prog.next.astype(np.int64) + base,
                           0).astype(np.int32)
        self.allow_pool = self._put(
            self.allow_pool.at[base:base + n].set(jnp.asarray(prog.allow))
        )
        self.next_pool = self._put(
            self.next_pool.at[base:base + n].set(jnp.asarray(nxt_abs))
        )
        self._resident[prog.digest] = [base, n, 1, self._tick]
        self.binds += 1
        SERVE_CONSTRAIN_PROGRAMS.set(len(self._resident))
        return base

    def release(self, digest: str) -> None:
        ent = self._resident.get(digest)
        if ent is not None and ent[2] > 0:
            ent[2] -= 1

    def debug(self) -> dict:
        used = sum(ent[1] for ent in self._resident.values())
        return {
            "rows": self.rows,
            "rows_used": used + 1,  # + the garbage row
            "programs": len(self._resident),
            "live_refs": sum(ent[2] for ent in self._resident.values()),
            "evictions": self.evictions,
            "binds": self.binds,
        }


# ---------------------------------------------------------------------------
# the solo oracle
# ---------------------------------------------------------------------------

def constrained_generate(
    cfg: Any,
    params: Any,
    prompt: Any,
    num_steps: int,
    *,
    program: CompiledProgram,
    temperature: float = 0.0,
    top_p: float | None = None,
    rng: Any = None,
) -> Any:
    """``generate`` with the constraint walked inline: the bit-identity
    oracle every constrained engine slot pins against. Per step the
    logits take the additive mask of the CURRENT state's allow row
    before temperature/top_p/argmax — the exact op order of the
    engine's ``_sample_token`` — and the state advances through the
    sampled token. [1, L] prompts (the per-slot shape); returns
    [1, num_steps]."""
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer,
        _nucleus_filter,
        _prefill,
    )

    if prompt.shape[0] != 1:
        raise ValueError("constrained_generate serves [1, L] prompts")
    if prompt.shape[1] + num_steps > cfg.max_seq_len:
        raise ValueError("prompt + steps exceeds max_seq_len")
    if temperature > 0 and rng is None:
        raise ValueError("temperature > 0 needs an rng key")
    if top_p is not None and temperature <= 0:
        raise ValueError("top_p requires temperature > 0")
    from dataclasses import replace

    dcfg = replace(cfg, decode=True, mesh=None, remat=False)
    model = Transformer(dcfg)
    # Mirror the engine pool's convention exactly: a disallowed
    # transition (only reachable once the grammar has COMPLETED and the
    # masked argmax picks garbage) lands on an always-allow free state —
    # the pool's row 0 — so engine and oracle agree bitwise for the
    # whole stream, not just up to completion. The scheduler retires at
    # completion either way; this keeps the pin unconditional.
    n_states, vocab = program.allow.shape
    free = n_states
    allow_t = jnp.asarray(np.concatenate(
        [program.allow, np.ones((1, vocab), np.bool_)], axis=0
    ))
    next_local = np.where(
        program.allow, program.next.astype(np.int32), free
    ).astype(np.int32)
    next_t = jnp.asarray(np.concatenate(
        [next_local, np.full((1, vocab), free, np.int32)], axis=0
    ))
    rng = jax.random.PRNGKey(0) if rng is None else rng
    temperature = float(temperature)
    top_p_f = None if top_p is None else float(top_p)

    def run(params, prompt, rng):
        cache, last_logits = _prefill(model, params, prompt)

        def sample(carry, step_rng):
            cache, logits, state = carry
            masked = logits + jnp.where(
                allow_t[state], 0.0, NEG_MASK
            )[None, :]
            if temperature > 0:
                scaled = masked / temperature
                if top_p_f is not None:
                    scaled = _nucleus_filter(scaled, top_p_f)
                tok = jax.random.categorical(step_rng, scaled)
            else:
                tok = masked.argmax(-1)
            state = next_t[state, tok[0]]
            logits2, updates = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                mutable=["cache"],
            )
            return (updates["cache"], logits2[:, 0], state), tok

        (_, _, _), toks = jax.lax.scan(
            sample, (cache, last_logits, jnp.int32(0)),
            jax.random.split(rng, num_steps),
        )
        return toks.swapaxes(0, 1)

    return jax.jit(run)(params, prompt, rng)


def walk_tokens(program: CompiledProgram, tokens: Sequence[int],
                state: int = 0) -> tuple[int, int | None]:
    """Walk delivered tokens through the program from ``state``;
    returns (final state, index AFTER which the grammar completed —
    None if it never did). The scheduler's trim rule and the tests'
    expected-output rule share this one walker."""
    done_at = None
    for i, tok in enumerate(tokens):
        state = program.walk(state, int(tok))
        if done_at is None and bool(program.complete[state]):
            done_at = i
    return state, done_at
